"""Benchmark: rows/sec/chip from a hash-partitioned lakehouse table into a
jitted JAX training loop (the north-star metric, BASELINE.json), plus ANN
serving QPS and a remote-store (latency-injected) leg.

Legs and honesty rules (VERDICT r1 #2):

1. **MOR delivery (headline)** — our table (native LSF format, hash-bucketed,
   one upsert wave so merge-on-read does real work) → scan → merge →
   device_put → jitted MLP train step on the chip.
2. **Arms-length baselines** — the same rows written as a plain parquet
   dataset by pyarrow itself (zstd level 1, no dictionary — the reference
   writer's settings, writer/mod.rs:215-240), consumed by a pure
   pyarrow.dataset → torch DataLoader pipeline with ZERO repo imports.
   Two measurements: `baseline_e2e` delivers into the SAME jitted train
   step on the same chip (BASELINE.md's comparator — "GPU-DataLoader
   rows/sec/chip" is a delivery-to-accelerator metric) and sets
   vs_baseline; the host-decode-only loop (no device, strictly less work)
   is kept as vs_baseline_host_decode_only for r1/r2 continuity.
3. **HBM-resident replay** — the loader's cache="device" epoch cache:
   steady-state epochs replay from device memory with zero storage/host/
   link traffic.  Separately labeled; it measures the epoch-cache feature,
   not delivery from storage.
4. **ANN QPS** — device-resident IVF-RaBitQ batch search over a 200k x 64d
   shard; reports QPS and recall@10 vs brute force (full probe + exact
   re-rank at depth 100: the resident kernel scans every packed code
   regardless of nprobe, so full probing is free on this path).
5. **Remote leg** — a smaller table on a latency-injected in-memory object
   store (10 ms per GET — GCS-like) read cold then warm through the owned
   page cache.
6. **Scale legs** (VERDICT r3 item 4) — a ≥100M-row table (env-tunable):
   (a) bounded-memory STREAMING read with a 256 MB budget pinned in table
   properties; the leg records rows/s AND its own subprocess peak RSS and
   FAILS if RSS crosses the 2 GB ceiling — throughput must not come from
   materializing the table; (b) multi-process sharded loaders: N worker
   processes concurrently scan shard(rank, world) slices over the shared
   store (the multi-host input-pipeline shape), aggregate rows/s.

7. **Hard ANN leg** (VERDICT r4 weak #3) — an overlapping mixture with MORE
   clusters than nlist, so recall@10 at the realistic nprobe=8 operating
   point sits well below 1.0 and MOVES if the index regresses (the easy leg
   stays for continuity; ref anchors on GloVe, test_e2e_glove.py:182).
8. **HTTP object-store leg** (VERDICT r4 weak #5) — the stream-scale table
   served over a real local HTTP server (ranged GETs on real sockets, the
   GCS-emulator shape): bounded-memory cold scan + page-cache warm scan,
   reporting rows/s, hit rate and subprocess peak RSS.

Un-killable by construction (VERDICT r4 weak #1 — round 4's bench timed out
under the driver and printed NOTHING):

- every completed leg immediately prints a CUMULATIVE result line to stdout
  and rewrites ``BENCH_partial.json``, so a timeout still leaves the latest
  partial record as the parseable tail;
- a global wall-clock budget (env ``LAKESOUL_BENCH_BUDGET_S``, default
  2700 s — well inside the driver's window) gates every leg: once spent,
  remaining legs are recorded under ``"skipped"`` instead of running;
- a leg that fails or exceeds the remaining budget is recorded under
  ``"leg_errors"`` and the bench MOVES ON — one bad leg never zeroes the
  round's evidence;
- the TPU probe is ONE cheap attempt by default (retries only with budget
  to spare) and runs concurrently with the host-only legs, so a dead
  tunnel costs nothing: the device legs just run on the labeled CPU
  fallback with the probe record in ``device_probe``.

The LAST stdout line is always the cumulative JSON record; ``"complete":
true`` marks a full run (every leg ran or was explicitly skipped).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np
import pyarrow as pa

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_ROWS = int(os.environ.get("LAKESOUL_BENCH_ROWS", 20_000_000))
# the scale leg (VERDICT r3 item 4): ≥100M rows through the bounded-memory
# streaming path + multi-process sharded loaders over shared storage
STREAM_ROWS = int(os.environ.get("LAKESOUL_BENCH_STREAM_ROWS", 100_000_000))
STREAM_BUDGET_MB = int(os.environ.get("LAKESOUL_BENCH_STREAM_BUDGET_MB", 256))
# hard ceiling the streaming leg must stay under (budget + runtime floor);
# exceeding it FAILS the leg loudly instead of reporting a pretty number
STREAM_RSS_CEILING_MB = int(os.environ.get("LAKESOUL_BENCH_STREAM_CEILING_MB", 2048))
SHARD_WORKERS = int(os.environ.get("LAKESOUL_BENCH_SHARD_WORKERS", 4))
UPSERT_FRAC = 0.05
N_FEATURES = 16
BUCKETS = 8
# 512k rows x 16 f32 ≈ 32 MB per transfer: per-dispatch latency (not
# bandwidth) dominates the host→chip link, so fewer, larger batches win.
# Clamped so small smoke runs still produce full (jit-friendly) batches.
BATCH = min(
    int(os.environ.get("LAKESOUL_BENCH_BATCH", 524288)),
    max(1024, N_ROWS // 8),
)
# optimizer steps fused into one device dispatch (lax.scan group); per-call
# link latency amortizes over the group
STEPS_PER_CALL = int(os.environ.get("LAKESOUL_BENCH_STEPS_PER_CALL", 8))
REMOTE_ROWS = min(N_ROWS, 2_000_000)
ANN_N, ANN_D, ANN_Q = 200_000, 64, 4096
# global wall-clock budget: once spent, remaining legs are SKIPPED (with a
# record) instead of letting the driver's timeout erase all evidence
BUDGET_S = float(os.environ.get("LAKESOUL_BENCH_BUDGET_S", 2700))
HTTP_PORT = int(os.environ.get("LAKESOUL_BENCH_HTTP_PORT", 18742))
_START = time.monotonic()


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _START)


class Emitter:
    """Cumulative result record, re-emitted after every completed leg.

    stdout gets one full JSON line per update (the driver's tail is always
    the freshest partial record) and ``BENCH_partial.json`` is rewritten
    alongside, so a timeout at ANY point leaves parseable evidence of
    everything measured so far."""

    def __init__(self):
        self.record: dict = {
            "complete": False,
            "legs_done": [],
            "skipped": [],
            "leg_errors": {},
            "budget_s": BUDGET_S,
        }

    def update(self, leg: str, fields: dict) -> None:
        self.record.update(fields)
        self.record["legs_done"].append(leg)
        self._emit()

    def skip(self, leg: str, reason: str) -> None:
        self.record["skipped"].append({"leg": leg, "reason": reason})
        self._emit()

    def error(self, leg: str, err: str) -> None:
        self.record["leg_errors"][leg] = err[-500:]
        self._emit()

    def _emit(self) -> None:
        self.record["elapsed_s"] = round(time.monotonic() - _START, 1)
        line = json.dumps(self.record)
        print(line, flush=True)
        try:
            with open(os.path.join(REPO, "BENCH_partial.json"), "w") as f:
                f.write(line + "\n")
        except OSError:
            pass

    def leg(self, name: str, fn, publish=None, *, cost_s: float = 60.0):
        """Run one leg inside the budget; failures and overruns are recorded,
        never fatal.  ``cost_s`` is the minimum remaining budget the leg
        needs to be worth starting; ``publish(out)`` maps the leg's result
        to record fields, merged and re-emitted on success."""
        if _remaining() < cost_s:
            self.skip(name, f"budget: {_remaining():.0f}s left < {cost_s:.0f}s estimate")
            return None
        try:
            out = fn()
        except Exception as e:  # noqa: BLE001 — one leg must not kill the round
            self.error(name, f"{type(e).__name__}: {e}")
            return None
        self.update(name, publish(out) if publish is not None else {})
        return out


def _bench_schema():
    fields = [("id", pa.int64())] + [(f"f{i}", pa.float32()) for i in range(N_FEATURES)]
    fields.append(("label", pa.int32()))
    return pa.schema(fields)


def _chunks(n_rows, start_at=0, chunk=500_000, seed=0):
    rng = np.random.default_rng(seed)
    for start in range(0, n_rows, chunk):
        n = min(chunk, n_rows - start)
        cols = {"id": np.arange(start_at + start, start_at + start + n, dtype=np.int64)}
        for i in range(N_FEATURES):
            cols[f"f{i}"] = rng.normal(size=n).astype(np.float32)
        cols["label"] = rng.integers(0, 2, n).astype(np.int32)
        yield pa.table(cols, schema=_bench_schema())


def _upsert_wave(t, seed: int, n_rows: int | None = None,
                 chunk: int = 2_000_000) -> None:
    """One MOR-provoking upsert wave: re-write UPSERT_FRAC of the keys,
    chunked so the wave never materializes whole in the driver.  Keys are
    sampled without replacement from DISJOINT id sub-ranges per chunk —
    `rng.choice(N, replace=False)` would permute the full N-row population
    (O(N) transient memory: ~8 GB at 1B rows) for a tiny sample."""
    n_rows = n_rows or N_ROWS
    rng = np.random.default_rng(seed)
    n_up = int(n_rows * UPSERT_FRAC)
    n_chunks = max(1, -(-n_up // chunk))
    span = n_rows // n_chunks

    def sample(n, k):
        # O(k) rejection sampling (k/n ≈ UPSERT_FRAC, so retries are rare)
        out = np.unique(rng.integers(0, n, int(k * 1.1) + 16, dtype=np.int64))
        while out.size < k:
            out = np.unique(
                np.concatenate([out, rng.integers(0, n, k, dtype=np.int64)])
            )
        rng.shuffle(out)
        return out[:k]

    for c in range(n_chunks):
        take = min(chunk, n_up - c * chunk)
        lo = c * span
        piece = lo + sample(min(span, n_rows - lo), take)
        cols = {"id": piece}
        for i in range(N_FEATURES):
            cols[f"f{i}"] = rng.normal(size=len(piece)).astype(np.float32)
        cols["label"] = rng.integers(0, 2, len(piece)).astype(np.int32)
        t.upsert(pa.table(cols, schema=_bench_schema()))


def build_table(catalog):
    """Our table in the framework's native LSF format + an upsert wave → real
    MOR.  Using LSF is the point of having a native format (the reference
    ships Vortex for the same reason): zero-copy mmap decode, ~9x parquet-lz4
    on this schema.  The baseline keeps the reference writer's parquet
    settings and zero repo code — the comparison stays arms-length."""
    name = f"bench_{N_ROWS}_lsf"
    if catalog.table_exists(name):
        return catalog.table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
        properties={"lakesoul.file_format": "lsf"},
    )
    for chunk in _chunks(N_ROWS):
        t.write_arrow(chunk)
    _upsert_wave(t, seed=1)
    return t


def build_stream_table(catalog):
    """The ≥100M-row table for the scale legs: LSF, hash-bucketed, a small
    memory budget pinned in table properties (forces the bounded STREAMING
    read path), and one upsert wave so the streaming merge does real
    merge-on-read work — not just sequential decode."""
    name = f"bench_stream_{STREAM_ROWS}_lsf"
    if catalog.table_exists(name):
        t = catalog.table(name)
        if t.info.properties.get("bench.complete") == "1":
            return t
        # a previous run died mid-build: measuring a partial table would be
        # a silent lie — rebuild from scratch
        catalog.drop_table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
        properties={
            "lakesoul.file_format": "lsf",
            "lakesoul.memory_budget_bytes": str(STREAM_BUDGET_MB << 20),
        },
    )
    for chunk in _chunks(STREAM_ROWS, chunk=2_000_000):
        t.write_arrow(chunk)
    _upsert_wave(t, seed=11, n_rows=STREAM_ROWS)
    t.set_properties({"bench.complete": "1"})
    return t


def bench_stream_bounded(t) -> dict:
    """Sustained bounded-memory streaming over the scale table: rows/s and
    the process's peak RSS, which must stay under STREAM_RSS_CEILING_MB —
    the whole point is that throughput does NOT come from materializing the
    table (ref stance: benches/spill_bench.rs, cache_bench.rs).  Runs in a
    fresh subprocess so the high-water mark is this leg's own; measured via
    VmHWM — ru_maxrss survives exec and would report the bench driver's
    peak (utils/memory.py).  No JAX in this leg (pure host path)."""
    from lakesoul_tpu.obs.stages import stage_seconds
    from lakesoul_tpu.utils.memory import peak_rss_mb as _peak

    stages0 = stage_seconds()
    start = time.perf_counter()
    rows = 0
    for batch in t.scan().batch_size(262_144).to_batches():
        rows += len(batch)
    wall = time.perf_counter() - start
    peak_rss_mb = _peak()
    if peak_rss_mb > STREAM_RSS_CEILING_MB:
        raise RuntimeError(
            f"stream leg peak RSS {peak_rss_mb:.0f} MB exceeded the"
            f" {STREAM_RSS_CEILING_MB} MB ceiling (budget {STREAM_BUDGET_MB} MB)"
        )
    return {
        "rows": rows,
        "rows_per_s": rows / wall,
        "peak_rss_mb": round(peak_rss_mb, 1),
        "budget_mb": STREAM_BUDGET_MB,
        "ceiling_mb": STREAM_RSS_CEILING_MB,
        # per-stage attribution (lakesoul_scan_stage_seconds delta): the
        # breakdown every scan-path perf claim is judged against
        "scan_stages": {
            k: round(v - stages0[k], 3) for k, v in stage_seconds().items()
        },
    }


def bench_sharded_loaders(n_workers: int) -> dict:
    """Multi-process DP loaders over SHARED storage: every worker scans its
    ``shard(rank, world)`` slice of the scale table concurrently (the
    multi-host input-pipeline shape, SURVEY §2.8 row 1 — rank sharding over
    scan units, coordination only through the shared store).  Aggregate
    rows/s from first start to last finish."""
    import subprocess as sp

    start = time.perf_counter()
    procs = [
        sp.Popen(
            [sys.executable, __file__, "--leg", f"shard_worker:{rank}:{n_workers}"],
            stdout=sp.PIPE, stderr=sp.PIPE, text=True,
        )
        for rank in range(n_workers)
    ]
    rows = 0
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=3600)
            lines = [ln for ln in out.splitlines() if ln.startswith("{")]
            if p.returncode != 0 or not lines:
                sys.stderr.write(err[-2000:])
                raise RuntimeError(
                    f"shard worker {rank}/{n_workers} failed (rc={p.returncode})"
                )
            rows += json.loads(lines[-1])["rows"]
    finally:
        for p in procs:  # never leave siblings scanning in the background
            if p.poll() is None:
                p.kill()
    wall = time.perf_counter() - start
    return {"rows": rows, "rows_per_s": rows / wall, "workers": n_workers}


def build_baseline_dataset(root: str) -> str:
    """Arms-length baseline data: plain parquet files written by pyarrow with
    the reference writer's settings — no repo code involved."""
    import pyarrow.parquet as pq

    data_dir = os.path.join(root, f"baseline_{N_ROWS}")
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        return data_dir
    os.makedirs(data_dir, exist_ok=True)
    for i, chunk in enumerate(_chunks(N_ROWS)):
        pq.write_table(
            chunk,
            os.path.join(data_dir, f"part-{i:05d}.parquet"),
            compression="zstd",
            compression_level=1,
            use_dictionary=False,
        )
    return data_dir


def _drain(x) -> None:
    """Force REAL completion of queued device work before stopping a timer.
    On the tunneled dev platform, block_until_ready returns while compute is
    still in flight (measured: 2 ms vs the 1.5 s a device_get then takes),
    which would credit an epoch with unfinished work — so every timed leg
    round-trips an actual value instead."""
    import jax

    jax.device_get(x)


def bench_lakesoul(t, *, epochs: int = 2, device_cache: bool = False) -> float:
    import jax
    import jax.numpy as jnp
    import optax

    from lakesoul_tpu.models.mlp import init_mlp_params, mlp_loss

    params = init_mlp_params(jax.random.key(0), N_FEATURES, hidden=256)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        # x arrives [F, k*B]: the host ships ONE contiguous array per k-step
        # group, and lax.scan runs k REAL optimizer steps (batch size BATCH
        # each) in a single dispatch — per-call latency on the chip link
        # (tunnel here, PCIe/DMA on a TPU VM) amortizes over k steps.  The
        # reshape/transpose to [k, B, F] happens on-chip where it's HBM-
        # bandwidth cheap and folds into the first matmul's layout.
        k = x.shape[1] // BATCH
        xs = x.reshape(N_FEATURES, k, BATCH).transpose(1, 2, 0)
        ys = y.reshape(k, BATCH).astype(jnp.int32)

        def body(carry, xy):
            p, o = carry
            xb, yb = xy
            loss, grads = jax.value_and_grad(mlp_loss)(p, xb, yb)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), (xs, ys))
        return params, opt_state, losses[-1]

    # ONE [F, rows] array per group: concatenating F contiguous columns is a
    # straight memcpy — ~6x cheaper on a 1-core host than np.stack's strided
    # transpose — and one big transfer beats many small ones on the link.
    # Features ship as bfloat16 (the TPU-native input dtype: halves wire
    # bytes, the MXU matmul promotes against f32 params — standard practice
    # per the scaling playbook).  The tail group is trimmed to a BATCH
    # multiple (every delivered row still passes through an optimizer step
    # and is counted exactly).
    import ml_dtypes

    def col_transform(b):
        n = (len(b["label"]) // BATCH) * BATCH
        x = np.concatenate(
            [b[f"f{i}"][:n] for i in range(N_FEATURES)]
        ).reshape(N_FEATURES, -1).astype(ml_dtypes.bfloat16)
        # class labels ride as int8 (widened on-chip): 4 → 1 wire bytes/row
        return {"x": x, "y": b["label"][:n].astype(np.int8)}

    group_rows = BATCH * STEPS_PER_CALL

    def batches(io_threads=None):
        return t.scan().batch_size(group_rows).to_jax_iter(
            transform=col_transform, io_threads=io_threads, drop_remainder=False,
        )

    # warm-up: AOT-compile every group shape from ShapeDtypeStructs — NO
    # data crosses the chip link before the timed epochs (a transfer-heavy
    # warm-up epoch would hand them a degraded tunnel; on a TPU VM this is
    # simply free AOT compilation).  The rebatcher emits fixed group_rows
    # windows plus one BATCH-trimmed tail, so the shapes derive from the
    # delivered row count (metadata-only on compacted tables).
    total = t.scan().count_rows()
    shapes = []
    if total >= group_rows:
        shapes.append(((N_FEATURES, group_rows), (group_rows,)))
    tail = (total % group_rows) // BATCH * BATCH
    if tail:
        shapes.append(((N_FEATURES, tail), (tail,)))
    sds = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), (params, opt_state))
    compiled = {
        xs: step.lower(
            sds[0], sds[1],
            jax.ShapeDtypeStruct(xs, jnp.bfloat16),
            jax.ShapeDtypeStruct(ys, jnp.int8),
        ).compile()
        for xs, ys in shapes
    }

    best = 0.0
    loss = None
    if device_cache:
        # HBM-resident leg: the loader's cache="device" pins the epoch in
        # device memory on the first pass (20M rows x 33 B/row ≈ 660 MB —
        # well inside one chip's HBM); steady-state epochs replay resident
        # arrays with ZERO storage/host/link traffic.  Reported separately —
        # this measures the epoch-cache feature, not delivery from storage.
        it = t.scan().batch_size(group_rows).to_jax_iter(
            transform=col_transform, io_threads=2, drop_remainder=False,
            cache="device",
        )
        for batch in it:  # fill epoch (trains too, untimed)
            if len(batch["y"]):
                params, opt_state, loss = compiled[batch["x"].shape](
                    params, opt_state, batch["x"], batch["y"]
                )
        _drain(loss)
        epoch_iter = lambda: it
    else:
        epoch_iter = lambda: batches(io_threads=2)
    for _ in range(epochs):  # best-of-N epochs damps filesystem/cache variance
        rows = 0
        start = time.perf_counter()
        # io_threads=2: lz4/lsf decode releases the GIL, overlapping unit
        # decode with device transfer even on small hosts
        for batch in epoch_iter():
            if not len(batch["y"]):
                continue
            params, opt_state, loss = compiled[batch["x"].shape](
                params, opt_state, batch["x"], batch["y"]
            )
            rows += len(batch["y"])  # exact, like the baseline counts
        _drain(loss)
        dt = time.perf_counter() - start
        best = max(best, rows / dt)
    return best


def bench_torch_baseline(data_dir: str) -> float:
    """Pure pyarrow.dataset → torch DataLoader loop.  No repo imports."""
    try:
        import torch
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError:
        return float("nan")

    import pyarrow.dataset as pads

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir) if f.endswith(".parquet")
    )

    class DS(IterableDataset):
        def __iter__(self):
            import torch.utils.data as tud

            info = tud.get_worker_info()
            mine = (
                files
                if info is None
                else [f for i, f in enumerate(files) if i % info.num_workers == info.id]
            )
            if not mine:
                return
            ds = pads.dataset(mine, format="parquet")
            yield from ds.to_batches(batch_size=BATCH)

    def collate(batches):
        b = batches[0]
        x = np.stack(
            [b.column(f"f{i}").to_numpy(zero_copy_only=False) for i in range(N_FEATURES)],
            axis=1,
        )
        y = b.column("label").to_numpy(zero_copy_only=False).astype(np.int32)
        return torch.from_numpy(x), torch.from_numpy(y)

    best = 0.0
    # give the baseline its best configuration: in-process decode AND
    # process-worker decode (the standard DataLoader parallelism).  The
    # worker leg forks, which is only safe because the baseline runs BEFORE
    # any JAX/TPU initialization (see main()).
    for workers in (0, 2):
        try:
            for _ in range(2):
                loader = DataLoader(
                    DS(), batch_size=1, collate_fn=collate, num_workers=workers
                )
                rows = 0
                acc = torch.zeros(())
                start = time.perf_counter()
                for x, y in loader:
                    acc = acc + x.sum() * 0  # consume
                    rows += len(x)
                dt = time.perf_counter() - start
                best = max(best, rows / dt)
        except Exception:
            if workers == 0:
                raise  # in-process leg must work; worker leg may not fork
    return best


def bench_torch_baseline_e2e(data_dir: str) -> float:
    """The BASELINE.md comparator measured end to end: a stock
    pyarrow.dataset → torch DataLoader pipeline DELIVERING INTO the same
    jitted train step on the same chip ("rows/sec/chip ≥ GPU-DataLoader
    rows/sec/chip" is a delivery-to-accelerator metric).  No repo imports:
    the model is the same 16→256→2 adam MLP written inline, fed the way a
    framework-less user feeds it — float32 [B, F] host batches, synchronous
    device_put, jit on first call.  The baseline keeps DataLoader worker
    parallelism: every jax device op is deferred until after the persistent
    workers have forked (fork-before-backend-init is safe; the workers
    survive across epochs, so no later fork sees an initialized runtime)."""
    try:
        import torch
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError:
        return float("nan")

    import pyarrow.dataset as pads

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir) if f.endswith(".parquet")
    )

    class DS(IterableDataset):
        def __iter__(self):
            import torch.utils.data as tud

            info = tud.get_worker_info()
            mine = (
                files
                if info is None
                else [f for i, f in enumerate(files) if i % info.num_workers == info.id]
            )
            if not mine:
                return
            ds = pads.dataset(mine, format="parquet")
            yield from ds.to_batches(batch_size=BATCH)

    def collate(batches):
        b = batches[0]
        x = np.stack(
            [b.column(f"f{i}").to_numpy(zero_copy_only=False) for i in range(N_FEATURES)],
            axis=1,
        )
        y = b.column("label").to_numpy(zero_copy_only=False).astype(np.int32)
        return torch.from_numpy(x), torch.from_numpy(y)

    state = {}  # jax model state, built lazily AFTER workers fork

    def make_step():
        import jax
        import jax.numpy as jnp
        import optax

        def loss_fn(params, x, y):
            h = jax.nn.relu(x @ params[0]["w"] + params[0]["b"])
            logits = h @ params[1]["w"] + params[1]["b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

        tx = optax.adam(1e-3)
        params = []
        key = jax.random.key(0)
        for a, b in zip((N_FEATURES, 256), (256, 2)):
            key, sub = jax.random.split(key)
            params.append({"w": jax.random.normal(sub, (a, b)) * (2.0 / a) ** 0.5,
                           "b": jnp.zeros((b,))})

        @jax.jit
        def step(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        state.update(params=params, opt_state=tx.init(params), step=step)

    best = 0.0
    for workers in (2, 0):
        try:
            kw = {"num_workers": workers, "persistent_workers": True} if workers else {}
            # ONE loader across epochs: persistent workers fork exactly once,
            # at first iteration — BEFORE any jax device op in this leg
            # (state is built lazily below), so the forked children never
            # inherit an initialized TPU runtime and no timed epoch pays
            # worker startup twice
            loader = DataLoader(DS(), batch_size=1, collate_fn=collate, **kw)
            for _ in range(2):  # best-of: first epoch pays the jit compile
                import jax

                rows = 0
                loss = None
                start = time.perf_counter()
                for x, y in loader:
                    if not state:
                        make_step()  # workers are alive; jax init is safe now
                    state["params"], state["opt_state"], loss = state["step"](
                        state["params"], state["opt_state"],
                        jax.device_put(x.numpy()), jax.device_put(y.numpy()),
                    )
                    rows += len(x)
                _drain(loss)
                dt = time.perf_counter() - start
                best = max(best, rows / dt)
        except Exception as e:
            if workers == 0:
                raise  # the in-process leg must work; the worker leg may not
            # a degraded baseline inflates vs_baseline — say so, loudly
            sys.stderr.write(
                f"bench: baseline_e2e worker leg failed ({e!r}); "
                "baseline is the single-process measurement only\n"
            )
    return best


def bench_ann() -> dict:
    """Device-resident ANN search: batch QPS, recall@10 (full probe AND the
    reference's realistic nprobe=8 operating point), serving QPS.

    Serving QPS = per-request traffic from 16 concurrent clients through the
    micro-batching AnnEndpoint (vector/serving.py)."""
    from lakesoul_tpu.vector.config import VectorIndexConfig
    from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams

    rng = np.random.default_rng(0)
    # mixture of gaussians — real embedding spaces are clustered; pure
    # isotropic noise has NO cluster structure, which makes IVF probing
    # look arbitrarily bad at low nprobe regardless of the index quality
    centers = rng.normal(size=(256, ANN_D)).astype(np.float32) * 1.5
    assign = rng.integers(0, len(centers), ANN_N)
    vectors = centers[assign] + rng.normal(size=(ANN_N, ANN_D)).astype(np.float32)
    ids = np.arange(ANN_N, dtype=np.uint64)
    cfg = VectorIndexConfig(column="emb", dim=ANN_D, nlist=128, total_bits=4)
    index = IvfRabitqIndex.train(vectors, ids, cfg, keep_raw=True)
    index.enable_device_cache()
    # HELD-OUT queries: fresh samples from the same mixture (not perturbed
    # dataset vectors, whose true neighbors are trivially themselves) — the
    # recall metric keeps headroom to catch index-quality regressions
    queries = (
        centers[rng.integers(0, len(centers), ANN_Q)]
        + rng.normal(size=(ANN_Q, ANN_D)).astype(np.float32)
    )
    # full probe + deep exact re-rank: the device-resident kernel scans every
    # packed code regardless of nprobe (the probe set only gates inclusion),
    # so probing all clusters costs nothing extra on this path and recall is
    # bounded only by the re-rank shortlist (measured 1.00 at depth 100)
    params = SearchParams(top_k=10, nprobe=128, rerank_depth=100)
    index.batch_search(queries[:256], params)  # warm-up the chunk shape (MAX_Q)
    qps = 0.0
    for _ in range(2):  # best-of-2 damps chip-link variance
        start = time.perf_counter()
        got_ids, _ = index.batch_search(queries, params)
        qps = max(qps, ANN_Q / (time.perf_counter() - start))
    # single-query serving path: requests arrive one at a time from many
    # concurrent clients and ride the micro-batching AnnEndpoint (collect a
    # few ms → ONE fused batch dispatch → fan out) — the TPU serving answer
    # to per-request traffic.  A strictly serial loop on this tunneled dev
    # link measures its ~150 ms round trip, not the framework, so the
    # serving figure is the honest per-request throughput metric here.
    import threading

    from lakesoul_tpu.vector.serving import AnnEndpoint

    index.search(queries[0], params)  # warm the Q=1..8 compiled shapes
    n_clients, per_client = 16, 16
    with AnnEndpoint(index, params, max_batch=256, max_wait_ms=5.0) as ep:
        ep.search(queries[0])  # warm the endpoint path end to end
        start = time.perf_counter()

        def client(lo):
            for q in queries[lo : lo + per_client]:
                ep.search(q, timeout=120)

        threads = [
            threading.Thread(target=client, args=(i * per_client,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        qps_single = n_clients * per_client / (time.perf_counter() - start)
    # realistic-probe leg (VERDICT r3 item 2): the reference asserts
    # recall@10 ≥ 0.5 at nprobe 4–8 (python/tests/vector/test_e2e_glove.py:
    # 182) — quote the same operating point alongside the full-probe figure
    params8 = SearchParams(top_k=10, nprobe=8, rerank_depth=100)
    got_ids8, _ = index.batch_search(queries, params8)

    # recall on a subsample (brute force over 200k x 4096 is the expensive bit)
    sample = rng.choice(ANN_Q, 100, replace=False)
    hits = hits8 = 0
    for s in sample:
        q = queries[s]
        d2 = np.sum((vectors - q) ** 2, axis=1)
        true = set(np.argpartition(d2, 10)[:10].tolist())
        hits += len(true & {int(i) for i in got_ids[s]})
        hits8 += len(true & {int(i) for i in got_ids8[s]})
    return {
        "qps": qps,
        "recall": hits / (len(sample) * 10),
        "qps_serving": qps_single,
        "recall_nprobe8": hits8 / (len(sample) * 10),
    }


def bench_remote() -> tuple[float, float, float]:
    """Latency-injected object store: (cold rows/s, warm rows/s, hit rate)."""
    import fsspec
    from fsspec.implementations.memory import MemoryFileSystem

    class SlowMemFS(MemoryFileSystem):
        """10 ms per GET — a GCS-like RTT on every ranged read."""

        protocol = "slowmem"
        latency = 0.010

        def cat_file(self, *a, **k):
            time.sleep(self.latency)
            return super().cat_file(*a, **k)

        def _open(self, *a, **k):
            if a and isinstance(a[0], str) and "w" not in (k.get("mode") or (a[1] if len(a) > 1 else "rb")):
                time.sleep(self.latency)
            return super()._open(*a, **k)

    if "slowmem" not in fsspec.registry:
        fsspec.register_implementation("slowmem", SlowMemFS, clobber=True)

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.io.object_store import cache_stats

    cache_dir = os.path.join(REPO, ".bench_data", "page_cache")
    shutil.rmtree(cache_dir, ignore_errors=True)
    # the in-memory 'remote' store is process-local: fresh metadata every run
    meta_db = os.path.join(REPO, ".bench_data", "remote_meta.db")
    for suffix in ("", "-wal", "-shm"):
        try:
            os.remove(meta_db + suffix)
        except OSError:
            pass
    opts = {"lakesoul.cache_dir": cache_dir}
    catalog = LakeSoulCatalog(
        "slowmem://bench_wh", storage_options=opts, db_path=meta_db
    )
    name = f"remote_{REMOTE_ROWS}"
    if not catalog.table_exists(name):
        t = catalog.create_table(
            name, _bench_schema(), primary_keys=["id"], hash_bucket_num=4
        )
        for chunk in _chunks(REMOTE_ROWS, seed=2):
            t.write_arrow(chunk)
    t = catalog.table(name)

    def scan_once():
        rows = 0
        start = time.perf_counter()
        for b in t.scan().batch_size(BATCH).to_batches():
            rows += len(b)
        return rows / (time.perf_counter() - start)

    cold = scan_once()
    before = cache_stats(opts)
    warm = scan_once()
    after = cache_stats(opts)
    # hit rate of the WARM scan alone (the cold scan is all misses by design)
    warm_hits = after["hits"] - before["hits"]
    warm_misses = after["misses"] - before["misses"]
    rate = warm_hits / max(1, warm_hits + warm_misses)
    return cold, warm, rate


def bench_ann_hard() -> dict:
    """The NON-saturated ANN leg (VERDICT r4 weak #3): the easy leg's
    metric pinned at 1.0 and could not catch index-quality regressions.
    Here the mixture has 8x MORE clusters than the index has lists (1024
    centers vs nlist=128, tighter spacing, 8-bit planes) so nprobe=8 covers
    only a fraction of the true neighborhoods — recall@10 lands mid-range
    (~0.6-0.9, like the reference's GloVe anchor at nprobe 4-8,
    python/tests/vector/test_e2e_glove.py:182) and MOVES if quantization,
    probing, or re-ranking regress."""
    from lakesoul_tpu.vector.config import VectorIndexConfig
    from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams

    rng = np.random.default_rng(7)
    n, d, n_q = 200_000, 64, 1024
    centers = rng.normal(size=(1024, d)).astype(np.float32)  # unit spacing: overlap
    assign = rng.integers(0, len(centers), n)
    vectors = centers[assign] + rng.normal(size=(n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.uint64)
    cfg = VectorIndexConfig(column="emb", dim=d, nlist=128, total_bits=4)
    index = IvfRabitqIndex.train(vectors, ids, cfg, keep_raw=True)
    index.enable_device_cache()
    queries = (
        centers[rng.integers(0, len(centers), n_q)]
        + rng.normal(size=(n_q, d)).astype(np.float32)
    )
    params8 = SearchParams(top_k=10, nprobe=8, rerank_depth=100)
    got8, _ = index.batch_search(queries, params8)
    params32 = SearchParams(top_k=10, nprobe=32, rerank_depth=100)
    got32, _ = index.batch_search(queries, params32)
    sample = rng.choice(n_q, 100, replace=False)
    hits8 = hits32 = 0
    for s in sample:
        d2 = np.sum((vectors - queries[s]) ** 2, axis=1)
        true = set(np.argpartition(d2, 10)[:10].tolist())
        hits8 += len(true & {int(i) for i in got8[s]})
        hits32 += len(true & {int(i) for i in got32[s]})
    return {
        "recall_nprobe8": hits8 / (len(sample) * 10),
        "recall_nprobe32": hits32 / (len(sample) * 10),
        "clusters": len(centers),
        "nlist": 128,
    }


# --------------------------------------------------------------- HTTP store
HTTP_ROOT = os.path.join(REPO, ".bench_data", "http_store")


def _register_benchhttp():
    """fsspec protocol ``benchhttp://``: WRITES pass through to the local
    directory the HTTP server serves (table builds run at disk speed);
    READS issue real ranged HTTP GETs against the local server — actual
    sockets, actual request latency, the GCS-emulator shape (VERDICT r4
    weak #5).  Metadata stat/list stays local (it is not the measured data
    path and the leg labels itself accordingly)."""
    import fsspec
    from fsspec.implementations.local import LocalFileSystem
    from fsspec.spec import AbstractBufferedFile

    class BenchHttpFS(LocalFileSystem):
        protocol = "benchhttp"
        root = HTTP_ROOT
        port = HTTP_PORT
        # LocalFileSystem is cachable-by-class; a distinct subclass keeps
        # instances separate from plain "file" usage
        cachable = False

        @classmethod
        def _strip_protocol(cls, path):
            path = str(path)
            if path.startswith("benchhttp://"):
                path = path[len("benchhttp://"):]
            path = "/" + path.lstrip("/")
            return cls.root + path if not path.startswith(cls.root) else path

        def _http_get(self, rel: str, start=None, end=None) -> bytes:
            import urllib.request

            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/{rel.lstrip('/')}"
            )
            if start is not None:
                req.add_header("Range", f"bytes={start}-{max(start, end - 1)}")
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()

        def _rel(self, path) -> str:
            p = self._strip_protocol(path)
            return p[len(self.root):].lstrip("/")

        def cat_file(self, path, start=None, end=None, **kw):
            if start is None and end is None:
                return self._http_get(self._rel(path))
            size = self.info(path)["size"]
            if start is None:
                start = 0
            if start < 0:
                start += size
            if end is None or end > size:
                end = size
            if end <= start:
                return b""
            return self._http_get(self._rel(path), start, end)

        def _open(self, path, mode="rb", block_size=None, **kw):
            if "r" not in mode:
                return super()._open(path, mode=mode, block_size=block_size, **kw)
            fs = self

            class F(AbstractBufferedFile):
                def _fetch_range(self, start, end):
                    return fs._http_get(fs._rel(self.path), start, end)

            return F(self, path, mode="rb", block_size=block_size or 4 << 20,
                     size=self.info(path)["size"])

    if "benchhttp" not in fsspec.registry:
        fsspec.register_implementation("benchhttp", BenchHttpFS, clobber=True)
    return BenchHttpFS


def _start_http_server():
    """Range-supporting static file server over HTTP_ROOT — the 'emulator'."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from lakesoul_tpu.service.storage_proxy import parse_range

    root = HTTP_ROOT

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            import urllib.parse

            rel = urllib.parse.unquote(self.path.lstrip("/"))
            full = os.path.join(root, rel)
            if not os.path.isfile(full):
                self.send_error(404)
                return
            size = os.path.getsize(full)
            try:
                rng = parse_range(self.headers.get("Range"), size)
            except ValueError:
                self.send_error(416)
                return
            start, end = rng if rng is not None else (0, size)
            self.send_response(206 if rng else 200)
            if rng:
                self.send_header("Content-Range", f"bytes {start}-{end - 1}/{size}")
            self.send_header("Content-Length", str(end - start))
            self.end_headers()
            with open(full, "rb") as f:
                f.seek(start)
                remaining = end - start
                while remaining > 0:
                    piece = f.read(min(1 << 20, remaining))
                    if not piece:
                        break
                    self.wfile.write(piece)
                    remaining -= len(piece)

    srv = ThreadingHTTPServer(("127.0.0.1", HTTP_PORT), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _http_catalog(cache: bool):
    from lakesoul_tpu import LakeSoulCatalog

    _register_benchhttp()
    os.makedirs(HTTP_ROOT, exist_ok=True)
    cache_dir = os.path.join(REPO, ".bench_data", "http_page_cache")
    opts = {"lakesoul.cache_dir": cache_dir} if cache else {}
    return LakeSoulCatalog(
        "benchhttp://wh",
        storage_options=opts,
        db_path=os.path.join(REPO, ".bench_data", "http_meta.db"),
    ), opts


def build_http_table() -> None:
    """Stream-scale table under the benchhttp warehouse (writes are local
    passthrough; the build costs what the local build costs)."""
    catalog, _ = _http_catalog(cache=False)
    name = f"bench_http_{STREAM_ROWS}_lsf"
    if catalog.table_exists(name):
        t = catalog.table(name)
        if t.info.properties.get("bench.complete") == "1":
            return
        catalog.drop_table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
        properties={
            "lakesoul.file_format": "lsf",
            "lakesoul.memory_budget_bytes": str(STREAM_BUDGET_MB << 20),
        },
    )
    for chunk in _chunks(STREAM_ROWS, chunk=2_000_000, seed=17):
        t.write_arrow(chunk)
    t.set_properties({"bench.complete": "1"})


def _spawn_http_server():
    """The emulator server runs in its OWN process (exactly like a real
    fake-gcs-server would), so the measured leg's peak RSS is the READER's
    memory alone — the bounded-memory contract is about the client."""
    import subprocess as sp
    import urllib.error
    import urllib.request

    proc = sp.Popen(
        [sys.executable, __file__, "--leg", "http_server"],
        stdout=sp.DEVNULL, stderr=sp.DEVNULL,
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            # the fresh server died (e.g. port held by a stale orphan) —
            # answering-port + dead-child means the answerer is NOT ours
            raise RuntimeError(
                f"http emulator exited rc={proc.returncode} (stale server"
                f" on port {HTTP_PORT}?)"
            )
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{HTTP_PORT}/__ready__", timeout=1
            )
            return proc
        except urllib.error.HTTPError:
            return proc  # 404 = server is up and answering
        except OSError:
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("http emulator server did not come up")


def bench_http_stream(warm: bool) -> dict:
    """Bounded-memory scan of the stream-scale table over REAL ranged HTTP
    GETs; the warm leg re-reads through the owned page cache.  Reports
    rows/s, this subprocess's peak RSS (same ceiling contract as the local
    stream leg), and — warm — the page-cache hit rate."""
    from lakesoul_tpu.io.object_store import cache_stats
    from lakesoul_tpu.utils.memory import peak_rss_mb as _peak

    cache_dir = os.path.join(REPO, ".bench_data", "http_page_cache")
    if not warm:
        shutil.rmtree(cache_dir, ignore_errors=True)
    catalog, opts = _http_catalog(cache=True)
    srv = _spawn_http_server()
    try:
        t = catalog.table(f"bench_http_{STREAM_ROWS}_lsf")
        before = cache_stats(opts)
        start = time.perf_counter()
        rows = 0
        for batch in t.scan().batch_size(262_144).to_batches():
            rows += len(batch)
        wall = time.perf_counter() - start
        after = cache_stats(opts)
        hits = after["hits"] - before["hits"]
        misses = after["misses"] - before["misses"]
        peak = _peak()
        if peak > STREAM_RSS_CEILING_MB:
            raise RuntimeError(
                f"http stream leg peak RSS {peak:.0f} MB exceeded the"
                f" {STREAM_RSS_CEILING_MB} MB ceiling"
            )
        return {
            "rows": rows,
            "rows_per_s": rows / wall,
            "hit_rate": hits / max(1, hits + misses),
            "peak_rss_mb": round(peak, 1),
        }
    finally:
        srv.terminate()
        srv.wait(timeout=10)


def _device_reachable(timeout_s: float = 180.0) -> bool:
    """Probe jax backend init on a daemon thread: a wedged TPU tunnel hangs
    jax.devices() forever, which must not leave the driver with no output.
    After a failed probe this PROCESS must never touch jax (the hung import
    holds locks) — the caller re-execs on CPU instead."""
    import subprocess as sp

    code = "import jax; jax.devices(); print('ok')"
    try:
        out = sp.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
            env={**os.environ},
        )
        return out.returncode == 0 and "ok" in out.stdout
    except sp.TimeoutExpired:
        return False


def _acquire_device(
    attempts: int | None = None,
    probe_timeout_s: float = 120.0,
    backoff_s: float = 30.0,
) -> tuple[bool, dict]:
    """Probe the chip (VERDICT r4 weak #1: ONE cheap attempt by default —
    round 4 burned ~12 min of budget on probe retries before any leg ran).
    Extra attempts only when explicitly asked for AND budget remains; the
    probe record rides into the final JSON either way so a CPU fallback is
    LOUD, not a silent number."""
    if attempts is None:
        attempts = int(os.environ.get("LAKESOUL_BENCH_PROBE_ATTEMPTS", 1))
    info = {
        "attempts": 0,
        "probe_timeout_s": probe_timeout_s,
        "backoff_s": backoff_s,
    }
    start = time.time()
    for i in range(attempts):
        info["attempts"] = i + 1
        if _device_reachable(probe_timeout_s):
            info["wait_s"] = round(time.time() - start, 1)
            return True, info
        if i < attempts - 1:
            if _remaining() < probe_timeout_s + backoff_s * (i + 1) + 600:
                info["stopped"] = "budget"
                break
            time.sleep(backoff_s * (i + 1))
    info["wait_s"] = round(time.time() - start, 1)
    return False, info


class _AsyncProbe:
    """Run the device probe on a thread so the host-only legs overlap it."""

    def __init__(self):
        import threading

        self.ok = False
        self.info: dict = {}
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            self.ok, self.info = False, {"forced": "cpu"}
            self._thread = None
            return

        def run():
            self.ok, self.info = _acquire_device()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def result(self) -> tuple[bool, dict]:
        if self._thread is not None:
            self._thread.join()
        return self.ok, self.info


def _run_leg(leg: str, *, env: dict | None = None) -> dict:
    """Execute one leg in a FRESH subprocess and parse its JSON line.

    Isolation matters twice over: (a) the torch-DataLoader baseline forks,
    which must never share a process with an initialized TPU runtime, and
    (b) long-lived tunneled-device processes degrade (transfer throughput
    decays as a session ages), which would punish whichever leg runs last —
    each leg gets a fresh runtime so legs are comparable.  The subprocess
    timeout is the REMAINING global budget: an overrunning leg is killed
    and recorded, it cannot eat the whole round."""
    import subprocess as sp

    timeout = max(60.0, _remaining())
    out = sp.run(
        [sys.executable, __file__, "--leg", leg],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, **(env or {})},
    )
    last = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode != 0 or not last:
        sys.stderr.write(out.stderr[-2000:])
        raise RuntimeError(f"bench leg {leg!r} failed (rc={out.returncode})")
    return json.loads(last[-1])


_HOST_LEGS = (
    "stream", "build_main", "build_stream", "build_http",
    "http_stream_cold", "http_stream_warm", "http_server",
)


def run_one_leg(leg: str) -> None:
    if leg in _HOST_LEGS or leg.startswith("shard_worker:"):
        # pure host legs: never let a stray jax use grab the device
        os.environ["JAX_PLATFORMS"] = "cpu"

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.utils import honor_platform_env

    honor_platform_env()
    warehouse = os.path.join(REPO, ".bench_data")
    if leg == "build_main":
        catalog = LakeSoulCatalog(warehouse)
        build_table(catalog)
        build_baseline_dataset(warehouse)
        print(json.dumps({"ok": 1}))
        return
    if leg == "build_stream":
        catalog = LakeSoulCatalog(warehouse)
        build_stream_table(catalog)
        print(json.dumps({"ok": 1}))
        return
    if leg == "build_http":
        build_http_table()
        print(json.dumps({"ok": 1}))
        return
    if leg == "http_server":
        srv = _start_http_server()
        # die WITH the parent leg: if the leg subprocess is killed at the
        # budget boundary, an orphaned server would hold the fixed port
        # forever and poison later runs with a stale tree
        parent = os.getppid()
        try:
            while os.getppid() == parent:
                time.sleep(2)
        finally:
            srv.shutdown()
        return
    if leg == "http_stream_cold":
        print(json.dumps(bench_http_stream(warm=False)))
        return
    if leg == "http_stream_warm":
        print(json.dumps(bench_http_stream(warm=True)))
        return
    if leg == "baseline":
        print(json.dumps({"baseline": bench_torch_baseline(
            os.path.join(warehouse, f"baseline_{N_ROWS}"))}))
        return
    if leg == "baseline_e2e":
        print(json.dumps({"baseline": bench_torch_baseline_e2e(
            os.path.join(warehouse, f"baseline_{N_ROWS}"))}))
        return
    if leg == "remote":
        cold, warm, rate = bench_remote()
        print(json.dumps({"cold": cold, "warm": warm, "hit_rate": rate}))
        return
    if leg == "ann":
        print(json.dumps(bench_ann()))
        return
    if leg == "ann_hard":
        print(json.dumps(bench_ann_hard()))
        return
    if leg == "stream":
        catalog = LakeSoulCatalog(warehouse)
        print(json.dumps(bench_stream_bounded(
            catalog.table(f"bench_stream_{STREAM_ROWS}_lsf"))))
        return
    if leg.startswith("shard_worker:"):
        _, rank, world = leg.split(":")
        catalog = LakeSoulCatalog(warehouse)
        t = catalog.table(f"bench_stream_{STREAM_ROWS}_lsf")
        rows = 0
        for batch in t.scan().shard(int(rank), int(world)).batch_size(262_144).to_batches():
            rows += len(batch)
        print(json.dumps({"rows": rows}))
        return
    catalog = LakeSoulCatalog(warehouse)
    t = catalog.table(f"bench_{N_ROWS}_lsf")
    from lakesoul_tpu.obs.stages import stage_seconds

    if leg == "train_hbm":
        print(json.dumps({"rows_per_s": bench_lakesoul(t, epochs=3, device_cache=True)}))
        return
    stages0 = stage_seconds()
    value = bench_lakesoul(t, epochs=5)
    print(json.dumps({
        "rows_per_s": value,
        # per-stage attribution over ALL epochs of the leg (ratios are what
        # matter; the throughput figure is best-of-epochs above)
        "scan_stages": {
            k: round(v - stages0[k], 3) for k, v in stage_seconds().items()
        },
    }))


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--leg":
        run_one_leg(sys.argv[2])
        return

    emit = Emitter()
    emit.record.update(
        {
            "metric": "rows/sec/chip into JAX train loop (hash table)",
            "value": None,
            "unit": "rows/s/chip",
            "vs_baseline": None,
            # worker processes time-slice the same cores; on a 1-core host
            # the sharded leg proves concurrent shared-store correctness,
            # not scale-out
            "host_cores": os.cpu_count(),
        }
    )
    # the probe runs on a thread while the host-only legs do real work — a
    # dead tunnel costs nothing; the parent NEVER initializes JAX itself
    probe = _AsyncProbe()

    # ---- builds (subprocesses: killable at the budget boundary) ----------
    built_main = emit.leg(
        "build_main", lambda: _run_leg("build_main"), cost_s=120
    ) is not None
    from lakesoul_tpu import LakeSoulCatalog

    warehouse = os.path.join(REPO, ".bench_data")
    catalog = LakeSoulCatalog(warehouse)

    # ---- host-only legs while the probe owns the (possibly dead) tunnel --
    baseline_host = None
    if not built_main:
        emit.skip("baseline_host", "build_main did not complete")
    else:
        baseline_host = emit.leg(
            "baseline_host",
            lambda: _run_leg("baseline", env={"JAX_PLATFORMS": "cpu"})["baseline"],
            lambda out: (
                {"baseline_host_rows_per_s": round(out, 1)} if out == out else {}
            ),
            cost_s=240,
        )
    emit.leg(
        "remote", lambda: _run_leg("remote", env={"JAX_PLATFORMS": "cpu"}),
        lambda out: {
            "remote_cold_rows_per_s": round(out["cold"], 1),
            "remote_warm_rows_per_s": round(out["warm"], 1),
            "cache_hit_rate": round(out["hit_rate"], 4),
        },
        cost_s=180,
    )

    # ---- device acquisition ---------------------------------------------
    ok, probe_info = probe.result()
    device_label = "tpu" if ok else (
        "cpu" if probe_info.get("forced") else "cpu-fallback (device unreachable)"
    )
    dev_env = {} if ok else {"JAX_PLATFORMS": "cpu"}
    emit.update("device_probe", {"device": device_label, "device_probe": probe_info})

    # ---- headline train legs --------------------------------------------
    value = None
    if not built_main:
        # "complete" promises every leg ran or was EXPLICITLY skipped: a
        # failed build must not silently omit its dependents
        for name in ("mor_uncompacted", "headline", "baseline_e2e", "train_hbm"):
            emit.skip(name, "build_main did not complete")
    if built_main:
        t = catalog.table(f"bench_{N_ROWS}_lsf")

        def mor_leg():
            # live MOR: a cached table left compacted by a previous run gets
            # a fresh upsert wave so this leg never measures no-merge decode
            if all(len(u.data_files) <= 1 for u in t.scan().scan_plan()):
                _upsert_wave(t, seed=3)
            return _run_leg("train", env=dev_env)["rows_per_s"]

        emit.leg(
            "mor_uncompacted", mor_leg,
            lambda out: {"mor_uncompacted_rows_per_s": round(out, 1)},
            cost_s=420,
        )

        def headline_leg():
            # headline: steady-state delivery after compaction, the state a
            # served table sits in (ref stance: read throughput = bucket
            # parallelism + aggressive compaction, SURVEY §7)
            t.compact()
            return _run_leg("train", env=dev_env)

        def headline_fields(out):
            fields = {"value": round(out["rows_per_s"], 1)}
            if out.get("scan_stages"):
                # committed breakdown: every scan-path claim is a number
                fields["scan_stages"] = out["scan_stages"]
            if baseline_host is not None and baseline_host == baseline_host:
                fields["vs_baseline_host_decode_only"] = round(
                    out["rows_per_s"] / baseline_host, 3
                )
            return fields

        headline_out = emit.leg("headline", headline_leg, headline_fields, cost_s=420)
        value = headline_out["rows_per_s"] if headline_out else None

        def baseline_e2e_fields(out):
            if out != out:  # torch missing → NaN: never fake a 1.0 ratio
                return {}
            return {
                "baseline_e2e_rows_per_s": round(out, 1),
                # vs_baseline compares like for like: both sides deliver rows
                # into the SAME jitted train step on the same device
                "vs_baseline": (
                    round(value / out, 3) if value is not None else None
                ),
            }

        emit.leg(
            "baseline_e2e",
            lambda: _run_leg("baseline_e2e", env=dev_env)["baseline"],
            baseline_e2e_fields,
            cost_s=300,
        )
        emit.leg(
            "train_hbm",
            lambda: _run_leg("train_hbm", env=dev_env)["rows_per_s"],
            lambda out: {"hbm_resident_replay_rows_per_s": round(out, 1)},
            cost_s=300,
        )

    # ---- ANN legs --------------------------------------------------------
    emit.leg(
        "ann", lambda: _run_leg("ann", env=dev_env),
        lambda out: {
            "ann_qps": round(out["qps"], 1),
            "ann_qps_serving": round(out["qps_serving"], 1),
            "ann_recall_at_10": round(out["recall"], 4),
            "ann_recall_at_10_nprobe8": round(out["recall_nprobe8"], 4),
        },
        cost_s=240,
    )
    emit.leg(
        "ann_hard", lambda: _run_leg("ann_hard", env=dev_env),
        lambda out: {
            "ann_hard_recall_at_10_nprobe8": round(out["recall_nprobe8"], 4),
            "ann_hard_recall_at_10_nprobe32": round(out["recall_nprobe32"], 4),
            "ann_hard_clusters": out["clusters"],
        },
        cost_s=180,
    )

    # ---- stream-scale legs (most expensive; cached across runs) ----------
    built_stream = emit.leg(
        "build_stream", lambda: _run_leg("build_stream"), cost_s=420
    ) is not None
    if not built_stream:
        for name in ("stream", "sharded_loaders"):
            emit.skip(name, "build_stream did not complete")
    if built_stream:
        ts = catalog.table(f"bench_stream_{STREAM_ROWS}_lsf")

        def stream_leg():
            # the stream leg must exercise the streaming MERGE, not plain
            # decode: a previously-compacted cached table gets a fresh wave
            if all(len(u.data_files) <= 1 for u in ts.scan().scan_plan()):
                _upsert_wave(ts, seed=13, n_rows=STREAM_ROWS)
            return _run_leg("stream")

        emit.leg(
            "stream", stream_leg,
            lambda out: {
                "stream_rows": out["rows"],
                "stream_rows_per_s": round(out["rows_per_s"], 1),
                "stream_peak_rss_mb": out["peak_rss_mb"],
                "stream_budget_mb": out["budget_mb"],
                "stream_rss_ceiling_mb": out["ceiling_mb"],
                "stream_scan_stages": out.get("scan_stages"),
            },
            cost_s=300,
        )
        emit.leg(
            "sharded_loaders", lambda: bench_sharded_loaders(SHARD_WORKERS),
            lambda out: {
                "sharded_loaders_rows_per_s": round(out["rows_per_s"], 1),
                "sharded_loaders_workers": out["workers"],
            },
            cost_s=300,
        )

    # ---- HTTP object-store legs (GCS-emulator shape) ---------------------
    built_http = emit.leg(
        "build_http", lambda: _run_leg("build_http"), cost_s=420
    ) is not None
    if not built_http:
        for name in ("http_stream_cold", "http_stream_warm"):
            emit.skip(name, "build_http did not complete")
    if built_http:
        emit.leg(
            "http_stream_cold", lambda: _run_leg("http_stream_cold"),
            lambda out: {
                "http_stream_rows": out["rows"],
                "http_stream_cold_rows_per_s": round(out["rows_per_s"], 1),
                "http_stream_peak_rss_mb": out["peak_rss_mb"],
            },
            cost_s=300,
        )
        emit.leg(
            "http_stream_warm", lambda: _run_leg("http_stream_warm"),
            lambda out: {
                "http_stream_warm_rows_per_s": round(out["rows_per_s"], 1),
                "http_stream_warm_hit_rate": round(out["hit_rate"], 4),
            },
            cost_s=240,
        )

    # ---- freshness chaos leg (ingest-to-train SLO under fire) ------------
    def freshness_leg():
        """Run benchmarks/micro.py freshness in a fresh subprocess (three
        real roles + SIGKILL + flaky faults; see bench_freshness) and
        commit its published figures into the trajectory."""
        import subprocess as sp

        out = sp.run(
            [sys.executable, os.path.join(REPO, "benchmarks", "micro.py"),
             "freshness"],
            capture_output=True, text=True,
            timeout=max(60.0, _remaining()),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        lines = [
            json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")
        ]
        legs = [d for d in lines if d.get("bench") == "freshness" and "value" in d]
        if out.returncode != 0 or not legs:
            sys.stderr.write(out.stderr[-2000:])
            raise RuntimeError(
                f"freshness leg failed (rc={out.returncode})"
            )
        return legs[-1]

    emit.leg(
        "freshness", freshness_leg,
        lambda out: {
            "freshness_seconds": {
                "p50": out["freshness_p50_s"],
                "p99": out["freshness_p99_s"],
                "max": out["freshness_max_s"],
            },
            "freshness_slo_target_s": out["slo_target_s"],
            "freshness_slo_in_budget": out["slo_in_budget"],
            "freshness_rows_per_s": out["rows_per_s"],
            "freshness_rows": out["rows"],
            "freshness_oracle_exact": out["oracle_exact"],
            "freshness_chaos": {
                "fault_p": out["fault_p"],
                "compactor_sigkilled": out["compactor_sigkilled"],
                "takeover_fenced": out["takeover_fenced"],
                "lease_ttl_s": out["lease_ttl_s"],
            },
        },
        cost_s=240,
    )

    # ---- soak leg (resource-boundedness: flat fd/thread/heap slopes) -----
    def soak_leg():
        """Run benchmarks/micro.py soak in a fresh subprocess (repeated
        open→scan→serve→close lifecycles; see bench_soak) — a fresh
        runtime matters MORE here than elsewhere, the leg gates on this
        process's own fd/thread/heap slopes — and commit its published
        figures as BENCH_soak.json."""
        import subprocess as sp

        out = sp.run(
            [sys.executable, os.path.join(REPO, "benchmarks", "micro.py"),
             "soak"],
            capture_output=True, text=True,
            timeout=max(60.0, _remaining()),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        lines = [
            json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")
        ]
        legs = [d for d in lines if d.get("bench") == "soak_cycles" and "value" in d]
        if out.returncode != 0 or not legs:
            sys.stderr.write(out.stderr[-2000:])
            raise RuntimeError(
                f"soak leg failed (rc={out.returncode})"
            )
        with open(os.path.join(REPO, "BENCH_soak.json"), "w") as f:
            f.write(json.dumps(legs[-1]) + "\n")
        return legs[-1]

    emit.leg(
        "soak", soak_leg,
        lambda out: {
            "soak_cycles_per_s": out["value"],
            "soak_cycles": out["cycles"],
            "soak_slopes": {
                "fd": out["fd_slope"],
                "thread": out["thread_slope"],
                "heap_bytes": out["heap_slope_bytes"],
            },
            "soak_high_water": {
                "fd": out["fd_high_water"],
                "thread": out["thread_high_water"],
                "heap_bytes": out["heap_high_water"],
            },
            "soak_heap_budget": out["heap_budget"],
        },
        cost_s=60,
    )

    emit.record["complete"] = True
    emit._emit()


if __name__ == "__main__":
    main()
