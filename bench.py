"""Benchmark: rows/sec/chip from a hash-partitioned lakehouse table into a
jitted JAX training loop (the north-star metric, BASELINE.json), plus ANN
serving QPS and a remote-store (latency-injected) leg.

Legs and honesty rules (VERDICT r1 #2):

1. **MOR delivery (headline)** — our table (native LSF format, hash-bucketed,
   one upsert wave so merge-on-read does real work) → scan → merge →
   device_put → jitted MLP train step on the chip.
2. **Arms-length baselines** — the same rows written as a plain parquet
   dataset by pyarrow itself (zstd level 1, no dictionary — the reference
   writer's settings, writer/mod.rs:215-240), consumed by a pure
   pyarrow.dataset → torch DataLoader pipeline with ZERO repo imports.
   Two measurements: `baseline_e2e` delivers into the SAME jitted train
   step on the same chip (BASELINE.md's comparator — "GPU-DataLoader
   rows/sec/chip" is a delivery-to-accelerator metric) and sets
   vs_baseline; the host-decode-only loop (no device, strictly less work)
   is kept as vs_baseline_host_decode_only for r1/r2 continuity.
3. **HBM-resident replay** — the loader's cache="device" epoch cache:
   steady-state epochs replay from device memory with zero storage/host/
   link traffic.  Separately labeled; it measures the epoch-cache feature,
   not delivery from storage.
4. **ANN QPS** — device-resident IVF-RaBitQ batch search over a 200k x 64d
   shard; reports QPS and recall@10 vs brute force (full probe + exact
   re-rank at depth 100: the resident kernel scans every packed code
   regardless of nprobe, so full probing is free on this path).
5. **Remote leg** — a smaller table on a latency-injected in-memory object
   store (10 ms per GET — GCS-like) read cold then warm through the owned
   page cache.
6. **Scale legs** (VERDICT r3 item 4) — a ≥100M-row table (env-tunable):
   (a) bounded-memory STREAMING read with a 256 MB budget pinned in table
   properties; the leg records rows/s AND its own subprocess peak RSS and
   FAILS if RSS crosses the 2 GB ceiling — throughput must not come from
   materializing the table; (b) multi-process sharded loaders: N worker
   processes concurrently scan shard(rank, world) slices over the shared
   store (the multi-host input-pipeline shape), aggregate rows/s.

Device acquisition (VERDICT r3 item 2): the TPU probe retries with backoff;
when the tunnel stays wedged the bench emits a clearly-labeled CPU fallback
line with the probe record under "device_probe" — never a silent number.

Prints ONE json line:
  {"metric", "value", "unit", "vs_baseline", "vs_baseline_host_decode_only",
   "hbm_resident_replay_rows_per_s", "ann_qps", "ann_recall_at_10",
   "ann_recall_at_10_nprobe8", "remote_cold_rows_per_s",
   "remote_warm_rows_per_s", "cache_hit_rate", "stream_rows",
   "stream_rows_per_s", "stream_peak_rss_mb", "sharded_loaders_rows_per_s",
   "device", "device_probe"}
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np
import pyarrow as pa

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_ROWS = int(os.environ.get("LAKESOUL_BENCH_ROWS", 20_000_000))
# the scale leg (VERDICT r3 item 4): ≥100M rows through the bounded-memory
# streaming path + multi-process sharded loaders over shared storage
STREAM_ROWS = int(os.environ.get("LAKESOUL_BENCH_STREAM_ROWS", 100_000_000))
STREAM_BUDGET_MB = int(os.environ.get("LAKESOUL_BENCH_STREAM_BUDGET_MB", 256))
# hard ceiling the streaming leg must stay under (budget + runtime floor);
# exceeding it FAILS the leg loudly instead of reporting a pretty number
STREAM_RSS_CEILING_MB = int(os.environ.get("LAKESOUL_BENCH_STREAM_CEILING_MB", 2048))
SHARD_WORKERS = int(os.environ.get("LAKESOUL_BENCH_SHARD_WORKERS", 4))
UPSERT_FRAC = 0.05
N_FEATURES = 16
BUCKETS = 8
# 512k rows x 16 f32 ≈ 32 MB per transfer: per-dispatch latency (not
# bandwidth) dominates the host→chip link, so fewer, larger batches win.
# Clamped so small smoke runs still produce full (jit-friendly) batches.
BATCH = min(
    int(os.environ.get("LAKESOUL_BENCH_BATCH", 524288)),
    max(1024, N_ROWS // 8),
)
# optimizer steps fused into one device dispatch (lax.scan group); per-call
# link latency amortizes over the group
STEPS_PER_CALL = int(os.environ.get("LAKESOUL_BENCH_STEPS_PER_CALL", 8))
REMOTE_ROWS = min(N_ROWS, 2_000_000)
ANN_N, ANN_D, ANN_Q = 200_000, 64, 4096


def _bench_schema():
    fields = [("id", pa.int64())] + [(f"f{i}", pa.float32()) for i in range(N_FEATURES)]
    fields.append(("label", pa.int32()))
    return pa.schema(fields)


def _chunks(n_rows, start_at=0, chunk=500_000, seed=0):
    rng = np.random.default_rng(seed)
    for start in range(0, n_rows, chunk):
        n = min(chunk, n_rows - start)
        cols = {"id": np.arange(start_at + start, start_at + start + n, dtype=np.int64)}
        for i in range(N_FEATURES):
            cols[f"f{i}"] = rng.normal(size=n).astype(np.float32)
        cols["label"] = rng.integers(0, 2, n).astype(np.int32)
        yield pa.table(cols, schema=_bench_schema())


def _upsert_wave(t, seed: int, n_rows: int | None = None,
                 chunk: int = 2_000_000) -> None:
    """One MOR-provoking upsert wave: re-write UPSERT_FRAC of the keys,
    chunked so the wave never materializes whole in the driver.  Keys are
    sampled without replacement from DISJOINT id sub-ranges per chunk —
    `rng.choice(N, replace=False)` would permute the full N-row population
    (O(N) transient memory: ~8 GB at 1B rows) for a tiny sample."""
    n_rows = n_rows or N_ROWS
    rng = np.random.default_rng(seed)
    n_up = int(n_rows * UPSERT_FRAC)
    n_chunks = max(1, -(-n_up // chunk))
    span = n_rows // n_chunks

    def sample(n, k):
        # O(k) rejection sampling (k/n ≈ UPSERT_FRAC, so retries are rare)
        out = np.unique(rng.integers(0, n, int(k * 1.1) + 16, dtype=np.int64))
        while out.size < k:
            out = np.unique(
                np.concatenate([out, rng.integers(0, n, k, dtype=np.int64)])
            )
        rng.shuffle(out)
        return out[:k]

    for c in range(n_chunks):
        take = min(chunk, n_up - c * chunk)
        lo = c * span
        piece = lo + sample(min(span, n_rows - lo), take)
        cols = {"id": piece}
        for i in range(N_FEATURES):
            cols[f"f{i}"] = rng.normal(size=len(piece)).astype(np.float32)
        cols["label"] = rng.integers(0, 2, len(piece)).astype(np.int32)
        t.upsert(pa.table(cols, schema=_bench_schema()))


def build_table(catalog):
    """Our table in the framework's native LSF format + an upsert wave → real
    MOR.  Using LSF is the point of having a native format (the reference
    ships Vortex for the same reason): zero-copy mmap decode, ~9x parquet-lz4
    on this schema.  The baseline keeps the reference writer's parquet
    settings and zero repo code — the comparison stays arms-length."""
    name = f"bench_{N_ROWS}_lsf"
    if catalog.table_exists(name):
        return catalog.table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
        properties={"lakesoul.file_format": "lsf"},
    )
    for chunk in _chunks(N_ROWS):
        t.write_arrow(chunk)
    _upsert_wave(t, seed=1)
    return t


def build_stream_table(catalog):
    """The ≥100M-row table for the scale legs: LSF, hash-bucketed, a small
    memory budget pinned in table properties (forces the bounded STREAMING
    read path), and one upsert wave so the streaming merge does real
    merge-on-read work — not just sequential decode."""
    name = f"bench_stream_{STREAM_ROWS}_lsf"
    if catalog.table_exists(name):
        t = catalog.table(name)
        if t.info.properties.get("bench.complete") == "1":
            return t
        # a previous run died mid-build: measuring a partial table would be
        # a silent lie — rebuild from scratch
        catalog.drop_table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
        properties={
            "lakesoul.file_format": "lsf",
            "lakesoul.memory_budget_bytes": str(STREAM_BUDGET_MB << 20),
        },
    )
    for chunk in _chunks(STREAM_ROWS, chunk=2_000_000):
        t.write_arrow(chunk)
    _upsert_wave(t, seed=11, n_rows=STREAM_ROWS)
    t.set_properties({"bench.complete": "1"})
    return t


def bench_stream_bounded(t) -> dict:
    """Sustained bounded-memory streaming over the scale table: rows/s and
    the process's peak RSS, which must stay under STREAM_RSS_CEILING_MB —
    the whole point is that throughput does NOT come from materializing the
    table (ref stance: benches/spill_bench.rs, cache_bench.rs).  Runs in a
    fresh subprocess so the high-water mark is this leg's own; measured via
    VmHWM — ru_maxrss survives exec and would report the bench driver's
    peak (utils/memory.py).  No JAX in this leg (pure host path)."""
    from lakesoul_tpu.utils.memory import peak_rss_mb as _peak

    start = time.perf_counter()
    rows = 0
    for batch in t.scan().batch_size(262_144).to_batches():
        rows += len(batch)
    wall = time.perf_counter() - start
    peak_rss_mb = _peak()
    if peak_rss_mb > STREAM_RSS_CEILING_MB:
        raise RuntimeError(
            f"stream leg peak RSS {peak_rss_mb:.0f} MB exceeded the"
            f" {STREAM_RSS_CEILING_MB} MB ceiling (budget {STREAM_BUDGET_MB} MB)"
        )
    return {
        "rows": rows,
        "rows_per_s": rows / wall,
        "peak_rss_mb": round(peak_rss_mb, 1),
        "budget_mb": STREAM_BUDGET_MB,
        "ceiling_mb": STREAM_RSS_CEILING_MB,
    }


def bench_sharded_loaders(n_workers: int) -> dict:
    """Multi-process DP loaders over SHARED storage: every worker scans its
    ``shard(rank, world)`` slice of the scale table concurrently (the
    multi-host input-pipeline shape, SURVEY §2.8 row 1 — rank sharding over
    scan units, coordination only through the shared store).  Aggregate
    rows/s from first start to last finish."""
    import subprocess as sp

    start = time.perf_counter()
    procs = [
        sp.Popen(
            [sys.executable, __file__, "--leg", f"shard_worker:{rank}:{n_workers}"],
            stdout=sp.PIPE, stderr=sp.PIPE, text=True,
        )
        for rank in range(n_workers)
    ]
    rows = 0
    try:
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=3600)
            lines = [ln for ln in out.splitlines() if ln.startswith("{")]
            if p.returncode != 0 or not lines:
                sys.stderr.write(err[-2000:])
                raise RuntimeError(
                    f"shard worker {rank}/{n_workers} failed (rc={p.returncode})"
                )
            rows += json.loads(lines[-1])["rows"]
    finally:
        for p in procs:  # never leave siblings scanning in the background
            if p.poll() is None:
                p.kill()
    wall = time.perf_counter() - start
    return {"rows": rows, "rows_per_s": rows / wall, "workers": n_workers}


def build_baseline_dataset(root: str) -> str:
    """Arms-length baseline data: plain parquet files written by pyarrow with
    the reference writer's settings — no repo code involved."""
    import pyarrow.parquet as pq

    data_dir = os.path.join(root, f"baseline_{N_ROWS}")
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        return data_dir
    os.makedirs(data_dir, exist_ok=True)
    for i, chunk in enumerate(_chunks(N_ROWS)):
        pq.write_table(
            chunk,
            os.path.join(data_dir, f"part-{i:05d}.parquet"),
            compression="zstd",
            compression_level=1,
            use_dictionary=False,
        )
    return data_dir


def _drain(x) -> None:
    """Force REAL completion of queued device work before stopping a timer.
    On the tunneled dev platform, block_until_ready returns while compute is
    still in flight (measured: 2 ms vs the 1.5 s a device_get then takes),
    which would credit an epoch with unfinished work — so every timed leg
    round-trips an actual value instead."""
    import jax

    jax.device_get(x)


def bench_lakesoul(t, *, epochs: int = 2, device_cache: bool = False) -> float:
    import jax
    import jax.numpy as jnp
    import optax

    from lakesoul_tpu.models.mlp import init_mlp_params, mlp_loss

    params = init_mlp_params(jax.random.key(0), N_FEATURES, hidden=256)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        # x arrives [F, k*B]: the host ships ONE contiguous array per k-step
        # group, and lax.scan runs k REAL optimizer steps (batch size BATCH
        # each) in a single dispatch — per-call latency on the chip link
        # (tunnel here, PCIe/DMA on a TPU VM) amortizes over k steps.  The
        # reshape/transpose to [k, B, F] happens on-chip where it's HBM-
        # bandwidth cheap and folds into the first matmul's layout.
        k = x.shape[1] // BATCH
        xs = x.reshape(N_FEATURES, k, BATCH).transpose(1, 2, 0)
        ys = y.reshape(k, BATCH).astype(jnp.int32)

        def body(carry, xy):
            p, o = carry
            xb, yb = xy
            loss, grads = jax.value_and_grad(mlp_loss)(p, xb, yb)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), (xs, ys))
        return params, opt_state, losses[-1]

    # ONE [F, rows] array per group: concatenating F contiguous columns is a
    # straight memcpy — ~6x cheaper on a 1-core host than np.stack's strided
    # transpose — and one big transfer beats many small ones on the link.
    # Features ship as bfloat16 (the TPU-native input dtype: halves wire
    # bytes, the MXU matmul promotes against f32 params — standard practice
    # per the scaling playbook).  The tail group is trimmed to a BATCH
    # multiple (every delivered row still passes through an optimizer step
    # and is counted exactly).
    import ml_dtypes

    def col_transform(b):
        n = (len(b["label"]) // BATCH) * BATCH
        x = np.concatenate(
            [b[f"f{i}"][:n] for i in range(N_FEATURES)]
        ).reshape(N_FEATURES, -1).astype(ml_dtypes.bfloat16)
        # class labels ride as int8 (widened on-chip): 4 → 1 wire bytes/row
        return {"x": x, "y": b["label"][:n].astype(np.int8)}

    group_rows = BATCH * STEPS_PER_CALL

    def batches(io_threads=None):
        return t.scan().batch_size(group_rows).to_jax_iter(
            transform=col_transform, io_threads=io_threads, drop_remainder=False,
        )

    # warm-up: AOT-compile every group shape from ShapeDtypeStructs — NO
    # data crosses the chip link before the timed epochs (a transfer-heavy
    # warm-up epoch would hand them a degraded tunnel; on a TPU VM this is
    # simply free AOT compilation).  The rebatcher emits fixed group_rows
    # windows plus one BATCH-trimmed tail, so the shapes derive from the
    # delivered row count (metadata-only on compacted tables).
    total = t.scan().count_rows()
    shapes = []
    if total >= group_rows:
        shapes.append(((N_FEATURES, group_rows), (group_rows,)))
    tail = (total % group_rows) // BATCH * BATCH
    if tail:
        shapes.append(((N_FEATURES, tail), (tail,)))
    sds = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), (params, opt_state))
    compiled = {
        xs: step.lower(
            sds[0], sds[1],
            jax.ShapeDtypeStruct(xs, jnp.bfloat16),
            jax.ShapeDtypeStruct(ys, jnp.int8),
        ).compile()
        for xs, ys in shapes
    }

    best = 0.0
    loss = None
    if device_cache:
        # HBM-resident leg: the loader's cache="device" pins the epoch in
        # device memory on the first pass (20M rows x 33 B/row ≈ 660 MB —
        # well inside one chip's HBM); steady-state epochs replay resident
        # arrays with ZERO storage/host/link traffic.  Reported separately —
        # this measures the epoch-cache feature, not delivery from storage.
        it = t.scan().batch_size(group_rows).to_jax_iter(
            transform=col_transform, io_threads=2, drop_remainder=False,
            cache="device",
        )
        for batch in it:  # fill epoch (trains too, untimed)
            if len(batch["y"]):
                params, opt_state, loss = compiled[batch["x"].shape](
                    params, opt_state, batch["x"], batch["y"]
                )
        _drain(loss)
        epoch_iter = lambda: it
    else:
        epoch_iter = lambda: batches(io_threads=2)
    for _ in range(epochs):  # best-of-N epochs damps filesystem/cache variance
        rows = 0
        start = time.perf_counter()
        # io_threads=2: lz4/lsf decode releases the GIL, overlapping unit
        # decode with device transfer even on small hosts
        for batch in epoch_iter():
            if not len(batch["y"]):
                continue
            params, opt_state, loss = compiled[batch["x"].shape](
                params, opt_state, batch["x"], batch["y"]
            )
            rows += len(batch["y"])  # exact, like the baseline counts
        _drain(loss)
        dt = time.perf_counter() - start
        best = max(best, rows / dt)
    return best


def bench_torch_baseline(data_dir: str) -> float:
    """Pure pyarrow.dataset → torch DataLoader loop.  No repo imports."""
    try:
        import torch
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError:
        return float("nan")

    import pyarrow.dataset as pads

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir) if f.endswith(".parquet")
    )

    class DS(IterableDataset):
        def __iter__(self):
            import torch.utils.data as tud

            info = tud.get_worker_info()
            mine = (
                files
                if info is None
                else [f for i, f in enumerate(files) if i % info.num_workers == info.id]
            )
            if not mine:
                return
            ds = pads.dataset(mine, format="parquet")
            yield from ds.to_batches(batch_size=BATCH)

    def collate(batches):
        b = batches[0]
        x = np.stack(
            [b.column(f"f{i}").to_numpy(zero_copy_only=False) for i in range(N_FEATURES)],
            axis=1,
        )
        y = b.column("label").to_numpy(zero_copy_only=False).astype(np.int32)
        return torch.from_numpy(x), torch.from_numpy(y)

    best = 0.0
    # give the baseline its best configuration: in-process decode AND
    # process-worker decode (the standard DataLoader parallelism).  The
    # worker leg forks, which is only safe because the baseline runs BEFORE
    # any JAX/TPU initialization (see main()).
    for workers in (0, 2):
        try:
            for _ in range(2):
                loader = DataLoader(
                    DS(), batch_size=1, collate_fn=collate, num_workers=workers
                )
                rows = 0
                acc = torch.zeros(())
                start = time.perf_counter()
                for x, y in loader:
                    acc = acc + x.sum() * 0  # consume
                    rows += len(x)
                dt = time.perf_counter() - start
                best = max(best, rows / dt)
        except Exception:
            if workers == 0:
                raise  # in-process leg must work; worker leg may not fork
    return best


def bench_torch_baseline_e2e(data_dir: str) -> float:
    """The BASELINE.md comparator measured end to end: a stock
    pyarrow.dataset → torch DataLoader pipeline DELIVERING INTO the same
    jitted train step on the same chip ("rows/sec/chip ≥ GPU-DataLoader
    rows/sec/chip" is a delivery-to-accelerator metric).  No repo imports:
    the model is the same 16→256→2 adam MLP written inline, fed the way a
    framework-less user feeds it — float32 [B, F] host batches, synchronous
    device_put, jit on first call.  The baseline keeps DataLoader worker
    parallelism: every jax device op is deferred until after the persistent
    workers have forked (fork-before-backend-init is safe; the workers
    survive across epochs, so no later fork sees an initialized runtime)."""
    try:
        import torch
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError:
        return float("nan")

    import pyarrow.dataset as pads

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir) if f.endswith(".parquet")
    )

    class DS(IterableDataset):
        def __iter__(self):
            import torch.utils.data as tud

            info = tud.get_worker_info()
            mine = (
                files
                if info is None
                else [f for i, f in enumerate(files) if i % info.num_workers == info.id]
            )
            if not mine:
                return
            ds = pads.dataset(mine, format="parquet")
            yield from ds.to_batches(batch_size=BATCH)

    def collate(batches):
        b = batches[0]
        x = np.stack(
            [b.column(f"f{i}").to_numpy(zero_copy_only=False) for i in range(N_FEATURES)],
            axis=1,
        )
        y = b.column("label").to_numpy(zero_copy_only=False).astype(np.int32)
        return torch.from_numpy(x), torch.from_numpy(y)

    state = {}  # jax model state, built lazily AFTER workers fork

    def make_step():
        import jax
        import jax.numpy as jnp
        import optax

        def loss_fn(params, x, y):
            h = jax.nn.relu(x @ params[0]["w"] + params[0]["b"])
            logits = h @ params[1]["w"] + params[1]["b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

        tx = optax.adam(1e-3)
        params = []
        key = jax.random.key(0)
        for a, b in zip((N_FEATURES, 256), (256, 2)):
            key, sub = jax.random.split(key)
            params.append({"w": jax.random.normal(sub, (a, b)) * (2.0 / a) ** 0.5,
                           "b": jnp.zeros((b,))})

        @jax.jit
        def step(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        state.update(params=params, opt_state=tx.init(params), step=step)

    best = 0.0
    for workers in (2, 0):
        try:
            kw = {"num_workers": workers, "persistent_workers": True} if workers else {}
            # ONE loader across epochs: persistent workers fork exactly once,
            # at first iteration — BEFORE any jax device op in this leg
            # (state is built lazily below), so the forked children never
            # inherit an initialized TPU runtime and no timed epoch pays
            # worker startup twice
            loader = DataLoader(DS(), batch_size=1, collate_fn=collate, **kw)
            for _ in range(2):  # best-of: first epoch pays the jit compile
                import jax

                rows = 0
                loss = None
                start = time.perf_counter()
                for x, y in loader:
                    if not state:
                        make_step()  # workers are alive; jax init is safe now
                    state["params"], state["opt_state"], loss = state["step"](
                        state["params"], state["opt_state"],
                        jax.device_put(x.numpy()), jax.device_put(y.numpy()),
                    )
                    rows += len(x)
                _drain(loss)
                dt = time.perf_counter() - start
                best = max(best, rows / dt)
        except Exception as e:
            if workers == 0:
                raise  # the in-process leg must work; the worker leg may not
            # a degraded baseline inflates vs_baseline — say so, loudly
            sys.stderr.write(
                f"bench: baseline_e2e worker leg failed ({e!r}); "
                "baseline is the single-process measurement only\n"
            )
    return best


def bench_ann() -> dict:
    """Device-resident ANN search: batch QPS, recall@10 (full probe AND the
    reference's realistic nprobe=8 operating point), serving QPS.

    Serving QPS = per-request traffic from 16 concurrent clients through the
    micro-batching AnnEndpoint (vector/serving.py)."""
    from lakesoul_tpu.vector.config import VectorIndexConfig
    from lakesoul_tpu.vector.index import IvfRabitqIndex, SearchParams

    rng = np.random.default_rng(0)
    # mixture of gaussians — real embedding spaces are clustered; pure
    # isotropic noise has NO cluster structure, which makes IVF probing
    # look arbitrarily bad at low nprobe regardless of the index quality
    centers = rng.normal(size=(256, ANN_D)).astype(np.float32) * 1.5
    assign = rng.integers(0, len(centers), ANN_N)
    vectors = centers[assign] + rng.normal(size=(ANN_N, ANN_D)).astype(np.float32)
    ids = np.arange(ANN_N, dtype=np.uint64)
    cfg = VectorIndexConfig(column="emb", dim=ANN_D, nlist=128, total_bits=4)
    index = IvfRabitqIndex.train(vectors, ids, cfg, keep_raw=True)
    index.enable_device_cache()
    # HELD-OUT queries: fresh samples from the same mixture (not perturbed
    # dataset vectors, whose true neighbors are trivially themselves) — the
    # recall metric keeps headroom to catch index-quality regressions
    queries = (
        centers[rng.integers(0, len(centers), ANN_Q)]
        + rng.normal(size=(ANN_Q, ANN_D)).astype(np.float32)
    )
    # full probe + deep exact re-rank: the device-resident kernel scans every
    # packed code regardless of nprobe (the probe set only gates inclusion),
    # so probing all clusters costs nothing extra on this path and recall is
    # bounded only by the re-rank shortlist (measured 1.00 at depth 100)
    params = SearchParams(top_k=10, nprobe=128, rerank_depth=100)
    index.batch_search(queries[:256], params)  # warm-up the chunk shape (MAX_Q)
    qps = 0.0
    for _ in range(2):  # best-of-2 damps chip-link variance
        start = time.perf_counter()
        got_ids, _ = index.batch_search(queries, params)
        qps = max(qps, ANN_Q / (time.perf_counter() - start))
    # single-query serving path: requests arrive one at a time from many
    # concurrent clients and ride the micro-batching AnnEndpoint (collect a
    # few ms → ONE fused batch dispatch → fan out) — the TPU serving answer
    # to per-request traffic.  A strictly serial loop on this tunneled dev
    # link measures its ~150 ms round trip, not the framework, so the
    # serving figure is the honest per-request throughput metric here.
    import threading

    from lakesoul_tpu.vector.serving import AnnEndpoint

    index.search(queries[0], params)  # warm the Q=1..8 compiled shapes
    n_clients, per_client = 16, 16
    with AnnEndpoint(index, params, max_batch=256, max_wait_ms=5.0) as ep:
        ep.search(queries[0])  # warm the endpoint path end to end
        start = time.perf_counter()

        def client(lo):
            for q in queries[lo : lo + per_client]:
                ep.search(q, timeout=120)

        threads = [
            threading.Thread(target=client, args=(i * per_client,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        qps_single = n_clients * per_client / (time.perf_counter() - start)
    # realistic-probe leg (VERDICT r3 item 2): the reference asserts
    # recall@10 ≥ 0.5 at nprobe 4–8 (python/tests/vector/test_e2e_glove.py:
    # 182) — quote the same operating point alongside the full-probe figure
    params8 = SearchParams(top_k=10, nprobe=8, rerank_depth=100)
    got_ids8, _ = index.batch_search(queries, params8)

    # recall on a subsample (brute force over 200k x 4096 is the expensive bit)
    sample = rng.choice(ANN_Q, 100, replace=False)
    hits = hits8 = 0
    for s in sample:
        q = queries[s]
        d2 = np.sum((vectors - q) ** 2, axis=1)
        true = set(np.argpartition(d2, 10)[:10].tolist())
        hits += len(true & {int(i) for i in got_ids[s]})
        hits8 += len(true & {int(i) for i in got_ids8[s]})
    return {
        "qps": qps,
        "recall": hits / (len(sample) * 10),
        "qps_serving": qps_single,
        "recall_nprobe8": hits8 / (len(sample) * 10),
    }


def bench_remote() -> tuple[float, float, float]:
    """Latency-injected object store: (cold rows/s, warm rows/s, hit rate)."""
    import fsspec
    from fsspec.implementations.memory import MemoryFileSystem

    class SlowMemFS(MemoryFileSystem):
        """10 ms per GET — a GCS-like RTT on every ranged read."""

        protocol = "slowmem"
        latency = 0.010

        def cat_file(self, *a, **k):
            time.sleep(self.latency)
            return super().cat_file(*a, **k)

        def _open(self, *a, **k):
            if a and isinstance(a[0], str) and "w" not in (k.get("mode") or (a[1] if len(a) > 1 else "rb")):
                time.sleep(self.latency)
            return super()._open(*a, **k)

    if "slowmem" not in fsspec.registry:
        fsspec.register_implementation("slowmem", SlowMemFS, clobber=True)

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.io.object_store import cache_stats

    cache_dir = os.path.join(REPO, ".bench_data", "page_cache")
    shutil.rmtree(cache_dir, ignore_errors=True)
    # the in-memory 'remote' store is process-local: fresh metadata every run
    meta_db = os.path.join(REPO, ".bench_data", "remote_meta.db")
    for suffix in ("", "-wal", "-shm"):
        try:
            os.remove(meta_db + suffix)
        except OSError:
            pass
    opts = {"lakesoul.cache_dir": cache_dir}
    catalog = LakeSoulCatalog(
        "slowmem://bench_wh", storage_options=opts, db_path=meta_db
    )
    name = f"remote_{REMOTE_ROWS}"
    if not catalog.table_exists(name):
        t = catalog.create_table(
            name, _bench_schema(), primary_keys=["id"], hash_bucket_num=4
        )
        for chunk in _chunks(REMOTE_ROWS, seed=2):
            t.write_arrow(chunk)
    t = catalog.table(name)

    def scan_once():
        rows = 0
        start = time.perf_counter()
        for b in t.scan().batch_size(BATCH).to_batches():
            rows += len(b)
        return rows / (time.perf_counter() - start)

    cold = scan_once()
    before = cache_stats(opts)
    warm = scan_once()
    after = cache_stats(opts)
    # hit rate of the WARM scan alone (the cold scan is all misses by design)
    warm_hits = after["hits"] - before["hits"]
    warm_misses = after["misses"] - before["misses"]
    rate = warm_hits / max(1, warm_hits + warm_misses)
    return cold, warm, rate


def _device_reachable(timeout_s: float = 180.0) -> bool:
    """Probe jax backend init on a daemon thread: a wedged TPU tunnel hangs
    jax.devices() forever, which must not leave the driver with no output.
    After a failed probe this PROCESS must never touch jax (the hung import
    holds locks) — the caller re-execs on CPU instead."""
    import subprocess as sp

    code = "import jax; jax.devices(); print('ok')"
    try:
        out = sp.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
            env={**os.environ},
        )
        return out.returncode == 0 and "ok" in out.stdout
    except sp.TimeoutExpired:
        return False


def _acquire_device(
    attempts: int = 3, probe_timeout_s: float = 180.0, backoff_s: float = 60.0
) -> tuple[bool, dict]:
    """Probe-with-backoff (VERDICT r3 item 2): a wedged tunnel sometimes
    recovers, so retry before conceding; the probe record rides into the
    final JSON either way so a CPU fallback is LOUD, not a silent number."""
    info = {
        "attempts": 0,
        "probe_timeout_s": probe_timeout_s,
        "backoff_s": backoff_s,
    }
    start = time.time()
    for i in range(attempts):
        info["attempts"] = i + 1
        if _device_reachable(probe_timeout_s):
            info["wait_s"] = round(time.time() - start, 1)
            return True, info
        if i < attempts - 1:
            time.sleep(backoff_s * (i + 1))
    info["wait_s"] = round(time.time() - start, 1)
    return False, info


def _run_leg(leg: str) -> dict:
    """Execute one leg in a FRESH subprocess and parse its JSON line.

    Isolation matters twice over: (a) the torch-DataLoader baseline forks,
    which must never share a process with an initialized TPU runtime, and
    (b) long-lived tunneled-device processes degrade (transfer throughput
    decays as a session ages), which would punish whichever leg runs last —
    each leg gets a fresh runtime so legs are comparable."""
    import subprocess as sp

    out = sp.run(
        [sys.executable, __file__, "--leg", leg],
        capture_output=True, text=True, timeout=3600,
    )
    last = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode != 0 or not last:
        sys.stderr.write(out.stderr[-2000:])
        raise RuntimeError(f"bench leg {leg!r} failed")
    return json.loads(last[-1])


def run_one_leg(leg: str) -> None:
    if leg == "stream" or leg.startswith("shard_worker:"):
        # pure host legs: never let a stray jax use grab the device
        os.environ["JAX_PLATFORMS"] = "cpu"

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.utils import honor_platform_env

    honor_platform_env()
    warehouse = os.path.join(REPO, ".bench_data")
    if leg == "baseline":
        print(json.dumps({"baseline": bench_torch_baseline(
            os.path.join(warehouse, f"baseline_{N_ROWS}"))}))
        return
    if leg == "baseline_e2e":
        print(json.dumps({"baseline": bench_torch_baseline_e2e(
            os.path.join(warehouse, f"baseline_{N_ROWS}"))}))
        return
    if leg == "remote":
        cold, warm, rate = bench_remote()
        print(json.dumps({"cold": cold, "warm": warm, "hit_rate": rate}))
        return
    if leg == "ann":
        print(json.dumps(bench_ann()))
        return
    if leg == "stream":
        catalog = LakeSoulCatalog(warehouse)
        print(json.dumps(bench_stream_bounded(
            catalog.table(f"bench_stream_{STREAM_ROWS}_lsf"))))
        return
    if leg.startswith("shard_worker:"):
        _, rank, world = leg.split(":")
        catalog = LakeSoulCatalog(warehouse)
        t = catalog.table(f"bench_stream_{STREAM_ROWS}_lsf")
        rows = 0
        for batch in t.scan().shard(int(rank), int(world)).batch_size(262_144).to_batches():
            rows += len(batch)
        print(json.dumps({"rows": rows}))
        return
    catalog = LakeSoulCatalog(warehouse)
    t = catalog.table(f"bench_{N_ROWS}_lsf")
    if leg == "train_hbm":
        print(json.dumps({"rows_per_s": bench_lakesoul(t, epochs=3, device_cache=True)}))
        return
    print(json.dumps({"rows_per_s": bench_lakesoul(t, epochs=5)}))


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--leg":
        run_one_leg(sys.argv[2])
        return
    device_label = os.environ.get("LAKESOUL_BENCH_DEVICE_LABEL")
    if device_label is None:
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            device_label = "cpu"
        else:
            ok, probe = _acquire_device()
            if ok:
                device_label = "tpu"
                # record the probe even on success: 2 retries + minutes of
                # backoff before acquisition IS flaky-tunnel evidence
                os.environ["LAKESOUL_BENCH_PROBE_INFO"] = json.dumps(probe)
            else:
                # wedged tunnel even after retries: produce an honest,
                # clearly-labeled CPU line with the probe record instead of
                # hanging the driver with no output at all
                env = {
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    "LAKESOUL_BENCH_DEVICE_LABEL": "cpu-fallback (device unreachable)",
                    "LAKESOUL_BENCH_PROBE_INFO": json.dumps(probe),
                }
                import subprocess as sp

                raise SystemExit(sp.run([sys.executable, __file__], env=env).returncode)
        os.environ["LAKESOUL_BENCH_DEVICE_LABEL"] = device_label

    # the parent never initializes JAX: table build + compaction are pure
    # catalog work, every measured leg runs in its own fresh process
    from lakesoul_tpu import LakeSoulCatalog

    warehouse = os.path.join(REPO, ".bench_data")
    catalog = LakeSoulCatalog(warehouse)
    t = build_table(catalog)
    ts = build_stream_table(catalog)
    build_baseline_dataset(warehouse)

    # the stream leg must exercise the streaming MERGE, not plain decode: a
    # previously-compacted cached table gets a fresh upsert wave
    if all(len(u.data_files) <= 1 for u in ts.scan().scan_plan()):
        _upsert_wave(ts, seed=13, n_rows=STREAM_ROWS)

    # scale legs first (pure host work; no device needed)
    stream = _run_leg("stream")
    sharded = bench_sharded_loaders(SHARD_WORKERS)

    baseline_host = _run_leg("baseline")["baseline"]
    baseline = _run_leg("baseline_e2e")["baseline"]
    remote = _run_leg("remote")

    # leg 1: live MOR — uncompacted bucket stacks, the merge does real work.
    # A cached table from a previous run was left compacted: re-apply an
    # upsert wave so this leg never silently measures the no-merge workload.
    if all(len(u.data_files) <= 1 for u in t.scan().scan_plan()):
        _upsert_wave(t, seed=3)
    mor = _run_leg("train")["rows_per_s"]
    # leg 2 (headline): steady-state delivery after compaction, the state a
    # served table sits in (the reference's stance too: read throughput
    # comes from bucket parallelism + aggressive compaction, SURVEY §7)
    t.compact()
    value = _run_leg("train")["rows_per_s"]
    hbm = _run_leg("train_hbm")["rows_per_s"]
    ann = _run_leg("ann")
    # vs_baseline compares like for like: both sides deliver rows into the
    # SAME jitted train step on the same chip (BASELINE.md's metric); the
    # host-only decode ratio is kept alongside for continuity with r1/r2.
    # Null when torch isn't available — a fake 1.0 would be
    # indistinguishable from a genuinely measured parity result.
    vs = round(value / baseline, 3) if baseline == baseline else None
    vs_host = round(value / baseline_host, 3) if baseline_host == baseline_host else None
    print(
        json.dumps(
            {
                "metric": "rows/sec/chip into JAX train loop (hash table)",
                "value": round(value, 1),
                "unit": "rows/s/chip",
                "vs_baseline": vs,
                "vs_baseline_host_decode_only": vs_host,
                "device": device_label,
                "mor_uncompacted_rows_per_s": round(mor, 1),
                "hbm_resident_replay_rows_per_s": round(hbm, 1),
                "ann_qps": round(ann["qps"], 1),
                "ann_qps_serving": round(ann["qps_serving"], 1),
                "ann_recall_at_10": round(ann["recall"], 4),
                "ann_recall_at_10_nprobe8": round(ann["recall_nprobe8"], 4),
                "remote_cold_rows_per_s": round(remote["cold"], 1),
                "remote_warm_rows_per_s": round(remote["warm"], 1),
                "cache_hit_rate": round(remote["hit_rate"], 4),
                "stream_rows": stream["rows"],
                "stream_rows_per_s": round(stream["rows_per_s"], 1),
                "stream_peak_rss_mb": stream["peak_rss_mb"],
                "stream_budget_mb": stream["budget_mb"],
                "stream_rss_ceiling_mb": stream["ceiling_mb"],
                "sharded_loaders_rows_per_s": round(sharded["rows_per_s"], 1),
                "sharded_loaders_workers": sharded["workers"],
                # worker processes time-slice the same cores; on a 1-core
                # host the sharded leg proves concurrent shared-store
                # correctness, not scale-out
                "host_cores": os.cpu_count(),
                "device_probe": json.loads(
                    os.environ.get("LAKESOUL_BENCH_PROBE_INFO", "null")
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
