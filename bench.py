"""Benchmark: rows/sec/chip from a hash-partitioned lakehouse table into a
jitted JAX training loop (the north-star metric, BASELINE.json).

Builds (once, cached under .bench_data/) a hash-bucketed PK table with an
upsert wave so merge-on-read is exercised, then measures end-to-end delivery:
scan → MOR merge → rebatch → device_put → jitted MLP train step on the chip.

``vs_baseline`` compares against the REFERENCE pipeline design on the same
host: an identical table written with the reference's parquet settings
(zstd level 1, no dictionary — writer/mod.rs:215-240) consumed by a
torch-DataLoader-style loop (decode → torch tensor collate), i.e. the
LakeSoulDataset→torch stack the reference feeds GPUs with — minus the GPU
copy it would additionally pay.  Our pipeline does strictly more work
(device transfer + a real optimizer step on the chip); the ratio reflects
the TPU-first storage/delivery design (lz4 decode, mmap, zero-copy columns,
double-buffered device_put) against the reference's choices.

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "rows/s/chip", "vs_baseline": R}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np
import pyarrow as pa

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_ROWS = int(os.environ.get("LAKESOUL_BENCH_ROWS", 2_000_000))
UPSERT_FRAC = 0.05
N_FEATURES = 16
BUCKETS = 8
BATCH = int(os.environ.get("LAKESOUL_BENCH_BATCH", 131072))


def _bench_schema():
    fields = [("id", pa.int64())] + [(f"f{i}", pa.float32()) for i in range(N_FEATURES)]
    fields.append(("label", pa.int32()))
    return pa.schema(fields)


def _fill_table(t, schema):
    rng = np.random.default_rng(0)
    chunk = 500_000
    for start in range(0, N_ROWS, chunk):
        n = min(chunk, N_ROWS - start)
        cols = {"id": np.arange(start, start + n, dtype=np.int64)}
        for i in range(N_FEATURES):
            cols[f"f{i}"] = rng.normal(size=n).astype(np.float32)
        cols["label"] = rng.integers(0, 2, n).astype(np.int32)
        t.write_arrow(pa.table(cols, schema=schema))
    # upsert wave → several files per bucket → real merge work on read
    n_up = int(N_ROWS * UPSERT_FRAC)
    upd = rng.choice(N_ROWS, n_up, replace=False).astype(np.int64)
    cols = {"id": upd}
    for i in range(N_FEATURES):
        cols[f"f{i}"] = rng.normal(size=n_up).astype(np.float32)
    cols["label"] = rng.integers(0, 2, n_up).astype(np.int32)
    t.upsert(pa.table(cols, schema=schema))


def build_table(catalog):
    """Our table with TPU-first defaults (lz4)."""
    name = f"bench_{N_ROWS}"
    if catalog.table_exists(name):
        return catalog.table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS
    )
    _fill_table(t, _bench_schema())
    return t


def build_reference_table(catalog):
    """Same data written with the reference's parquet settings (zstd level 1,
    no dictionary) for the baseline pipeline."""
    name = f"bench_ref_{N_ROWS}"
    if catalog.table_exists(name):
        return catalog.table(name)
    t = catalog.create_table(
        name, _bench_schema(), primary_keys=["id"], hash_bucket_num=BUCKETS,
    )

    orig_io_config = t.io_config

    def ref_io_config(**overrides):
        cfg = orig_io_config(**overrides)
        cfg.compression = "zstd"
        cfg.compression_level = 1
        return cfg

    t.io_config = ref_io_config
    _fill_table(t, _bench_schema())
    t.io_config = orig_io_config
    return t


def transform(b):
    x = np.stack([b[f"f{i}"] for i in range(N_FEATURES)], axis=1)
    return {"x": x, "y": b["label"].astype(np.int32)}


def bench_lakesoul(t) -> float:
    import jax
    import jax.numpy as jnp
    import optax

    from lakesoul_tpu.models.mlp import init_mlp_params, mlp_loss

    params = init_mlp_params(jax.random.key(0), N_FEATURES, hidden=256)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    # feature columns transfer as-is (zero-copy from Arrow) and the chip does
    # the stacking inside the jitted step — saves a 1-core host copy per batch
    @jax.jit
    def step(params, opt_state, cols, y):
        x = jnp.stack(cols, axis=1)
        loss, grads = jax.value_and_grad(mlp_loss)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def col_transform(b):
        return {"cols": [b[f"f{i}"] for i in range(N_FEATURES)], "y": b["label"]}

    # warm-up: compile on one batch
    it = iter(t.scan().batch_size(BATCH).to_jax_iter(transform=col_transform))
    first = next(it)
    params, opt_state, loss = step(params, opt_state, first["cols"], first["y"])
    jax.block_until_ready(loss)

    best = 0.0
    for _ in range(2):  # best-of-2 epochs to damp filesystem/cache variance
        rows = 0
        start = time.perf_counter()
        # io_threads=2: lz4 decode releases the GIL, overlapping unit decode
        # with device transfer even on small hosts
        for batch in t.scan().batch_size(BATCH).to_jax_iter(
            transform=col_transform, io_threads=2
        ):
            params, opt_state, loss = step(params, opt_state, batch["cols"], batch["y"])
            rows += BATCH
        jax.block_until_ready(loss)
        dt = time.perf_counter() - start
        best = max(best, rows / dt)
    return best


def bench_torch_baseline(t) -> float:
    """torch-DataLoader-style loop over the same files: pyarrow decode +
    torch tensor collate, a no-op 'step' consuming the tensors."""
    try:
        import torch
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError:
        return float("nan")

    units = t.scan().scan_plan()
    schema = t.schema

    class DS(IterableDataset):
        def __iter__(self):
            import torch.utils.data as tud

            from lakesoul_tpu.io.reader import iter_scan_unit_batches

            # standard DataLoader worker sharding so num_workers parallelism
            # is available to the baseline too
            info = tud.get_worker_info()
            mine = (
                units
                if info is None
                else [u for i, u in enumerate(units) if i % info.num_workers == info.id]
            )
            for u in mine:
                yield from iter_scan_unit_batches(
                    u.data_files, u.primary_keys, batch_size=BATCH, schema=schema,
                    partition_values=u.partition_values,
                )

    def collate(batches):
        b = transform(
            {c: batches[0].column(c).to_numpy(zero_copy_only=False) for c in batches[0].schema.names}
        )
        return torch.from_numpy(b["x"]), torch.from_numpy(b["y"])

    best = 0.0
    # give the baseline its best configuration: in-process decode AND
    # process-worker decode (the standard DataLoader parallelism).  The
    # worker leg is best-effort: it forks, which is only safe because this
    # baseline runs BEFORE any JAX/TPU initialization (see main()).
    for workers in (0, 2):
        try:
            for _ in range(2):
                loader = DataLoader(
                    DS(), batch_size=1, collate_fn=collate, num_workers=workers
                )
                rows = 0
                acc = torch.zeros(())
                start = time.perf_counter()
                for x, y in loader:
                    acc = acc + x.sum() * 0  # consume
                    rows += len(x)
                dt = time.perf_counter() - start
                best = max(best, rows / dt)
        except Exception:
            if workers == 0:
                raise  # in-process leg must work; worker leg may not fork
    return best


def main():
    from lakesoul_tpu import LakeSoulCatalog

    warehouse = os.path.join(REPO, ".bench_data")
    catalog = LakeSoulCatalog(warehouse)
    t = build_table(catalog)
    t_ref = build_reference_table(catalog)

    # baseline first: its DataLoader worker leg forks, which must happen
    # before bench_lakesoul initializes JAX/TPU in this process
    baseline = bench_torch_baseline(t_ref)
    value = bench_lakesoul(t)
    # vs_baseline is null when torch isn't available — a fake 1.0 would be
    # indistinguishable from a genuinely measured parity result
    vs = round(value / baseline, 3) if baseline == baseline else None
    print(
        json.dumps(
            {
                "metric": "rows/sec/chip into JAX train loop (hash table, MOR)",
                "value": round(value, 1),
                "unit": "rows/s/chip",
                "vs_baseline": vs,
            }
        )
    )


if __name__ == "__main__":
    main()
