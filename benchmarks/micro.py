"""Micro-benchmark harness (the reference's ``benches/`` role:
rust/lakesoul-io/benches/{spill_bench,partial_merge,cache_bench}.rs and the
criterion harnesses).  Each leg prints one JSON line with a throughput figure
so regressions are visible run-to-run.

    python benchmarks/micro.py merge      # k-way MOR merge rows/s
    python benchmarks/micro.py scan_stages # per-stage scan breakdown + degeneracy budget
    python benchmarks/micro.py formats    # decode rows/s per physical format
    python benchmarks/micro.py streaming  # bounded-memory streaming merge rows/s
    python benchmarks/micro.py cache      # page-cache hit/miss throughput
    python benchmarks/micro.py spill      # writer auto-flush (spill) + re-merge
    python benchmarks/micro.py meta       # plan 1 partition out of 100k (ms)
    python benchmarks/micro.py pipeline   # serial vs runtime-pipelined scan
    python benchmarks/micro.py chaos      # clean vs faulted-scan degradation
    python benchmarks/micro.py lint       # lakelint wall-time over the package
    python benchmarks/micro.py topology   # SIGKILL→takeover latency (leased compaction)
    python benchmarks/micro.py scanplane  # disaggregated scan: 8 clients, 1→4 workers
    python benchmarks/micro.py freshness  # ingest-to-train SLO under three-role chaos
    python benchmarks/micro.py ann_scale  # sharded ANN plane: 10M x 128d build/recall/QPS
    python benchmarks/micro.py tensor_replay # epoch-1 stream vs epoch-2 device replay (8-dev mesh)
    python benchmarks/micro.py obs_fleet  # fleet obs: 3-role chaos, 1 snapshot, traces, postmortems
    python benchmarks/micro.py fleet      # multi-host trainers: 1→2→4 emulated hosts + kill-a-host
    python benchmarks/micro.py soak       # repeated open→scan→serve→close: flat fd/thread/heap gate
    python benchmarks/micro.py all
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np
import pyarrow as pa

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _emit(leg: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"bench": leg, "value": round(value, 1), "unit": unit, **extra}))


def bench_merge(n_rows: int = 2_000_000, n_files: int = 8) -> None:
    """k-way merge throughput over sorted int64 PK runs (partial_merge.rs
    role): overlapping key ranges, UseLast semantics."""
    from lakesoul_tpu.io.merge import merge_sorted_tables

    rng = np.random.default_rng(0)
    per = n_rows // n_files
    tables = []
    for i in range(n_files):
        keys = np.sort(rng.choice(n_rows * 2, per, replace=False)).astype(np.int64)
        tables.append(pa.table({
            "id": keys,
            "v": rng.normal(size=per),
        }))
    start = time.perf_counter()
    out = merge_sorted_tables(tables, ["id"])
    dt = time.perf_counter() - start
    _emit("merge_i64_kway", n_rows / dt, "rows/s", files=n_files, out_rows=len(out))

    # string keys exercise the bytes loser tree
    s_tables = [
        t.set_column(0, "id", pa.array([f"k{v:012d}" for v in t.column("id").to_pylist()]))
        for t in (tb.slice(0, per // 4) for tb in tables)
    ]
    n_s = sum(len(t) for t in s_tables)
    start = time.perf_counter()
    merge_sorted_tables(s_tables, ["id"])
    dt = time.perf_counter() - start
    _emit("merge_bytes_kway", n_s / dt, "rows/s", files=n_files)


# no-PK degeneracy budget: on a compacted/no-PK scan the non-decode stages
# (merge + fill + rebatch + collate) may cost at most this fraction of the
# decode stage — the machine-checked form of "the plan degenerates to raw
# decode".  The leg FAILS (assert) when the budget is exceeded.  Measured
# steady state is ~0.3-0.4x (merge/fill ~0; collate pays one memcpy only on
# the ~1/8 of windows that span a file boundary); the pre-PR-8
# concat-per-window rebatcher measured well past 1.0x, so 0.5 is a real
# regression tripwire, not a formality.
SCAN_STAGES_BUDGET = float(os.environ.get("LAKESOUL_SCAN_STAGES_BUDGET", 0.5))


def bench_scan_stages(n_rows: int = 4_000_000, n_files: int = 8) -> None:
    """Per-stage scan→train breakdown (decode / merge / fill / rebatch /
    collate / queue / device_put; arxiv 2604.21275's stage-attribution
    discipline) over two legs:

    - ``scan_stages_no_pk``: a plain multi-file LSF table through the full
      loader — the degenerate plan.  Enforces the budget above: the scan
      path may not burn more than ``SCAN_STAGES_BUDGET`` of decode time on
      non-decode stages, so a regression that reintroduces a copy FAILS the
      leg rather than shaving a throughput number nobody notices.
    - ``scan_stages_mor``: the same rows with a PK + 25% upsert wave — the
      real merge-on-read breakdown, published for the record (merge>0 is
      the POINT here; no budget)."""
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.obs.stages import stage_seconds

    rng = np.random.default_rng(0)
    schema = pa.schema([
        ("id", pa.int64()),
        ("label", pa.int32()),
        ("f0", pa.float32()), ("f1", pa.float32()),
        ("f2", pa.float32()), ("f3", pa.float32()),
    ])

    def chunk(lo: int, n: int) -> pa.Table:
        return pa.table({
            "id": np.arange(lo, lo + n, dtype=np.int64),
            "label": rng.integers(0, 10, n).astype(np.int32),
            **{f"f{j}": rng.normal(size=n).astype(np.float32) for j in range(4)},
        }, schema=schema)

    from lakesoul_tpu.obs.stages import queue_seconds_by_consumer

    def drive(t, consumer: str) -> tuple[int, float, dict, dict]:
        before = stage_seconds()
        q_before = queue_seconds_by_consumer()
        start = time.perf_counter()
        rows = 0
        for b in t.scan().batch_size(65_536).to_jax_iter(
            device_put=False, drop_remainder=False, consumer=consumer
        ):
            rows += len(b["id"])
        wall = time.perf_counter() - start
        after = stage_seconds()
        q_after = queue_seconds_by_consumer()
        q_delta = {
            k: round(v - q_before.get(k, 0.0), 4)
            for k, v in q_after.items()
            if v - q_before.get(k, 0.0) > 0
        }
        return rows, wall, {k: after[k] - before[k] for k in after}, q_delta

    def publish(leg: str, rows: int, wall: float, stages: dict, **extra) -> dict:
        total = sum(stages.values()) or 1.0
        breakdown = {
            k: {"s": round(v, 4), "pct": round(100.0 * v / total, 1)}
            for k, v in stages.items()
        }
        _emit(leg, rows / wall, "rows/s", stages=breakdown, **extra)
        return breakdown

    per = n_rows // n_files
    with tempfile.TemporaryDirectory() as d:
        catalog = LakeSoulCatalog(
            os.path.join(d, "wh"), db_path=os.path.join(d, "meta.db")
        )
        plain = catalog.create_table(
            "plain", schema, properties={"lakesoul.file_format": "lsf"}
        )
        for i in range(n_files):
            plain.write_arrow(chunk(i * per, per))
        # best-of-3 on the RATIO: the stages sum to ~100 ms here, so one
        # scheduler hiccup can double a stage; transient noise only ever
        # inflates the ratio, so the min across repeats is the achievable
        # degeneracy — what the budget is about
        best = None
        for _ in range(3):
            rows, wall, stages, q_split = drive(plain, "no_pk")
            assert rows == n_rows, (rows, n_rows)
            overhead = (
                stages["merge"] + stages["fill"]
                + stages["rebatch"] + stages["collate"]
            )
            frac = overhead / max(stages["decode"], 1e-9)
            if best is None or frac < best[0]:
                best = (frac, rows, wall, stages, overhead, q_split)
        frac, rows, wall, stages, overhead, q_split = best
        publish(
            "scan_stages_no_pk", rows, wall, stages,
            overhead_over_decode=round(frac, 3), budget=SCAN_STAGES_BUDGET,
            queue_by_consumer=q_split,
        )
        assert frac <= SCAN_STAGES_BUDGET, (
            f"no-PK degeneracy violated: (merge+fill+rebatch+collate)="
            f"{overhead:.3f}s is {frac:.2f}x decode "
            f"({stages['decode']:.3f}s) — budget {SCAN_STAGES_BUDGET}"
        )

        mor = catalog.create_table(
            "mor", schema, primary_keys=["id"], hash_bucket_num=2,
            properties={"lakesoul.file_format": "lsf"},
        )
        for i in range(n_files):
            mor.write_arrow(chunk(i * per, per))
        ids = rng.choice(n_rows, n_rows // 4, replace=False).astype(np.int64)
        wave = pa.table({
            "id": np.sort(ids),
            "label": rng.integers(0, 10, len(ids)).astype(np.int32),
            **{f"f{j}": rng.normal(size=len(ids)).astype(np.float32) for j in range(4)},
        }, schema=schema)
        mor.upsert(wave)
        rows, wall, stages, q_split = drive(mor, "mor")
        assert rows == n_rows, (rows, n_rows)
        publish(
            "scan_stages_mor", rows, wall, stages, upsert_frac=0.25,
            queue_by_consumer=q_split,
        )


def bench_formats(n_rows: int = 2_000_000) -> None:
    """Decode throughput per registered physical format (file_format.rs role;
    LSF is the Vortex-role fast-decode format)."""
    from lakesoul_tpu.io.config import IOConfig
    from lakesoul_tpu.io.formats import format_by_name

    rng = np.random.default_rng(0)
    cols = {"id": np.arange(n_rows, dtype=np.int64)}
    for i in range(8):
        cols[f"f{i}"] = rng.normal(size=n_rows).astype(np.float32)
    t = pa.table(cols)
    with tempfile.TemporaryDirectory() as d:
        for name, ext in (("parquet", ".parquet"), ("arrow", ".arrow"), ("lsf", ".lsf")):
            fmt = format_by_name(name)
            path = os.path.join(d, f"t{ext}")
            cfg = IOConfig(compression="lz4")
            start = time.perf_counter()
            size = fmt.write_table(t, path, config=cfg)
            wdt = time.perf_counter() - start
            best = 1e9
            for _ in range(3):
                start = time.perf_counter()
                got = fmt.read_table(path)
                best = min(best, time.perf_counter() - start)
            assert got.num_rows == n_rows
            _emit(
                f"decode_{name}", n_rows / best, "rows/s",
                write_rows_per_s=round(n_rows / wdt, 1), file_mb=round(size / 1e6, 1),
            )


def bench_cache(n_objects: int = 64, obj_kb: int = 256) -> None:
    """Read-through page cache throughput, cold vs warm (cache_bench.rs
    role), over a latency-injected store."""
    import fsspec
    from fsspec.implementations.memory import MemoryFileSystem

    class SlowFS(MemoryFileSystem):
        protocol = "slowmicro"
        latency = 0.005

        def cat_file(self, *a, **k):
            time.sleep(self.latency)
            return super().cat_file(*a, **k)

    if "slowmicro" not in fsspec.registry:
        fsspec.register_implementation("slowmicro", SlowFS, clobber=True)
    from lakesoul_tpu.io.object_store import cache_stats, filesystem_for

    mem = fsspec.filesystem("slowmicro")
    blob = os.urandom(obj_kb * 1024)
    # MemoryFileSystem only strips its own "memory://" prefix: custom-protocol
    # keys must be written in the same URL form they are read with
    for i in range(n_objects):
        mem.pipe_file(f"slowmicro://micro/o{i}", blob)
    cache_dir = tempfile.mkdtemp(prefix="lsf_cache_bench")
    opts = {"lakesoul.cache_dir": cache_dir}
    try:
        def sweep():
            total = 0
            start = time.perf_counter()
            for i in range(n_objects):
                fs, p = filesystem_for(f"slowmicro://micro/o{i}", opts)
                total += len(fs.cat_file(p))
            return total / (time.perf_counter() - start)

        cold = sweep()
        warm = sweep()
        stats = cache_stats(opts)
        _emit(
            "page_cache", warm / 1e6, "MB/s warm",
            cold_mb_per_s=round(cold / 1e6, 1), hit_rate=round(stats["hit_rate"], 4),
        )
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def bench_spill(n_rows: int = 1_000_000) -> None:
    """Writer byte-budget auto-flush (sorted spill runs) + bounded streaming
    re-merge (spill_bench.rs role)."""
    from lakesoul_tpu import LakeSoulCatalog

    with tempfile.TemporaryDirectory() as wh:
        catalog = LakeSoulCatalog(wh)
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table(
            "spill", schema, primary_keys=["id"], hash_bucket_num=1,
            properties={"lakesoul.memory_budget_bytes": str(8 << 20)},
        )
        rng = np.random.default_rng(0)
        ids = rng.permutation(n_rows).astype(np.int64)
        vals = rng.normal(size=n_rows)
        start = time.perf_counter()
        # several commits of overlapping sorted runs: the staged files ARE
        # the spill runs; the bounded streaming merger re-combines them
        step = n_rows // 8
        for lo in range(0, n_rows, step):
            t.write_arrow(pa.table(
                {"id": ids[lo:lo + step], "v": vals[lo:lo + step]}, schema=schema
            ))
        wdt = time.perf_counter() - start
        files = [f for u in t.scan().scan_plan() for f in u.data_files]
        start = time.perf_counter()
        rows = sum(len(b) for b in t.scan().batch_size(65_536).to_batches())
        rdt = time.perf_counter() - start
        assert rows == n_rows
        _emit(
            "spill_write", n_rows / wdt, "rows/s",
            runs=len(files), read_rows_per_s=round(n_rows / rdt, 1),
        )


def bench_streaming_merge(n_rows: int = 2_000_000, n_files: int = 8) -> None:
    """Bounded-memory k-way streaming merge (sorted_stream_merger.rs role),
    parquet vs LSF streams: per-stream batch DECODE dominates this path
    (~87% of wall on parquet), so the native format's cheap decode is the
    lever on streaming MOR throughput."""
    from lakesoul_tpu.io.formats import format_by_name
    from lakesoul_tpu.io.streaming_merge import iter_merged_windows

    rng = np.random.default_rng(0)
    per = n_rows // n_files
    with tempfile.TemporaryDirectory() as d:
        schema = None
        runs = []
        for i in range(n_files):
            keys = np.sort(rng.choice(n_rows * 2, per, replace=False)).astype(np.int64)
            t = pa.table({
                "id": keys,
                "v": rng.normal(size=per),
                "f0": rng.normal(size=per).astype(np.float32),
                "f1": rng.normal(size=per).astype(np.float32),
            })
            schema = t.schema
            runs.append(t)
        for name, ext in (("parquet", ".parquet"), ("lsf", ".lsf")):
            fmt = format_by_name(name)
            files = []
            for i, t in enumerate(runs):
                p = os.path.join(d, f"run{i}{ext}")
                fmt.write_table(t, p)
                files.append(p)
            start = time.perf_counter()
            rows = sum(
                len(w)
                for w in iter_merged_windows(files, ["id"], file_schema=schema)
            )
            dt = time.perf_counter() - start
            _emit(f"streaming_merge_{name}", n_rows / dt, "rows/s in",
                  files=n_files, out_rows=rows)


def bench_meta_prune(n_partitions: int = 100_000) -> None:
    """Partition-filter pushdown at scale: plan one partition out of
    ``n_partitions`` (the reference's 3.0 headline claims ≈50 ms against a
    table with millions of partitions on PostgreSQL;
    website/blog/2025-09-05-lakesoul-3.0.0-release/index.md:8).  Metadata
    only — commits are synthesized through the client with fake file paths,
    which is exactly what that claim measures."""
    from lakesoul_tpu.meta.client import MetaDataClient
    from lakesoul_tpu.meta.entity import CommitOp, DataFileOp

    with tempfile.TemporaryDirectory() as d:
        client = MetaDataClient(db_path=f"{d}/meta.db")
        schema = pa.schema([("id", pa.int64()), ("day", pa.string()), ("v", pa.float64())])
        info = client.create_table(
            "wide", f"{d}/wide", schema, primary_keys=["id"],
            range_partitions=["day"],
        )
        start = time.perf_counter()
        # batched commits: 1000 partitions per commit_data_files call; file
        # names carry the trailing _NNNN hash-bucket suffix the planner
        # extracts (client.extract_hash_bucket_id)
        step = 1000
        for lo in range(0, n_partitions, step):
            files = {
                f"day=d{p:07d}": [
                    DataFileOp(path=f"{d}/wide/day=d{p:07d}/part-0_0000.lsf", size=1024)
                ]
                for p in range(lo, min(lo + step, n_partitions))
            }
            client.commit_data_files(info, files, CommitOp.APPEND)
        ingest_dt = time.perf_counter() - start

        probe = f"d{(n_partitions * 2 // 5):07d}"  # an existing mid-table partition
        start = time.perf_counter()
        units = client.get_scan_plan_partitions("wide", {"day": probe})
        one_dt = time.perf_counter() - start
        assert len(units) >= 1
        start = time.perf_counter()
        all_units = client.get_scan_plan_partitions("wide")
        all_dt = time.perf_counter() - start
        assert len(all_units) == n_partitions
        _emit(
            "meta_prune_one_of_n", one_dt * 1e3, "ms",
            n_partitions=n_partitions,
            full_plan_ms=round(all_dt * 1e3, 1),
            ingest_partitions_per_s=round(n_partitions / ingest_dt, 1),
        )


def bench_pipeline_scan(
    n_rows: int = 800_000, n_files: int = 8, latency_s: float = 0.04
) -> None:
    """Serial vs runtime-pipelined scan of one multi-file (multi-row-group)
    table on a latency-injected object store — the overlap win the
    lakesoul_tpu/runtime/ subsystem exists for: with one worker every file
    GET serializes; with the pool, fetch+decode of all files overlap (and
    MOR-free postprocess overlaps decode).  The batch streams must be
    BYTE-IDENTICAL between modes (the pipeline's ordered-merge guarantee);
    this leg asserts it."""
    import fsspec
    from fsspec.implementations.memory import MemoryFileSystem

    class SlowScanFS(MemoryFileSystem):
        protocol = "slowscan"
        latency = latency_s

        def _open(self, *a, **k):
            time.sleep(SlowScanFS.latency)  # per-object GET latency
            return super()._open(*a, **k)

        def cat_file(self, *a, **k):
            time.sleep(SlowScanFS.latency)
            return super().cat_file(*a, **k)

    if "slowscan" not in fsspec.registry:
        fsspec.register_implementation("slowscan", SlowScanFS, clobber=True)

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.runtime import shutdown_pool

    def set_pool(n: int) -> None:
        shutdown_pool()
        os.environ["LAKESOUL_RUNTIME_THREADS"] = str(n)

    prev_threads = os.environ.get("LAKESOUL_RUNTIME_THREADS")
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        catalog = LakeSoulCatalog(
            "slowscan://pipe-bench/wh", db_path=os.path.join(d, "meta.db")
        )
        schema = pa.schema(
            [("id", pa.int64()), ("f0", pa.float32()), ("f1", pa.float32())]
        )
        t = catalog.create_table("scanme", schema)
        per = n_rows // n_files
        for i in range(n_files):
            t.write_arrow(pa.table({
                "id": np.arange(i * per, (i + 1) * per),
                "f0": rng.normal(size=per).astype(np.float32),
                "f1": rng.normal(size=per).astype(np.float32),
            }, schema=schema))
        try:
            set_pool(1)
            start = time.perf_counter()
            serial = list(t.scan().batch_size(65_536).to_batches())
            serial_dt = time.perf_counter() - start

            set_pool(8)
            start = time.perf_counter()
            piped = list(t.scan().batch_size(65_536).to_batches(num_threads=8))
            piped_dt = time.perf_counter() - start
        finally:
            shutdown_pool()
            if prev_threads is None:
                os.environ.pop("LAKESOUL_RUNTIME_THREADS", None)
            else:
                os.environ["LAKESOUL_RUNTIME_THREADS"] = prev_threads

        # determinism contract: byte-identical batch order across modes
        assert len(serial) == len(piped), (len(serial), len(piped))
        for a, b in zip(serial, piped):
            assert a.equals(b)
        rows = sum(len(b) for b in serial)
        assert rows == n_rows
        _emit(
            "pipeline_scan", n_rows / piped_dt, "rows/s",
            serial_rows_per_s=round(n_rows / serial_dt, 1),
            speedup=round(serial_dt / piped_dt, 2),
            files=n_files, fetch_latency_ms=latency_s * 1e3,
        )


def bench_chaos(n_rows: int = 400_000, n_files: int = 8, p: float = 0.3) -> None:
    """Clean vs chaos-faulted scan throughput (the resilience layer's cost
    leg): the same table is scanned twice, the second time with p=0.3
    transient faults injected into every object-store open/info call
    (runtime/faults.py `flaky` kind).  The retry policy must absorb every
    fault — the leg asserts the batch streams are BYTE-IDENTICAL — and the
    published `degradation` ratio (faulted/clean throughput) is the price
    of absorption.  Retry counters ride in the obs delta."""
    import numpy as np

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.runtime import faults

    saved = {
        k: os.environ.get(k)
        for k in ("LAKESOUL_RETRY_MAX_ATTEMPTS", "LAKESOUL_RETRY_BASE_S",
                  "LAKESOUL_RETRY_CAP_S")
    }
    os.environ.update({
        "LAKESOUL_RETRY_MAX_ATTEMPTS": "10",
        "LAKESOUL_RETRY_BASE_S": "0.001",
        "LAKESOUL_RETRY_CAP_S": "0.01",
    })
    rng = np.random.default_rng(0)
    try:
        with tempfile.TemporaryDirectory() as d:
            catalog = LakeSoulCatalog(
                "memory://chaos-bench/wh", db_path=os.path.join(d, "meta.db")
            )
            schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
            t = catalog.create_table("chaos", schema)
            per = n_rows // n_files
            for i in range(n_files):
                t.write_arrow(pa.table({
                    "id": np.arange(i * per, (i + 1) * per),
                    "v": rng.normal(size=per),
                }, schema=schema))

            start = time.perf_counter()
            clean = list(t.scan().batch_size(65_536).to_batches())
            clean_dt = time.perf_counter() - start

            faults.clear()
            faults.install(f"object_store.open:{p}:flaky")
            faults.install(f"object_store.info:{p}:flaky")
            try:
                start = time.perf_counter()
                faulted = list(t.scan().batch_size(65_536).to_batches())
                faulted_dt = time.perf_counter() - start
            finally:
                faults.clear()

            assert len(clean) == len(faulted)
            for a, b in zip(clean, faulted):
                assert a.equals(b), "chaos run diverged from the clean scan"
            _emit(
                "chaos_scan", n_rows / faulted_dt, "rows/s",
                clean_rows_per_s=round(n_rows / clean_dt, 1),
                degradation=round((n_rows / faulted_dt) / (n_rows / clean_dt), 3),
                fault_p=p, files=n_files,
            )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_lint() -> None:
    """Analyzer wall-time over the whole package (CI-gate cost leg: the
    lint gate runs on every PR, so its cost is tracked next to the perf
    legs; target < 10 s for all 40 rules INCLUDING the project call-graph
    build the interprocedural rules share, the device-index/taint passes
    of the JAX/TPU pack, the thread-root/lockset passes of the
    concurrency pack, the filesystem-op index of the durability pack,
    the SQL-site/taint passes of the isolation pack, and the shared
    container/thread/child lifecycle index of the boundedness pack).  Per-rule wall milliseconds ride along in the leg
    JSON so a future rule regression is attributable to ONE rule id — note
    a shared index (call graph, device index, thread roots) bills to the
    first rule that builds it."""
    from lakesoul_tpu.analysis import run_repo
    from lakesoul_tpu.analysis.engine import Project, Module, package_root

    # parse+rule cost is dominated by file IO the first time; report the
    # steady-state of a fresh run, which is what CI pays
    timings: dict = {}
    start = time.perf_counter()
    findings, _ = run_repo(timings=timings)
    dt = time.perf_counter() - start
    n_files = sum(
        len([f for f in files if f.endswith(".py")])
        for _, _, files in os.walk(os.path.join(REPO, "lakesoul_tpu"))
    )
    # the call-graph build in isolation, so a regression is attributable
    project = Project(root=package_root().parent)
    for p in sorted(package_root().rglob("*.py")):
        mod = Module.load(p, package_root().parent)
        if mod is not None:
            project.modules.append(mod)
    start = time.perf_counter()
    graph = project.callgraph()
    cg_dt = time.perf_counter() - start
    _emit(
        "lint_package", dt * 1e3, "ms",
        files=n_files, findings=len(findings),
        files_per_s=round(n_files / dt, 1),
        callgraph_ms=round(cg_dt * 1e3, 1),
        rules=len(timings),
        rule_ms={
            rule_id: round(seconds * 1e3, 1)
            for rule_id, seconds in sorted(
                timings.items(), key=lambda kv: -kv[1]
            )
        },
        **{f"callgraph_{k}": v for k, v in graph.stats().items()},
    )
    assert dt < 10.0, f"lint gate took {dt:.1f}s — budget is 10s"


def bench_topology(
    n_versions: int = 12, rows_per_commit: int = 2000, ttl_s: float = 2.0
) -> None:
    """Multi-process failover cost leg: how long a partition whose leased
    compactor was SIGKILLed mid-job waits until a peer service completes
    it (kill → peer-commits latency, dominated by one lease TTL), and the
    proof that the failover path changes NOTHING about the data — the
    failover-compacted table scans byte-identical to a clean-compacted
    copy of the same commit sequence.  ``LAKESOUL_RETRY_SEED`` pins every
    backoff schedule so the run reproduces."""
    import signal
    import subprocess

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.compaction.service import LeasedCompactionService
    from lakesoul_tpu.meta.entity import CommitOp

    schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
    rng = np.random.default_rng(0)
    batches = [
        pa.table({
            "id": np.arange(rows_per_commit, dtype=np.int64),
            "v": rng.normal(size=rows_per_commit),
        }, schema=schema)
        for _ in range(n_versions)
    ]

    def build(wh: str, db: str):
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", schema, primary_keys=["id"], hash_bucket_num=1
        )
        for b in batches:
            t.upsert(b)
        return catalog, t

    def sorted_ipc(table: pa.Table) -> bytes:
        import io

        out = table.sort_by("id").combine_chunks()
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, out.schema) as w:
            w.write_table(out)
        return sink.getvalue()

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "LAKESOUL_RETRY_SEED": "7",
        "LAKESOUL_FAULTS": "compaction.leased_job:1:hang:300",
    })
    with tempfile.TemporaryDirectory() as d:
        # clean run: same commits, in-process leased compaction
        cat1, t1 = build(os.path.join(d, "wh1"), os.path.join(d, "m1.db"))
        LeasedCompactionService(
            cat1, lease_ttl_s=30, poll_interval_s=0.01
        ).poll_once()
        clean_bytes = sorted_ipc(t1.refresh().to_arrow())

        # failover run: victim service process hangs inside the leased job
        wh2, db2 = os.path.join(d, "wh2"), os.path.join(d, "m2.db")
        cat2, t2 = build(wh2, db2)
        store = cat2.client.store
        proc = subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.compaction",
             "--warehouse", wh2, "--db-path", db2,
             "--lease-ttl-s", str(ttl_s), "--poll-s", "0.1",
             "--service-id", "victim"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        key = f"compaction/{t2.info.table_id}/-5"
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if store.get_lease(key) is not None:
                    break
                time.sleep(0.05)
            assert store.get_lease(key) is not None, "victim never leased"
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10.0)
        killed_at = time.monotonic()
        peer = LeasedCompactionService(
            cat2, service_id="peer", lease_ttl_s=ttl_s, poll_interval_s=0.1
        )
        drain_deadline = time.monotonic() + 60.0
        while store.get_compaction_candidates():
            if time.monotonic() > drain_deadline:
                raise RuntimeError(
                    "peer failed to drain compaction candidates within 60s: "
                    f"{store.get_compaction_candidates()}"
                )
            peer.poll_once()
            time.sleep(0.05)
        takeover_ms = (time.monotonic() - killed_at) * 1e3

        head = store.get_latest_partition_info(t2.info.table_id, "-5")
        assert head.commit_op == CommitOp.COMPACTION
        assert head.expression == "fence=2", head.expression
        failover_bytes = sorted_ipc(t2.refresh().to_arrow())
        assert failover_bytes == clean_bytes, (
            "failover-compacted scan diverged from the clean run"
        )
        _emit(
            "topology_takeover", takeover_ms, "ms",
            lease_ttl_s=ttl_s,
            takeovers=peer.stats.takeovers,
            byte_identical=True,
            rows=n_versions * rows_per_commit,
        )


# the scanplane leg's scaling gate: aggregate client rows/s must grow at
# least this factor from 1 → 4 worker processes (near-linear modulo fixed
# session/connect overheads); the leg FAILS below it
SCANPLANE_SCALE_FLOOR = float(os.environ.get("LAKESOUL_SCANPLANE_SCALE_FLOOR", 3.0))


def bench_scanplane(
    n_rows: int = 6_000_000, n_buckets: int = 16, n_clients: int = 8,
    ttl_s: float = 2.0, store_latency_s: float = 0.35,
) -> None:
    """Disaggregated scan plane at fleet shape (ROADMAP item 3): ≥8
    concurrent trainer-client PROCESSES stream one MOR table's shards
    through the Flight gateway while decode/merge workers run as separate
    leased processes.  Worker range production carries an injected
    per-range store latency (``scanplane.range:1:delay`` — the same
    latency-emulation discipline as the ``pipeline``/``cache`` legs: the
    deployment this layer scales is remote object storage, where range
    fetch+decode is latency-bound, not host-memcpy-bound).  Three claims,
    all asserted:

    - **byte identity**: every client's stream sha256 equals the
      single-process ``scan.shard(rank, world)`` scan of the same table;
    - **scaling**: aggregate client rows/s grows ≥``SCANPLANE_SCALE_FLOOR``
      from 1 → 4 worker processes (the handoff-bound single process was
      the queue-stage wall PR 8 left standing — this leg is the scale-out
      answer to it);
    - **exactly-once under SIGKILL**: a worker killed while HOLDING a
      range lease delays that range by ≤ one lease TTL (a peer takes
      over, fencing token bumped), and every client still completes with
      the same shas — no duplicate, no missing batches."""
    import signal
    import subprocess
    import threading

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.scanplane import spool as sp
    from lakesoul_tpu.scanplane.delivery import ScanPlaneDelivery
    from lakesoul_tpu.scanplane.session import ScanSession
    from lakesoul_tpu.service.flight import LakeSoulFlightServer

    rng = np.random.default_rng(0)
    schema = pa.schema([
        ("id", pa.int64()), ("label", pa.int32()),
        ("f0", pa.float32()), ("f1", pa.float32()),
        ("f2", pa.float32()), ("f3", pa.float32()),
    ])
    batch_size = 65_536

    def child_env() -> dict:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
            "LAKESOUL_RETRY_SEED": "7",
        })
        return env

    def spawn_worker(wh, db, spool, worker_id, **extra_env):
        env = child_env()
        env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.scanplane", "worker",
             "--warehouse", wh, "--db-path", db, "--spool", spool,
             "--lease-ttl-s", str(ttl_s), "--poll-s", "0.05",
             "--worker-id", worker_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True,
        )

    def spawn_client(location, rank):
        return subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.scanplane", "drive",
             "--location", location, "--table", "t",
             "--batch-size", str(batch_size),
             "--rank", str(rank), "--world", str(n_clients)],
            env=child_env(), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )

    def run_fleet(catalog, wh, db, n_workers, spool, *, chaos=False):
        """One fleet run; returns (outputs by rank, wall_s, takeover_s).

        Order matters for a clean measurement: clients launch FIRST (they
        connect, create the session, and park on the empty spool), then
        the workers; the wall clock runs from all-workers-ready to the
        last client's final byte — fleet delivery throughput, not python
        interpreter boot."""
        os.makedirs(spool, exist_ok=True)
        delivery = ScanPlaneDelivery(catalog, spool, wait_s=180)
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", scanplane=delivery
        )
        threading.Thread(target=server.serve, daemon=True).start()
        location = f"grpc://127.0.0.1:{server.port}"
        workers = []
        takeover_s = None
        try:
            clients = [spawn_client(location, r) for r in range(n_clients)]
            # the first connected client publishes the session manifest —
            # its appearance means the fleet is parked and waiting
            session = ScanSession.plan(
                catalog, {"table": "t", "batch_size": batch_size}
            )
            manifest = os.path.join(spool, session.session_id, "manifest.json")
            deadline = time.monotonic() + 120.0
            while not os.path.exists(manifest):
                assert time.monotonic() < deadline, "no client connected"
                time.sleep(0.02)
            victim = None
            if chaos:
                victim = spawn_worker(
                    wh, db, spool, "victim",
                    LAKESOUL_FAULTS="scanplane.range:1:hang:300",
                )
                workers.append(victim)
                workers.append(spawn_worker(wh, db, spool, "peer"))
            else:
                workers.extend(
                    spawn_worker(
                        wh, db, spool, f"w{i}",
                        LAKESOUL_FAULTS=(
                            f"scanplane.range:1:delay:{store_latency_s}"
                        ),
                    )
                    for i in range(n_workers)
                )
            for w in workers:
                w.stdout.readline()  # readiness line
            fleet_t0 = time.time()
            if chaos:
                # watch the lease table until the victim HOLDS a range,
                # then SIGKILL it
                store = catalog.client.store
                keys = [
                    f"scanplane/{session.session_id}/{i}"
                    for i in range(len(session.ranges))
                ]
                held = None
                deadline = time.monotonic() + 120.0
                while held is None and time.monotonic() < deadline:
                    for k in keys:
                        lease = store.get_lease(k)
                        if lease is not None and lease.holder == "victim":
                            held = k
                            break
                    time.sleep(0.02)
                assert held is not None, "victim never leased a range"
                victim.send_signal(signal.SIGKILL)
                victim.wait(10.0)
                killed = time.monotonic()
                index = int(held.rsplit("/", 1)[-1])
                sdir = session.dir(spool)
                while not sp.range_ready(sdir, index):
                    assert time.monotonic() - killed < 60.0, "no takeover"
                    time.sleep(0.02)
                takeover_s = time.monotonic() - killed
                assert takeover_s < ttl_s + 4.0, takeover_s
                # the fencing trail proves the takeover: the surviving peer
                # produced the victim's range under a BUMPED token (exact
                # value depends on how many held/fenced cycles the two
                # workers interleaved before the kill; the controlled
                # single-step trail is pinned in test_scanplane_chaos.py)
                side = sp.read_sidecar(sdir, index)
                assert side["worker"] == "peer" and side["fence"] >= 2, side
            outputs = {}
            for rank, c in enumerate(clients):
                out, err = c.communicate(timeout=600)
                lines = [ln for ln in out.splitlines() if ln.startswith("{")]
                assert c.returncode == 0 and lines, err[-2000:]
                outputs[rank] = json.loads(lines[-1])
            wall = max(o["ended_unix"] for o in outputs.values()) - fleet_t0
            return outputs, wall, takeover_s
        finally:
            for w in workers:
                if w.poll() is None:
                    w.terminate()
            for w in workers:
                try:
                    w.wait(10.0)
                except subprocess.TimeoutExpired:
                    w.kill()
            server.shutdown()

    with tempfile.TemporaryDirectory() as d:
        wh, db = os.path.join(d, "wh"), os.path.join(d, "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", schema, primary_keys=["id"], hash_bucket_num=n_buckets,
            properties={"lakesoul.file_format": "lsf"},
        )
        t.write_arrow(pa.table({
            "id": np.arange(n_rows, dtype=np.int64),
            "label": rng.integers(0, 10, n_rows).astype(np.int32),
            **{f"f{j}": rng.normal(size=n_rows).astype(np.float32)
               for j in range(4)},
        }, schema=schema))
        ids = np.sort(
            rng.choice(n_rows, n_rows // 4, replace=False)
        ).astype(np.int64)
        t.upsert(pa.table({
            "id": ids,
            "label": rng.integers(0, 10, len(ids)).astype(np.int32),
            **{f"f{j}": rng.normal(size=len(ids)).astype(np.float32)
               for j in range(4)},
        }, schema=schema))

        # single-process baseline shas: the byte-identity oracle per rank
        import hashlib

        def shard_sha(rank: int) -> tuple[str, int]:
            digest = hashlib.sha256()
            rows = 0
            for b in (
                t.scan().batch_size(batch_size)
                .shard(rank, n_clients).to_batches()
            ):
                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, b.schema) as w:
                    w.write_batch(b)
                digest.update(sink.getvalue().to_pybytes())
                rows += b.num_rows
            return digest.hexdigest(), rows

        oracle = {r: shard_sha(r) for r in range(n_clients)}
        total_rows = sum(rows for _, rows in oracle.values())

        # spool on tmpfs when available: the shm fast path is then literal
        # shared memory; each run gets a FRESH spool so production repeats
        spool_base = "/dev/shm" if os.path.isdir("/dev/shm") else d
        rates = {}
        for n_workers in (1, 4):
            spool = os.path.join(
                tempfile.mkdtemp(prefix="lss-", dir=spool_base)
            )
            try:
                outputs, wall, _ = run_fleet(catalog, wh, db, n_workers, spool)
                for rank, out in outputs.items():
                    sha, rows = oracle[rank]
                    assert out["rows"] == rows, (rank, out["rows"], rows)
                    assert out["sha256"] == sha, f"rank {rank} diverged"
                rates[n_workers] = total_rows / wall
            finally:
                shutil.rmtree(spool, ignore_errors=True)
        scale = rates[4] / rates[1]

        # chaos variant: 2 workers, SIGKILL the one holding a lease
        spool = os.path.join(tempfile.mkdtemp(prefix="lss-", dir=spool_base))
        try:
            outputs, chaos_wall, takeover_s = run_fleet(
                catalog, wh, db, 2, spool, chaos=True
            )
            for rank, out in outputs.items():
                sha, rows = oracle[rank]
                # exactly-once through the kill: same rows, same bytes
                assert out["rows"] == rows and out["sha256"] == sha, rank
        finally:
            shutil.rmtree(spool, ignore_errors=True)

        _emit(
            "scanplane_fleet", rates[4], "rows/s",
            clients=n_clients,
            rows=total_rows,
            workers_1_rows_per_s=round(rates[1], 1),
            workers_4_rows_per_s=round(rates[4], 1),
            scale_1_to_4=round(scale, 2),
            scale_floor=SCANPLANE_SCALE_FLOOR,
            byte_identical=True,
            chaos_takeover_s=round(takeover_s, 2),
            chaos_exactly_once=True,
            lease_ttl_s=ttl_s,
            emulated_store_latency_s=store_latency_s,
        )
        assert scale >= SCANPLANE_SCALE_FLOOR, (
            f"scan plane scaled only {scale:.2f}x from 1→4 workers —"
            f" floor is {SCANPLANE_SCALE_FLOOR}x"
        )


# freshness-leg SLO gates (env-tunable for slow boxes): the leg FAILS if
# the p99 commit-to-visible latency or the sustained delivery rate misses
FRESHNESS_SLO_S = float(os.environ.get("LAKESOUL_FRESHNESS_SLO_S", 10.0))
FRESHNESS_TPUT_FLOOR = float(
    os.environ.get("LAKESOUL_FRESHNESS_THROUGHPUT_FLOOR", 100.0)
)


def bench_freshness(
    commits: int = 15, rows_per_commit: int = 400, ttl_s: float = 2.0,
    fault_p: float = 0.3,
) -> None:
    """The always-fresh-lakehouse leg (ROADMAP item 4): three REAL roles
    against one warehouse — ``python -m lakesoul_tpu.freshness writer``
    streaming checkpointed CDC upserts, the real ``python -m
    lakesoul_tpu.compaction`` leased service (SIGKILLed mid-leased-job,
    with a peer taking over under the fencing trail), and a follower
    trainer in THIS process under p=0.3 flaky-store + flaky-poll faults.
    Publishes ``freshness_seconds`` p50/p99 (commit-to-visible, measured
    at the follower's consumer hand-off) and sustained rows/s, and FAILS
    unless both declared SLOs hold AND delivery exactly matches the
    writer's oracle.  ``LAKESOUL_RETRY_SEED`` pins every backoff schedule
    so the run reproduces."""
    import signal
    import subprocess
    import threading

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.freshness import FreshFollower, SloMonitor, ThroughputSlo
    from lakesoul_tpu.freshness.__main__ import oracle_sha
    from lakesoul_tpu.meta.entity import CommitOp, now_millis
    from lakesoul_tpu.runtime import faults
    from lakesoul_tpu.runtime.resilience import RetryPolicy

    schema = pa.schema([
        ("id", pa.int64()), ("seq", pa.int64()), ("v", pa.float64()),
    ])
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "LAKESOUL_RETRY_SEED": "7",
    })
    victim_env = dict(env, LAKESOUL_FAULTS="compaction.leased_job:1:hang:300")
    expected = commits * rows_per_commit

    with tempfile.TemporaryDirectory() as d:
        wh, db = os.path.join(d, "wh"), os.path.join(d, "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "fresh", schema, primary_keys=["id"], hash_bucket_num=2, cdc=True
        )
        start_ts = now_millis() - 1
        store = catalog.client.store
        lease_key = f"compaction/{t.info.table_id}/-5"

        def compactor(service_id: str, e: dict) -> subprocess.Popen:
            return subprocess.Popen(
                [sys.executable, "-m", "lakesoul_tpu.compaction",
                 "--warehouse", wh, "--db-path", db,
                 "--lease-ttl-s", str(ttl_s), "--poll-s", "0.1",
                 "--version-gap", "3", "--service-id", service_id],
                env=e, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )

        victim = compactor("victim", victim_env)
        writer = subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.freshness", "writer",
             "--warehouse", wh, "--db-path", db, "--table", "fresh",
             "--commits", str(commits),
             "--rows-per-commit", str(rows_per_commit),
             "--interval-s", "0.15"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )

        peer_box: dict = {}
        killed_at: dict = {}

        def kill_and_replace():
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if store.get_lease(lease_key) is not None:
                    victim.send_signal(signal.SIGKILL)
                    victim.wait(10.0)
                    killed_at["t"] = time.monotonic()
                    peer_box["peer"] = compactor("peer", env)
                    return
                time.sleep(0.05)

        watcher = threading.Thread(target=kill_and_replace, daemon=True)

        slo = SloMonitor(target_s=FRESHNESS_SLO_S, budget_fraction=0.05,
                         slo="bench-freshness")
        tput = ThroughputSlo(FRESHNESS_TPUT_FLOOR, slo="bench-freshness-tput")
        stop = threading.Event()
        follower = FreshFollower(
            catalog.table("fresh").scan().batch_size(2048),
            start_timestamp_ms=start_ts,
            poll_interval=0.05,
            stop_event=stop,
            retry_policy=RetryPolicy(
                max_attempts=12, base_delay_s=0.002, max_delay_s=0.05, seed=7
            ),
            slo=slo,
        )

        rows: list[tuple[int, int, float]] = []
        faults.clear()
        faults.install(f"follow.poll:{fault_p}:flaky")
        faults.install(f"object_store.cat_file:{fault_p}:flaky")
        faults.install(f"object_store.open:{fault_p}:flaky")
        try:
            tput.start()
            watcher.start()

            def consume():
                for b in follower.iter_batches():
                    rows.extend(zip(
                        b.column("seq").to_pylist(),
                        b.column("id").to_pylist(),
                        b.column("v").to_pylist(),
                    ))
                    if len(rows) >= expected:
                        stop.set()

            th = threading.Thread(target=consume, daemon=True)
            th.start()
            deadline = time.monotonic() + 180.0
            while th.is_alive() and time.monotonic() < deadline:
                th.join(timeout=0.2)
            stop.set()
            th.join(timeout=15.0)
            tput.add_rows(len(rows))
        finally:
            faults.clear()
            out, _ = writer.communicate(timeout=60.0)
            if victim.poll() is None:
                victim.send_signal(signal.SIGKILL)

        try:
            oracle = json.loads(out.strip().splitlines()[-1])
            assert writer.returncode == 0
            assert len(rows) == expected, (
                f"delivered {len(rows)} of {expected} rows"
            )
            assert oracle_sha(rows) == oracle["sha256"], (
                "delivered rows diverged from the writer oracle"
            )
            assert "t" in killed_at, "victim compactor never held a lease"

            snap = slo.snapshot()
            rate = tput.evaluate()
            assert snap["in_budget"] and snap["p99_s"] <= FRESHNESS_SLO_S, snap
            assert rate["ok"], rate

            # the peer completes the compaction under the fencing trail
            fence_deadline = time.monotonic() + 60.0
            fenced = []
            while time.monotonic() < fence_deadline and not fenced:
                fenced = [
                    v for v in store.get_partition_versions(
                        t.info.table_id, "-5"
                    )
                    if v.commit_op == CommitOp.COMPACTION
                    and v.expression.startswith("fence=")
                ]
                if not fenced:
                    time.sleep(0.2)
            assert fenced and any(
                int(v.expression.split("=", 1)[1]) >= 2 for v in fenced
            ), "no fenced takeover CompactionCommit"
        finally:
            peer = peer_box.get("peer")
            if peer is not None and peer.poll() is None:
                peer.send_signal(signal.SIGKILL)
                peer.wait(10.0)

        _emit(
            "freshness", snap["p99_s"], "s",
            freshness_p50_s=round(snap["p50_s"], 4),
            freshness_p99_s=round(snap["p99_s"], 4),
            freshness_max_s=round(snap["max_s"], 4),
            slo_target_s=FRESHNESS_SLO_S,
            slo_in_budget=snap["in_budget"],
            slo_violations=snap["violations"],
            commits_observed=snap["count"],
            rows=len(rows),
            rows_per_s=round(rate["rows_per_s"], 1),
            throughput_floor=FRESHNESS_TPUT_FLOOR,
            oracle_exact=True,
            compactor_sigkilled=True,
            takeover_fenced=True,
            fault_p=fault_p,
            lease_ttl_s=ttl_s,
        )


# ann_scale gates (env-tunable for slow boxes): the leg FAILS on a recall
# floor breach or a serving-QPS floor breach — same discipline as the
# scan_stages degeneracy budget.  The QPS floor is 10x the committed
# single-shard serving baseline (~125 QPS, BENCH_r05 ann_qps_serving).
ANN_SCALE_ROWS = int(os.environ.get("LAKESOUL_ANN_SCALE_ROWS", 10_000_000))
ANN_SCALE_DIM = int(os.environ.get("LAKESOUL_ANN_SCALE_DIM", 128))
ANN_SCALE_RECALL_FLOOR = float(
    os.environ.get("LAKESOUL_ANN_SCALE_RECALL_FLOOR", 0.95)
)
ANN_SCALE_QPS_FLOOR = float(os.environ.get("LAKESOUL_ANN_SCALE_QPS_FLOOR", 1250.0))
ANN_SCALE_RSS_CEILING_MB = int(
    os.environ.get("LAKESOUL_ANN_SCALE_RSS_CEILING_MB", 4096)
)
ANN_SCALE_SHARD_BUDGET = int(
    os.environ.get("LAKESOUL_ANN_SHARD_BUDGET_BYTES", 768 << 20)
)


def _ann_scale_corpus_chunks(n_rows: int, dim: int, chunk: int = 500_000):
    """Deterministic clustered corpus, regenerable chunk by chunk: the exact
    oracle streams over a SECOND generation of the same chunks instead of
    holding 5 GB of raw vectors."""
    rng_c = np.random.default_rng(20260801)
    centers = (rng_c.normal(size=(4096, dim)) * 3.0).astype(np.float32)
    for lo in range(0, n_rows, chunk):
        n = min(chunk, n_rows - lo)
        rng = np.random.default_rng(77_000 + lo // chunk)
        vecs = (
            centers[rng.integers(0, len(centers), n)]
            + rng.normal(size=(n, dim)).astype(np.float32)
        )
        yield lo, vecs


def _ann_scale_queries(dim: int, n_q: int = 64):
    rng_c = np.random.default_rng(20260801)
    centers = (rng_c.normal(size=(4096, dim)) * 3.0).astype(np.float32)
    rng = np.random.default_rng(99)
    return (
        centers[rng.integers(0, len(centers), n_q)]
        + rng.normal(size=(n_q, dim)).astype(np.float32)
    )


def _ann_serve_qps(plane, params, *, n_clients=64, per_client=64, depth=16,
                   max_batch=1024, max_wait_ms=3.0, name="serve"):
    """Serving QPS: ``n_clients`` threads, each pipelining ``depth`` async
    submits (the serving pattern of a fleet of low-latency clients), through
    ONE ragged micro-batching endpoint."""
    import collections
    import threading

    from lakesoul_tpu.annplane import ShardedAnnEndpoint

    queries = _ann_scale_queries(plane.dim, 256)
    with ShardedAnnEndpoint(
        plane, params, max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_pending=2 * n_clients * depth, name=name,
    ) as ep:
        ep.search(queries[0])  # warm the dispatch path
        start = time.perf_counter()

        def client(ci):
            inflight = collections.deque()
            for j in range(per_client):
                inflight.append(ep.submit(queries[(ci * 31 + j) % len(queries)]))
                if len(inflight) >= depth:
                    inflight.popleft().result(timeout=120)
            while inflight:
                inflight.popleft().result(timeout=120)

        threads = [
            threading.Thread(target=client, args=(ci,)) for ci in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        stats = ep.stats()
    return n_clients * per_client / wall, stats


def bench_ann_scale() -> None:
    """The production-scale ANN leg (ROADMAP item 1): a >=10M x 128d corpus
    written to a real LSF table, streamed through the BOUNDED scan path into
    a memory-bounded multi-shard build (peak RSS asserted against a ceiling
    far below the 6.6 GB resident corpus), then served at fleet shape.
    Publishes and GATES:

    - build rows/s + peak RSS <= ``LAKESOUL_ANN_SCALE_RSS_CEILING_MB``;
    - multi-shard search recall@10 vs the streaming exact oracle
      >= ``LAKESOUL_ANN_SCALE_RECALL_FLOOR`` (leg FAILS below, like the
      scan_stages degeneracy budget);
    - ragged-batched serving QPS (64 pipelined clients) >=
      ``LAKESOUL_ANN_SCALE_QPS_FLOOR`` = 10x the committed ~125 QPS
      single-shard baseline;
    - the 64-client overload story at the new scale: typed sheds only;
    - a 1/2/4-shard sweep on a 600k sub-corpus: recall held at every shard
      count (sharding must not cost recall) with QPS per count published.
    """
    import pyarrow as pa

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.annplane import (
        AnnPlane,
        AnnPlaneConfig,
        ShardedAnnBuilder,
        ShardedAnnEndpoint,
        iter_table_vectors,
    )
    from lakesoul_tpu.errors import OverloadedError
    from lakesoul_tpu.utils.memory import peak_rss_mb
    from lakesoul_tpu.vector.config import VectorIndexConfig
    from lakesoul_tpu.vector.index import SearchParams
    from lakesoul_tpu.vector.oracle import (
        StreamingExactOracle,
        exact_topk,
        recall_at_k,
    )

    dim = ANN_SCALE_DIM
    n_rows = ANN_SCALE_ROWS
    queries = _ann_scale_queries(dim)
    params = SearchParams(top_k=10, nprobe=48, rerank_depth=64)

    def shard_sweep_leg() -> dict:
        """1/2/4-shard sweep on a 600k sub-corpus: sharding must not cost
        recall (floor enforced at EVERY count), QPS per count published.
        Runs AFTER the 10M build so the RSS assertion sees a clean peak."""
        import gc

        sub_n = 600_000
        sub_vecs = np.concatenate(
            [v for _, v in _ann_scale_corpus_chunks(sub_n, dim, chunk=200_000)]
        )
        sub_ids = np.arange(sub_n, dtype=np.uint64)
        sub_truth = exact_topk(sub_vecs, sub_ids, queries, 10)
        sweep = {}
        with tempfile.TemporaryDirectory() as d:
            for n_shards in (1, 2, 4):
                index_cfg = VectorIndexConfig(
                    column="emb", dim=dim, nlist=256, total_bits=4
                )
                probe = AnnPlaneConfig(
                    index=index_cfg, shard_budget_bytes=1 << 40
                )
                rows_per = -(-sub_n // n_shards)
                cfg = AnnPlaneConfig(
                    index=index_cfg,
                    shard_budget_bytes=rows_per * probe.bytes_per_vector(),
                )
                root = os.path.join(d, f"plane{n_shards}")
                ShardedAnnBuilder(root, cfg).build(
                    (sub_vecs[lo : lo + 200_000], sub_ids[lo : lo + 200_000])
                    for lo in range(0, sub_n, 200_000)
                )
                plane = AnnPlane.open(root, use_pallas=False)
                assert len(plane.shards) == n_shards, (
                    len(plane.shards), n_shards,
                )
                got, _ = plane.batch_search(queries, params)
                recall = recall_at_k(sub_truth, got)
                qps, _ = _ann_serve_qps(
                    plane, params, n_clients=16, per_client=32, depth=4,
                    name=f"sweep{n_shards}",
                )
                sweep[n_shards] = {
                    "recall_at_10": round(recall, 4), "qps": round(qps, 1),
                }
                assert recall >= ANN_SCALE_RECALL_FLOOR, (
                    f"{n_shards}-shard recall {recall:.4f} breached the"
                    f" {ANN_SCALE_RECALL_FLOOR} floor"
                )
                del plane
                gc.collect()
        return sweep

    # ---- the 10M leg: table write -> bounded-scan build ------------------
    with tempfile.TemporaryDirectory() as d:
        catalog = LakeSoulCatalog(
            os.path.join(d, "wh"), db_path=os.path.join(d, "meta.db")
        )
        schema = pa.schema(
            [("id", pa.int64()), ("emb", pa.list_(pa.float32(), dim))]
        )
        table = catalog.create_table(
            "corpus", schema, properties={"lakesoul.file_format": "lsf"}
        )
        # peak_rss_mb is the PROCESS-lifetime high-water mark: under
        # `micro.py all` an earlier leg may already own the peak, which
        # would gate the wrong thing — only assert when this leg starts
        # with clean headroom (standalone runs, the committed mode)
        rss_at_leg_start = peak_rss_mb()
        rss_gate_armed = rss_at_leg_start < 0.5 * ANN_SCALE_RSS_CEILING_MB
        write_start = time.perf_counter()
        for lo, vecs in _ann_scale_corpus_chunks(n_rows, dim):
            table.write_arrow(pa.table({
                "id": np.arange(lo, lo + len(vecs), dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(
                    pa.array(vecs.reshape(-1)), dim
                ),
            }, schema=schema))
        write_dt = time.perf_counter() - write_start

        index_cfg = VectorIndexConfig(
            column="emb", dim=dim, nlist=512, total_bits=4
        )
        cfg = AnnPlaneConfig(
            index=index_cfg, shard_budget_bytes=ANN_SCALE_SHARD_BUDGET
        )
        root = os.path.join(d, "plane")
        build_start = time.perf_counter()
        manifest = ShardedAnnBuilder(root, cfg).build(
            iter_table_vectors(table, "emb", "id", batch_size=262_144)
        )
        build_dt = time.perf_counter() - build_start
        build_rss = peak_rss_mb()
        assert manifest["complete"] and manifest["total_rows"] == n_rows
        if rss_gate_armed:
            assert build_rss <= ANN_SCALE_RSS_CEILING_MB, (
                f"build peak RSS {build_rss:.0f} MB exceeded the declared"
                f" {ANN_SCALE_RSS_CEILING_MB} MB ceiling (shard budget"
                f" {ANN_SCALE_SHARD_BUDGET >> 20} MiB)"
            )
        else:
            sys.stderr.write(
                f"ann_scale: RSS gate skipped — peak was already"
                f" {rss_at_leg_start:.0f} MB at leg start (earlier legs own"
                " the high-water mark)\n"
            )

        # streaming exact oracle over a REGENERATION of the corpus: truth
        # never holds more than one chunk + Q x k running best
        oracle = StreamingExactOracle(queries, 10)
        for lo, vecs in _ann_scale_corpus_chunks(n_rows, dim):
            oracle.consume(vecs, np.arange(lo, lo + len(vecs), dtype=np.uint64))
        truth = oracle.truth()

        plane = AnnPlane.open(root, use_pallas=False)
        got, _ = plane.batch_search(queries, params)
        recall = recall_at_k(truth, got)
        assert recall >= ANN_SCALE_RECALL_FLOOR, (
            f"10M recall@10 {recall:.4f} breached the"
            f" {ANN_SCALE_RECALL_FLOOR} floor"
        )

        qps, serve_stats = _ann_serve_qps(plane, params)
        # overload at the new scale: 64 clients, tiny pending bound — every
        # rejection must be the typed shed (anything else would have landed
        # in errors and failed the count check)
        import threading

        ep = ShardedAnnEndpoint(
            plane, params, max_batch=16, max_wait_ms=5.0, max_pending=32,
            name="overload",
        )
        sheds = [0]
        served = [0]
        errors = []

        def hammer(ci):
            for j in range(16):
                try:
                    ep.search(queries[(ci + j) % len(queries)], timeout=120)
                    served[0] += 1
                except OverloadedError:
                    sheds[0] += 1
                except Exception as e:  # pragma: no cover — asserted below
                    errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(ci,)) for ci in range(64)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        overload_stats = ep.stats()
        ep.close()
        assert not errors, errors[:3]
        assert sheds[0] > 0, "overload hammer never tripped the pending bound"

        shard_sweep = shard_sweep_leg()

        _emit(
            "ann_scale", qps, "QPS",
            rows=n_rows,
            dim=dim,
            shards=len(manifest["shards"]),
            shard_budget_mb=ANN_SCALE_SHARD_BUDGET >> 20,
            build_rows_per_s=round(n_rows / build_dt, 1),
            table_write_rows_per_s=round(n_rows / write_dt, 1),
            build_peak_rss_mb=round(build_rss, 1),
            rss_ceiling_mb=ANN_SCALE_RSS_CEILING_MB,
            rss_gate_armed=rss_gate_armed,
            recall_at_10=round(recall, 4),
            recall_floor=ANN_SCALE_RECALL_FLOOR,
            qps_floor=ANN_SCALE_QPS_FLOOR,
            qps_vs_committed_baseline=round(qps / 125.2, 1),
            serving_mean_batch=round(serve_stats["mean_batch"], 1),
            serving_latency_p50_s=round(serve_stats["latency_p50"], 4),
            serving_latency_p99_s=round(serve_stats["latency_p99"], 4),
            nprobe=params.nprobe,
            overload_sheds=sheds[0],
            overload_served=served[0],
            overload_rejected_typed=overload_stats["rejected"],
            shard_sweep=shard_sweep,
        )
        assert qps >= ANN_SCALE_QPS_FLOOR, (
            f"ragged serving {qps:.0f} QPS below the {ANN_SCALE_QPS_FLOOR}"
            " floor (10x the committed single-shard baseline)"
        )


# tensor_replay gate: epoch-2 device replay must beat epoch-1 streaming by
# this factor (byte-identity asserted separately).  Replay serves pinned
# device shards — no decode, no collate, no put — so the measured margin is
# an order of magnitude; 2.0 is the declared floor a regression (a host
# round trip sneaking into the replay path, accidental re-collate) trips.
TENSOR_REPLAY_FLOOR = float(os.environ.get("LAKESOUL_TENSOR_REPLAY_FLOOR", 2.0))


def _tensor_replay_child() -> None:
    """Runs in a subprocess with an 8-device CPU mesh (XLA_FLAGS must be
    set BEFORE jax imports, so the parent leg spawns this).  Prints one
    JSON result line."""
    import hashlib

    import jax
    import jax.numpy as jnp  # noqa: F401 — force backend init under the flags
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.tensorplane import tensor_field
    from lakesoul_tpu.tensorplane.smoke import run_smoke

    devices = jax.devices()
    assert len(devices) >= 8, f"mesh leg needs 8 devices, got {len(devices)}"
    mesh = Mesh(np.array(devices[:8]), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))

    n_rows, width, batch = 131_072, 64, 1_024
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        catalog = LakeSoulCatalog(d)
        schema = pa.schema([
            ("id", pa.int64()),
            tensor_field("emb", (width,), "float32"),
            ("label", pa.int32()),
        ])
        t = catalog.create_table(
            "tensors", schema, properties={"lakesoul.file_format": "lsf"}
        )
        for lo in range(0, n_rows, 32_768):
            n = min(32_768, n_rows - lo)
            emb = rng.normal(size=(n, width)).astype(np.float32)
            t.write_arrow(pa.table({
                "id": np.arange(lo, lo + n, dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(
                    pa.array(emb.ravel()), width
                ).cast(schema.field("emb").type),
                "label": rng.integers(0, 10, n).astype(np.int32),
            }, schema=schema))

        def epoch_rows_per_s(it) -> tuple[float, int]:
            start = time.perf_counter()
            rows = 0
            last = None
            for b in it:
                rows += b["emb"].shape[0]
                last = b
            jax.block_until_ready(last)
            return rows / (time.perf_counter() - start), rows

        def epoch_hashes(it) -> list[str]:
            out = []
            for b in it:
                h = hashlib.sha256()
                for k in sorted(b):
                    h.update(np.asarray(b[k]).tobytes())
                out.append(h.hexdigest())
            return out

        # --- fully-resident leg: epoch-1 stream (+pin) vs epoch-2 replay
        it = t.scan().batch_size(batch).to_jax_iter(
            cache="device", sharding=sharding
        )
        stream_rps, rows1 = epoch_rows_per_s(it)
        assert it.stats()["replay"]["ready"]
        replay_rps, rows2 = epoch_rows_per_s(it)
        assert rows1 == rows2 == n_rows
        # byte-identity: a third (replay) epoch vs a freshly streamed loader
        replay_sha = epoch_hashes(it)
        stream_sha = epoch_hashes(
            t.scan().batch_size(batch).to_jax_iter(sharding=sharding)
        )
        assert replay_sha == stream_sha, "replay diverged from stream"

        # --- budget-spill leg: half the epoch resident, tail re-streamed.
        # The budget is PER DEVICE: a dp-sharded batch bills each of the 8
        # chips an eighth of its host bytes
        per_batch_dev = batch * (width * 4 + 4 + 4) // 8
        budget = (n_rows // batch // 2) * per_batch_dev + 64
        it_sp = t.scan().batch_size(batch).to_jax_iter(
            cache="device", sharding=sharding, replay_budget_bytes=budget
        )
        spill_stream_rps, _ = epoch_rows_per_s(it_sp)
        st = it_sp.stats()["replay"]
        assert st["spilled"], st
        hybrid_rps, rows_h = epoch_rows_per_s(it_sp)
        assert rows_h == n_rows
        assert epoch_hashes(it_sp) == stream_sha, "hybrid epoch diverged"

        smoke = run_smoke()
        print(json.dumps({
            "rows": n_rows,
            "tensor_width": width,
            "batch": batch,
            "devices": len(devices),
            "stream_rows_per_s": round(stream_rps, 1),
            "replay_rows_per_s": round(replay_rps, 1),
            "replay_over_stream": round(replay_rps / stream_rps, 2),
            "spill_resident_batches": st["resident_batches"],
            "spill_budget_bytes": budget,
            "hybrid_rows_per_s": round(hybrid_rps, 1),
            "hybrid_over_stream": round(hybrid_rps / spill_stream_rps, 2),
            "byte_identity": True,
            "tpu_smoke": {
                "platform": smoke["platform"],
                "ok": smoke["ok"],
                "untested_on_tpu": smoke["untested_on_tpu"],
                "uncovered_kernels": smoke["kernel_enumeration"]["uncovered"],
            },
        }))


def bench_tensor_replay() -> None:
    """Epoch-1 streaming delivery vs epoch-2 device-resident replay on the
    8-device CPU mesh (tensorplane/replay.py), with byte-identity asserted
    per batch, a budget-spill hybrid variant, and the TPU-smoke fallback
    record published.  FAILS when replay does not beat streaming by
    ``TENSOR_REPLAY_FLOOR``."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "_tensor_replay_child"],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    ratio = result["replay_over_stream"]
    _emit(
        "tensor_replay", result["replay_rows_per_s"], "rows/s",
        floor=TENSOR_REPLAY_FLOOR, **result,
    )
    assert ratio >= TENSOR_REPLAY_FLOOR, (
        f"epoch-2 replay beat streaming only {ratio:.2f}x — below the"
        f" declared {TENSOR_REPLAY_FLOOR} floor"
    )
    assert result["byte_identity"]
    assert result["tpu_smoke"]["ok"], "smoke register failed on fallback"


# obs_fleet overhead budget: fleet telemetry (member/recorder flushes during
# the scan window, fleet-wide, plus ONE aggregator merge) may cost at most
# this fraction of the scan-leg wall time.  The leg FAILS on breach — the
# observability plane must be cheap enough to leave on everywhere.
OBS_FLEET_BUDGET = float(os.environ.get("LAKESOUL_OBS_FLEET_BUDGET", 0.01))


def bench_obs_fleet(
    n_rows: int = 2_000_000, n_buckets: int = 8,
    commits: int = 8, rows_per_commit: int = 250,
    ttl_s: float = 1.5, fault_p: float = 0.3, flush_s: float = 1.0,
    store_latency_s: float = 0.35,
) -> None:
    """The fleet-observability acceptance run: a three-role chaos fleet —
    a freshness writer + leased compactor (SIGKILLed while HOLDING its
    lease) + in-process fresh follower under p=0.3 flaky faults, then a
    scanplane fleet (2 workers + a drive client, all separate processes) —
    every role publishing to ONE obs spool.  Asserts the plane's four
    claims:

    - ONE aggregated fleet snapshot with per-role series (build_info per
      role, counters summed fleet-wide, freshness SLO evaluated from the
      MERGED histogram);
    - an end-to-end commit → decode → delivery trace whose spans come
      from ≥ 2 distinct processes, assembled from the spool by trace id;
    - a recoverable postmortem for the SIGKILLed compactor (stale by
      heartbeat age, flight-recorder dump + last-flushed snapshot intact);
    - overhead budget: scan-window flush cost (fleet-wide delta of
      ``lakesoul_obs_flush_seconds``) + one aggregator merge ≤
      ``OBS_FLEET_BUDGET`` of the scan-leg wall time (FAILS on breach)."""
    import signal
    import subprocess
    import threading

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.freshness import FreshFollower, SloMonitor
    from lakesoul_tpu.obs import fleet, parse_series_key
    from lakesoul_tpu.obs.tracing import ENV_TRACE_ID, new_trace_id
    from lakesoul_tpu.runtime import faults
    from lakesoul_tpu.runtime.resilience import RetryPolicy
    from lakesoul_tpu.scanplane.delivery import ScanPlaneDelivery
    from lakesoul_tpu.scanplane.session import ScanSession
    from lakesoul_tpu.service.flight import LakeSoulFlightServer

    rng = np.random.default_rng(0)
    batch_size = 65_536
    trace_id = new_trace_id()
    spool_base = "/dev/shm" if os.path.isdir("/dev/shm") else None

    with tempfile.TemporaryDirectory() as d, \
            tempfile.TemporaryDirectory(prefix="lsobs-", dir=spool_base) as shm:
        obs_spool = os.path.join(shm, "obs")
        scan_spool = os.path.join(shm, "scan")
        os.makedirs(obs_spool)
        os.makedirs(scan_spool)
        wh, db = os.path.join(d, "wh"), os.path.join(d, "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)

        def child_env(**extra) -> dict:
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                "LAKESOUL_RETRY_SEED": "7",
                "LAKESOUL_OBS_SPOOL": obs_spool,
                "LAKESOUL_OBS_FLUSH_S": str(flush_s),
                ENV_TRACE_ID: trace_id,
            })
            env.update(extra)
            return env

        saved_trace = os.environ.get(ENV_TRACE_ID)
        os.environ[ENV_TRACE_ID] = trace_id  # driver spans join the trace
        pub = fleet.arm("bench-driver", spool_dir=obs_spool, flush_s=flush_s)
        try:
            # ---- phase A: freshness writer + leased compactor chaos + in-
            # process follower under flaky faults ------------------------
            schema_f = pa.schema([
                ("id", pa.int64()), ("seq", pa.int64()), ("v", pa.float64()),
            ])
            from lakesoul_tpu.meta.entity import now_millis

            tf = catalog.create_table(
                "fresh", schema_f, primary_keys=["id"], hash_bucket_num=2,
                cdc=True,
            )
            start_ts = now_millis() - 1
            store = catalog.client.store
            lease_key = f"compaction/{tf.info.table_id}/-5"

            def compactor(service_id: str, env: dict) -> subprocess.Popen:
                return subprocess.Popen(
                    [sys.executable, "-m", "lakesoul_tpu.compaction",
                     "--warehouse", wh, "--db-path", db,
                     "--lease-ttl-s", str(ttl_s), "--poll-s", "0.1",
                     "--version-gap", "3", "--service-id", service_id],
                    env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )

            victim = compactor("victim", child_env(
                LAKESOUL_FAULTS="compaction.leased_job:1:hang:300"
            ))
            peer_box: dict = {}
            writer = subprocess.Popen(
                [sys.executable, "-m", "lakesoul_tpu.freshness", "writer",
                 "--warehouse", wh, "--db-path", db, "--table", "fresh",
                 "--commits", str(commits),
                 "--rows-per-commit", str(rows_per_commit),
                 "--interval-s", "0.1"],
                env=child_env(), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )

            killed: dict = {}

            def kill_when_leased():
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    lease = store.get_lease(lease_key)
                    if lease is not None and lease.holder == "victim":
                        victim.send_signal(signal.SIGKILL)
                        victim.wait(10.0)
                        killed["pid"] = victim.pid
                        killed["t"] = time.monotonic()
                        # the replacement compactor takes over under the
                        # fencing trail (proven by the freshness leg; here
                        # it keeps a live compactor member in the fleet)
                        peer_box["peer"] = compactor("peer", child_env())
                        return
                    time.sleep(0.05)

            watcher = threading.Thread(target=kill_when_leased, daemon=True)
            watcher.start()

            expected = commits * rows_per_commit
            slo = SloMonitor(target_s=FRESHNESS_SLO_S, budget_fraction=0.05,
                             slo="obs-fleet")
            stop = threading.Event()
            follower = FreshFollower(
                catalog.table("fresh").scan().batch_size(2048),
                start_timestamp_ms=start_ts,
                poll_interval=0.05,
                stop_event=stop,
                retry_policy=RetryPolicy(
                    max_attempts=12, base_delay_s=0.002, max_delay_s=0.05,
                    seed=7,
                ),
                slo=slo,
            )
            delivered = 0
            faults.clear()
            faults.install(f"follow.poll:{fault_p}:flaky")
            faults.install(f"object_store.cat_file:{fault_p}:flaky")
            faults.install(f"object_store.open:{fault_p}:flaky")
            try:
                def consume():
                    nonlocal delivered
                    for b in follower.iter_batches():
                        delivered += b.num_rows
                        if delivered >= expected:
                            stop.set()

                th = threading.Thread(target=consume, daemon=True)
                th.start()
                deadline = time.monotonic() + 120.0
                while th.is_alive() and time.monotonic() < deadline:
                    th.join(timeout=0.2)
                stop.set()
                th.join(timeout=15.0)
            finally:
                faults.clear()
                writer.communicate(timeout=60.0)
                watcher.join(timeout=15.0)
                if victim.poll() is None:
                    victim.send_signal(signal.SIGKILL)
                    victim.wait(10.0)
                peer = peer_box.get("peer")
                if peer is not None:
                    peer.terminate()
                    peer.wait(10.0)
            assert delivered == expected, (delivered, expected)
            assert "pid" in killed, "victim compactor never held a lease"

            # ---- phase B: scanplane fleet, the wall-clock the obs plane
            # is budgeted against ------------------------------------------
            schema_t = pa.schema([
                ("id", pa.int64()), ("label", pa.int32()),
                ("f0", pa.float32()), ("f1", pa.float32()),
            ])
            t = catalog.create_table(
                "t", schema_t, primary_keys=["id"],
                hash_bucket_num=n_buckets,
                properties={"lakesoul.file_format": "lsf"},
            )
            t.write_arrow(pa.table({
                "id": np.arange(n_rows, dtype=np.int64),
                "label": rng.integers(0, 10, n_rows).astype(np.int32),
                "f0": rng.normal(size=n_rows).astype(np.float32),
                "f1": rng.normal(size=n_rows).astype(np.float32),
            }, schema=schema_t))

            agg = fleet.FleetAggregator(obs_spool, stale_after_s=5.0)

            def flush_sum(snapshot: dict) -> float:
                h = snapshot.get("lakesoul_obs_flush_seconds")
                return float(h["sum"]) if isinstance(h, dict) else 0.0

            delivery = ScanPlaneDelivery(catalog, scan_spool, wait_s=180)
            server = LakeSoulFlightServer(
                catalog, "grpc://127.0.0.1:0", scanplane=delivery
            )
            threading.Thread(target=server.serve, daemon=True).start()
            location = f"grpc://127.0.0.1:{server.port}"
            workers: list = []
            try:
                for i in range(2):
                    workers.append(subprocess.Popen(
                        [sys.executable, "-m", "lakesoul_tpu.scanplane",
                         "worker", "--warehouse", wh, "--db-path", db,
                         "--spool", scan_spool,
                         "--lease-ttl-s", str(ttl_s), "--poll-s", "0.05",
                         "--worker-id", f"w{i}"],
                        # per-range store latency: the same emulation
                        # discipline as the scanplane/pipeline legs — the
                        # deployment this budget protects scans remote
                        # object storage, not page cache
                        env=child_env(LAKESOUL_FAULTS=(
                            f"scanplane.range:1:delay:{store_latency_s}"
                        )),
                        stdout=subprocess.PIPE,
                        stderr=subprocess.DEVNULL, text=True,
                    ))
                for w in workers:
                    w.stdout.readline()  # readiness line

                def scan_pass(bsz: int) -> tuple[float, float]:
                    """One drive process over a fresh session; returns
                    (scan wall, fleet flush seconds spent in the window).
                    The window opens at scan start (fleet boot flushes are
                    arming cost, not per-scan overhead) and closes right
                    after the drive's atexit flush lands."""
                    drive = subprocess.Popen(
                        [sys.executable, "-m", "lakesoul_tpu.scanplane",
                         "drive", "--location", location, "--table", "t",
                         "--batch-size", str(bsz),
                         "--rank", "0", "--world", "1"],
                        env=child_env(), stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, text=True,
                    )
                    session = ScanSession.plan(
                        catalog, {"table": "t", "batch_size": bsz}
                    )
                    manifest = os.path.join(
                        scan_spool, session.session_id, "manifest.json"
                    )
                    deadline = time.monotonic() + 120.0
                    while not os.path.exists(manifest):
                        assert time.monotonic() < deadline, "drive never connected"
                        time.sleep(0.02)
                    # no flush_now here: forcing a flush to measure flushes
                    # would bill the measurement to the budget; periodic
                    # flushes lag the window edges by ≤ flush_s on each
                    # side, unbiased in expectation
                    f0 = flush_sum(agg.aggregate()["snapshot"])
                    t0 = time.time()
                    out, err = drive.communicate(timeout=600)
                    lines = [
                        ln for ln in out.splitlines() if ln.startswith("{")
                    ]
                    assert drive.returncode == 0 and lines, err[-2000:]
                    drive_out = json.loads(lines[-1])
                    assert drive_out["rows"] == n_rows, drive_out
                    wall = drive_out["ended_unix"] - t0
                    f1 = flush_sum(agg.aggregate()["snapshot"])
                    return wall, max(0.0, f1 - f0)

                # best-of-2 passes: flush timers land in the window at
                # ±1-flush granularity, so a single pass is noisy; a
                # DIFFERENT batch size forces a fresh session (same-size
                # requests coalesce onto the already-produced spool)
                passes = [scan_pass(batch_size), scan_pass(batch_size + 4096)]
                # two flush periods so the workers' final spans/heartbeats
                # reach the spool (SIGTERM skips atexit by design)
                time.sleep(2.5 * flush_s)
            finally:
                for w in workers:
                    if w.poll() is None:
                        w.terminate()
                for w in workers:
                    try:
                        w.wait(10.0)
                    except subprocess.TimeoutExpired:
                        w.kill()
                server.shutdown()

            # the victim's heartbeat age must provably exceed the staleness
            # threshold (a fast scan leg can finish inside it)
            since_kill = time.monotonic() - killed["t"]
            if since_kill < 5.5:
                time.sleep(5.5 - since_kill)

            # ---- the four claims ----------------------------------------
            merge_t0 = time.perf_counter()
            doc = agg.aggregate()
            merge_s = time.perf_counter() - merge_t0
            snapshot = doc["snapshot"]

            roles = set()
            for key in snapshot:
                if key.startswith("lakesoul_build_info"):
                    _, labels = parse_series_key(key)
                    roles.add((labels or {}).get("role"))
            assert roles >= {
                "bench-driver", "freshness-writer", "compactor",
                "scanplane-worker", "scanplane-drive",
            }, roles
            fr = doc["slos"]["freshness"]
            # one observation per delivered (commit, bucket) hand-off — at
            # least one per commit made it through the flaky faults
            assert fr["count"] >= commits and fr["in_budget"], fr
            assert doc["fleet"]["rows"] >= n_rows + expected
            assert doc["fleet"]["rows_per_s"] > 0

            trace = agg.trace(trace_id)
            names = [s["name"] for s in trace]
            pids = {s["pid"] for s in trace}
            assert "freshness.commit" in names, names
            assert "scanplane.drive.deliver" in names, names
            assert len(pids) >= 2, pids
            commit_t = min(
                s["t_unix"] for s in trace if s["name"] == "freshness.commit"
            )
            deliver_t = max(
                s["t_unix"] for s in trace
                if s["name"] == "scanplane.drive.deliver"
            )
            assert commit_t < deliver_t  # commit → delivery, end to end

            stale_ids = {m["service_id"] for m in agg.stale_members()}
            assert "victim" in stale_ids, [
                (m["service_id"], round(time.time() - m["heartbeat_unix"], 2))
                for m in agg.members()
            ]
            pm = next(
                p for p in agg.postmortems() if p["service_id"] == "victim"
            )
            assert pm["role"] == "compactor" and pm["pid"] == killed["pid"]
            assert any(
                k.startswith("lakesoul_build_info") for k in pm["last_snapshot"]
            ), "victim's last-flushed snapshot not recovered"

            overheads = [(fl + merge_s) / wall for wall, fl in passes]
            best = overheads.index(min(overheads))
            scan_wall, flush_win = passes[best]
            overhead = overheads[best]
            _emit(
                "obs_fleet", 100.0 * overhead, "% of scan wall",
                budget_pct=100.0 * OBS_FLEET_BUDGET,
                scan_wall_s=round(scan_wall, 3),
                scan_rows=n_rows,
                scan_rows_per_s=round(n_rows / scan_wall, 1),
                flush_scan_window_s=round(flush_win, 5),
                pass_overheads_pct=[round(100 * o, 2) for o in overheads],
                merge_s=round(merge_s, 5),
                flush_interval_s=flush_s,
                members=len(doc["members"]),
                stale_members=len(stale_ids),
                roles=sorted(r for r in roles if r),
                fleet_rows=doc["fleet"]["rows"],
                fleet_rows_per_s=doc["fleet"]["rows_per_s"],
                freshness_slo_in_budget=fr["in_budget"],
                freshness_commits=fr["count"],
                follower_rows=delivered,
                fault_p=fault_p,
                trace_spans=len(trace),
                trace_processes=len(pids),
                trace_commit_to_delivery=True,
                victim_sigkilled=True,
                postmortem_recovered=True,
            )
            assert overhead <= OBS_FLEET_BUDGET, (
                f"obs overhead {100 * overhead:.2f}% of scan wall — budget is"
                f" {100 * OBS_FLEET_BUDGET:.2f}%"
            )
        finally:
            if saved_trace is None:
                os.environ.pop(ENV_TRACE_ID, None)
            else:
                os.environ[ENV_TRACE_ID] = saved_trace
            if pub is not None:
                pub.stop()


# the fleet leg's scaling gate: aggregate trainer rows/s must grow at
# least this factor from 1 → 2 emulated hosts (near-linear modulo fixed
# session/connect overheads); the leg FAILS below it
FLEET_SCALE_FLOOR = float(os.environ.get("LAKESOUL_FLEET_SCALE_FLOOR", 1.7))


def bench_fleet(
    n_rows: int = 2_000_000, n_buckets: int = 16, ttl_s: float = 2.0,
    total_devices: int = 8, step_s: float = 0.15,
) -> None:
    """Multi-host training surface at fleet shape (ROADMAP item 2): N
    emulated hosts — each a REAL gateway process plus a REAL trainer
    process (``python -m lakesoul_tpu.fleet train`` under
    ``LAKESOUL_FLEET_PROCESS_INDEX/_COUNT``, bound to a disjoint device
    subset via ``xla_force_host_platform_device_count``) — consume one
    table through the scan fabric on the forced ``stream`` transport (the
    no-shared-medium cross-host floor).  Three claims, all asserted:

    - **per-rank sha identity**: every rank's collated-host-array sha256
      equals the single-process ``scan.shard(rank, world)`` stream;
    - **scaling**: aggregate trainer rows/s grows ≥``FLEET_SCALE_FLOOR``
      from 1 → 2 hosts (4-host figure emitted alongside) over a warm
      spool with an emulated fixed per-batch training step (``step_s`` —
      each host's devices are busy per batch, the realistic consumption
      shape): N hosts step over disjoint shards concurrently, so the
      fabric's aggregate feed rate must scale with hosts.  Production is
      bench_scanplane's axis;
    - **kill-a-host chaos**: SIGKILL one host's gateway AND one
      autoscaler-owned worker mid-run → the surviving rank completes
      exactly-once, the autoscaler backfills the dead worker within one
      lease TTL, and the orphaned rank relaunched against the surviving
      gateway completes the same session exactly-once."""
    import hashlib
    import signal
    import subprocess
    import threading

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.fleet.multihost import digest_batch
    from lakesoul_tpu.scanplane.session import ScanSession
    from lakesoul_tpu.scanplane.worker import ScanPlaneWorker

    rng = np.random.default_rng(0)
    schema = pa.schema([
        ("id", pa.int64()), ("label", pa.int32()),
        ("f0", pa.float32()), ("f1", pa.float32()),
        ("f2", pa.float32()), ("f3", pa.float32()),
    ])
    batch_size = 65_536

    def child_env(**extra) -> dict:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
            "LAKESOUL_RETRY_SEED": "7", "LAKESOUL_RETRY_CAP_S": "0.5",
        })
        env.update(extra)
        return env

    def spawn_gateway(wh, db, spool):
        proc = subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.scanplane", "service",
             "--warehouse", wh, "--db-path", db, "--spool", spool,
             "--workers", "0"],
            env=child_env(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
        )
        handle = proc.stdout.readline()
        assert handle, "gateway died before printing its handle"
        return proc, json.loads(handle)["location"]

    def spawn_trainer(wh, db, location, rank, world, step_s=0.0):
        # each emulated host owns a DISJOINT device subset of the mesh
        return subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.fleet", "train",
             "--warehouse", wh, "--db-path", db, "--table", "t",
             "--batch-size", str(batch_size), "--location", location,
             "--step-s", str(step_s)],
            env=child_env(
                LAKESOUL_FLEET_PROCESS_INDEX=str(rank),
                LAKESOUL_FLEET_PROCESS_COUNT=str(world),
                LAKESOUL_FLEET_TRANSPORT="stream",
                XLA_FLAGS=(
                    "--xla_force_host_platform_device_count="
                    f"{total_devices // world}"
                ),
            ),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )

    def finish(proc, *, timeout=600.0) -> dict:
        out, err = proc.communicate(timeout=timeout)
        lines = [ln for ln in out.splitlines() if ln.startswith("{")]
        assert proc.returncode == 0 and lines, err[-2000:]
        return json.loads(lines[-1])

    with tempfile.TemporaryDirectory() as d:
        wh, db = os.path.join(d, "wh"), os.path.join(d, "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", schema, primary_keys=["id"], hash_bucket_num=n_buckets,
        )
        t.write_arrow(pa.table({
            "id": np.arange(n_rows, dtype=np.int64),
            "label": rng.integers(0, 10, n_rows).astype(np.int32),
            **{f"f{j}": rng.normal(size=n_rows).astype(np.float32)
               for j in range(4)},
        }, schema=schema))
        ids = np.sort(
            rng.choice(n_rows, n_rows // 4, replace=False)
        ).astype(np.int64)
        t.upsert(pa.table({
            "id": ids,
            "label": rng.integers(0, 10, len(ids)).astype(np.int32),
            **{f"f{j}": rng.normal(size=len(ids)).astype(np.float32)
               for j in range(4)},
        }, schema=schema))

        # single-process shard-scan oracles, hashed EXACTLY as the train
        # role hashes (collated host arrays through digest_batch)
        def shard_sha(rank: int, world: int) -> "tuple[str, int]":
            scan = t.scan().batch_size(batch_size)
            if world > 1:
                scan = scan.shard(rank, world)
            digest = hashlib.sha256()
            rows = 0
            for batch in scan.to_jax_iter(
                device_put=False, drop_remainder=False
            ):
                rows += digest_batch(digest, batch)
            return digest.hexdigest(), rows

        oracle = {
            world: {r: shard_sha(r, world) for r in range(world)}
            for world in (1, 2, 4)
        }
        total_rows = sum(rows for _, rows in oracle[1].values())

        # warm spool for the scaling legs: production (bench_scanplane's
        # axis) runs once up front; the measured window is pure delivery —
        # gateway stream + collate + hash per host
        spool_base = "/dev/shm" if os.path.isdir("/dev/shm") else d
        spool = tempfile.mkdtemp(prefix="lsf-", dir=spool_base)
        try:
            ScanSession.plan(
                catalog, {"table": "t", "batch_size": batch_size}
            ).publish(spool)
            ScanPlaneWorker(catalog, spool, lease_ttl_s=30).poll_once()

            rates = {}
            for world in (1, 2, 4):
                gws = []
                try:
                    gws = [spawn_gateway(wh, db, spool) for _ in range(world)]
                    trainers = [
                        spawn_trainer(wh, db, gws[r][1], r, world,
                                      step_s=step_s)
                        for r in range(world)
                    ]
                    outs = [finish(p) for p in trainers]
                    for rank, doc in enumerate(outs):
                        sha, rows = oracle[world][rank]
                        assert doc["rows"] == rows, (world, rank)
                        assert doc["sha256"] == sha, (
                            f"rank {rank}/{world} diverged from the"
                            " single-process shard scan"
                        )
                        assert doc["local_devices"] == total_devices // world
                    window = max(o["ended_unix"] for o in outs) \
                        - min(o["started_unix"] for o in outs)
                    rates[world] = total_rows / window
                finally:
                    for gw, _ in gws:
                        gw.terminate()
                    for gw, _ in gws:
                        try:
                            gw.wait(10.0)
                        except subprocess.TimeoutExpired:
                            gw.kill()
            scale2 = rates[2] / rates[1]
            scale4 = rates[4] / rates[1]
        finally:
            shutil.rmtree(spool, ignore_errors=True)

        # kill-a-host chaos: COLD spool, the worker fleet owned by a real
        # autoscaler; SIGKILL host B's gateway + one autoscaler child
        spool = tempfile.mkdtemp(prefix="lsf-", dir=spool_base)
        events = []
        worker_pids = set()
        procs = []
        backfill_s = None
        try:
            gw_a, loc_a = spawn_gateway(wh, db, spool)
            procs.append(gw_a)
            gw_b, loc_b = spawn_gateway(wh, db, spool)
            procs.append(gw_b)
            scaler = subprocess.Popen(
                [sys.executable, "-m", "lakesoul_tpu.fleet", "autoscale",
                 "--warehouse", wh, "--db-path", db, "--spool", spool,
                 "--min-workers", "2", "--max-workers", "4",
                 "--lease-ttl-s", str(ttl_s), "--poll-s", "0.1",
                 "--worker-lease-ttl-s", str(ttl_s),
                 "--worker-poll-s", "0.05"],
                env=child_env(), stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            )
            procs.append(scaler)

            def pump():
                for line in scaler.stdout:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    ev["_at"] = time.monotonic()
                    if ev.get("event") == "spawn":
                        worker_pids.add(ev["pid"])
                    events.append(ev)

            threading.Thread(target=pump, daemon=True).start()
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and len(worker_pids) < 2:
                assert scaler.poll() is None, "autoscaler exited early"
                time.sleep(0.05)
            assert len(worker_pids) >= 2, "autoscaler never reached min"

            rank0 = spawn_trainer(wh, db, loc_a, 0, 2)
            procs.append(rank0)
            rank1 = spawn_trainer(wh, db, loc_b, 1, 2)
            procs.append(rank1)
            time.sleep(1.0)
            victim_pid = sorted(worker_pids)[0]
            gw_b.send_signal(signal.SIGKILL)
            os.kill(victim_pid, signal.SIGKILL)
            killed_at = time.monotonic()

            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and backfill_s is None:
                snap = list(events)
                for i, ev in enumerate(snap):
                    if ev.get("event") == "worker_exit" \
                            and ev.get("pid") == victim_pid:
                        later = [e for e in snap[i + 1:]
                                 if e.get("event") == "spawn"]
                        if later:
                            backfill_s = later[0]["_at"] - killed_at
                        break
                time.sleep(0.05)
            assert backfill_s is not None, "autoscaler never backfilled"
            assert backfill_s < ttl_s, (
                f"backfill took {backfill_s:.2f}s — one lease TTL is {ttl_s}s"
            )

            doc0 = finish(rank0)
            sha, rows = oracle[2][0]
            assert doc0["rows"] == rows and doc0["sha256"] == sha, (
                "surviving rank diverged through the kill"
            )
            # the orphaned rank, relaunched against the SURVIVING gateway,
            # completes the same session exactly-once (delivered state
            # lives in the spool fabric, not the dead gateway)
            try:
                rank1.communicate(timeout=60.0)
            except subprocess.TimeoutExpired:
                rank1.kill()
                rank1.communicate(timeout=10.0)
            relaunched = spawn_trainer(wh, db, loc_a, 1, 2)
            procs.append(relaunched)
            doc1 = finish(relaunched)
            sha, rows = oracle[2][1]
            assert doc1["rows"] == rows and doc1["sha256"] == sha, (
                "relaunched rank diverged after the gateway kill"
            )
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(10.0)
                except subprocess.TimeoutExpired:
                    p.kill()
            for pid in worker_pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            shutil.rmtree(spool, ignore_errors=True)

        _emit(
            "fleet", rates[2], "rows/s",
            rows=total_rows,
            transport="stream",
            hosts_1_rows_per_s=round(rates[1], 1),
            hosts_2_rows_per_s=round(rates[2], 1),
            hosts_4_rows_per_s=round(rates[4], 1),
            scale_1_to_2=round(scale2, 2),
            scale_1_to_4=round(scale4, 2),
            scale_floor=FLEET_SCALE_FLOOR,
            devices_per_host={w: total_devices // w for w in (1, 2, 4)},
            per_rank_sha_identical=True,
            emulated_step_s=step_s,
            chaos_backfill_s=round(backfill_s, 3),
            chaos_exactly_once=True,
            lease_ttl_s=ttl_s,
        )
        assert scale2 >= FLEET_SCALE_FLOOR, (
            f"fleet scaled only {scale2:.2f}x from 1→2 hosts —"
            f" floor is {FLEET_SCALE_FLOOR}x"
        )


# soak leak-slope gate: over repeated open→scan→serve→close cycles the
# traced-heap high-water may climb at most this many bytes between the
# first-third and last-third cycle averages.  Steady state measures ~0
# (caches warm during the first third); a per-cycle retention of even one
# scanned table (~0.6 MB at the default leg shape) blows the budget, so
# this is an O(cycles) leak tripwire, not a formality.
SOAK_HEAP_BUDGET = float(os.environ.get("LAKESOUL_SOAK_HEAP_BUDGET", 4_000_000))


def bench_soak(cycles: int = 12, n_rows: int = 40_000) -> None:
    """Resource-boundedness replay (the runtime half of lakelint's
    boundedness pack): run ``cycles`` full open→scan→serve→close lifecycles
    — open a catalog over a seeded warehouse, scan the table through the
    loader path, serve one real ``/metrics`` scrape from the Prometheus
    exporter, shut everything down — sampling ``leakcheck.snapshot()``
    (fds + live threads) and the tracemalloc heap after every cycle.

    The gate is the SLOPE, not the absolute: first-third vs last-third
    cycle averages must be flat (fds within 2, threads within 1, heap
    within ``SOAK_HEAP_BUDGET`` bytes).  A lifecycle that leaks one fd,
    thread, or table per cycle fails the leg outright — the same
    fail-don't-shave contract as ``scan_stages``."""
    import gc
    import tracemalloc
    import urllib.request

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.analysis import leakcheck
    from lakesoul_tpu.obs.exporter import serve_prometheus

    wh = tempfile.mkdtemp(prefix="lakesoul-soak-")
    try:
        rng = np.random.default_rng(0)
        seed_cat = LakeSoulCatalog(wh)
        table = seed_cat.create_table(
            "soak",
            pa.schema([("id", pa.int64()), ("v", pa.float64())]),
        )
        table.write_arrow(pa.table({
            "id": np.arange(n_rows, dtype=np.int64),
            "v": rng.normal(size=n_rows),
        }))
        del table, seed_cat
        gc.collect()

        tracemalloc.start()
        samples = []
        start = time.perf_counter()
        for _ in range(cycles):
            cat = LakeSoulCatalog(wh)  # open
            rows = len(cat.table("soak").to_arrow())  # scan
            assert rows == n_rows
            srv = serve_prometheus(port=0, host="127.0.0.1")  # serve
            port = srv.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                assert resp.status == 200 and resp.read()
            srv.shutdown()  # close
            srv.server_close()
            del cat, srv
            gc.collect()
            snap = leakcheck.snapshot()
            samples.append((
                snap.fd_count,
                snap.thread_count,
                tracemalloc.get_traced_memory()[0],
            ))
        dt = time.perf_counter() - start
        tracemalloc.stop()

        third = max(1, cycles // 3)

        def slope(idx: int) -> float:
            first = [s[idx] for s in samples[:third]]
            last = [s[idx] for s in samples[-third:]]
            return sum(last) / len(last) - sum(first) / len(first)

        fd_slope, thread_slope, heap_slope = slope(0), slope(1), slope(2)
        _emit(
            "soak_cycles", cycles / dt, "cycles/s",
            cycles=cycles, rows_per_cycle=n_rows,
            fd_slope=round(fd_slope, 2),
            thread_slope=round(thread_slope, 2),
            heap_slope_bytes=round(heap_slope, 1),
            fd_high_water=max(s[0] for s in samples),
            thread_high_water=max(s[1] for s in samples),
            heap_high_water=max(s[2] for s in samples),
            heap_budget=SOAK_HEAP_BUDGET,
        )
        assert fd_slope <= 2.0, (
            f"soak fd high-water climbs {fd_slope:.2f}/third — an fd leaks"
            " somewhere in the open→scan→serve→close lifecycle"
        )
        assert thread_slope <= 1.0, (
            f"soak thread count climbs {thread_slope:.2f}/third — a thread"
            " outlives its cycle (nothing joined or stopped it)"
        )
        assert heap_slope <= SOAK_HEAP_BUDGET, (
            f"soak heap climbs {heap_slope:.0f} bytes/third — budget"
            f" {SOAK_HEAP_BUDGET:.0f} (LAKESOUL_SOAK_HEAP_BUDGET)"
        )
    finally:
        shutil.rmtree(wh, ignore_errors=True)


LEGS = {
    "merge": bench_merge,
    "scan_stages": bench_scan_stages,
    "formats": bench_formats,
    "streaming": bench_streaming_merge,
    "cache": bench_cache,
    "spill": bench_spill,
    "meta": bench_meta_prune,
    "pipeline": bench_pipeline_scan,
    "chaos": bench_chaos,
    "lint": bench_lint,
    "topology": bench_topology,
    "scanplane": bench_scanplane,
    "freshness": bench_freshness,
    "ann_scale": bench_ann_scale,
    "tensor_replay": bench_tensor_replay,
    "obs_fleet": bench_obs_fleet,
    "fleet": bench_fleet,
    "soak": bench_soak,
}


def _obs_snapshot() -> dict:
    from lakesoul_tpu.obs import registry

    return registry().snapshot()


def _emit_obs(leg: str, before: dict) -> None:
    """Registry DELTA over one leg (the registry is process-cumulative), so
    BENCH_*.json rounds can record loader/scan/merge throughput counters
    alongside wall-clock figures.  Histograms compress to count/sum/mean;
    series a leg didn't move are dropped."""
    obs = {}
    for name, value in sorted(_obs_snapshot().items()):
        if isinstance(value, dict):
            prev = before.get(name, {"count": 0, "sum": 0.0})
            count = value["count"] - prev["count"]
            total = value["sum"] - prev["sum"]
            if count:
                obs[name] = {
                    "count": count,
                    "sum": round(total, 6),
                    "mean": round(total / count, 6),
                }
        else:
            prev = before.get(name, 0)
            delta = value - prev if isinstance(prev, (int, float)) else value
            if delta:
                obs[name] = round(delta, 3) if isinstance(delta, float) else delta
    print(json.dumps({"bench": leg, "obs": obs}))


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which == "_tensor_replay_child":
        _tensor_replay_child()  # subprocess arm of the tensor_replay leg
        return
    legs = list(LEGS) if which == "all" else [which]
    for leg in legs:
        before = _obs_snapshot()
        LEGS[leg]()
        _emit_obs(leg, before)


if __name__ == "__main__":
    main()
