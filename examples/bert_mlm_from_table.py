"""BERT MLM training fed from a lakehouse text table (BASELINE.json config 3
in miniature): tokenized C4-style rows stored in a hash-bucketed table,
streamed through the sharded data plane into a dp/tp/sp-parallel train step
with ring attention.

Run (CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/bert_mlm_from_table.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lakesoul_tpu.utils import honor_platform_env

honor_platform_env()

import numpy as np
import pyarrow as pa


def main() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.models.bert import BertConfig
    from lakesoul_tpu.models.train import make_bert_train_state, make_bert_train_step
    from lakesoul_tpu.parallel.mesh import make_mesh

    plan = make_mesh(jax.devices())
    print(f"mesh: dp={plan.dp} tp={plan.tp} sp={plan.sp}")

    cfg = BertConfig(
        vocab_size=512,
        hidden=64 * plan.tp,
        layers=2,
        heads=2 * plan.tp,
        ff=128 * plan.tp,
        max_len=32 * max(plan.sp, 1),
    )
    T = cfg.max_len
    B = 2 * plan.dp

    # "C4" rows: pre-tokenized sequences in a PK table
    catalog = LakeSoulCatalog(tempfile.mkdtemp(prefix="lakesoul_c4_"))
    rng = np.random.default_rng(0)
    n_docs = 64
    tokens = rng.integers(4, cfg.vocab_size, (n_docs, T)).astype(np.int32)
    schema = pa.schema(
        [("doc_id", pa.int64()), ("tokens", pa.list_(pa.int32(), T))]
    )
    t = catalog.create_table("c4", schema, primary_keys=["doc_id"], hash_bucket_num=4)
    t.write_arrow(
        pa.table(
            {
                "doc_id": np.arange(n_docs),
                "tokens": pa.FixedSizeListArray.from_arrays(tokens.reshape(-1), T),
            },
            schema=schema,
        )
    )

    params, opt_state, tx, shardings = make_bert_train_state(cfg, plan, lr=1e-3)
    step = make_bert_train_step(cfg, plan, tx, shardings)
    batch_sharding = NamedSharding(plan.mesh, P("dp", "sp"))

    def transform(b):
        ids = np.stack(b["tokens"])  # [rows, T]
        labels = np.full_like(ids, -100)
        mask_pos = rng.random(ids.shape) < 0.15
        labels[mask_pos] = ids[mask_pos]
        masked = ids.copy()
        masked[mask_pos] = 3  # [MASK]
        return {
            "ids": masked.astype(np.int32),
            "labels": labels.astype(np.int32),
            "mask": np.ones_like(ids, dtype=bool),
        }

    it = t.scan().batch_size(B).to_jax_iter(transform=transform, sharding=batch_sharding)
    losses = []
    for i, batch in enumerate(it):
        params, opt_state, loss = step(
            params, opt_state, batch["ids"], batch["labels"], batch["mask"]
        )
        losses.append(float(loss))
    print(f"{len(losses)} steps, loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main()
