"""Multi-engine access through the Arrow Flight SQL gateway.

The reference's answer to "other engines" is its FlightSqlService
(rust/lakesoul-flight): Spark/Presto/any ADBC or JDBC client speaks the
standard Flight SQL protocol to the lakehouse.  This example runs that
loop here: start the gateway, then drive it with the SAME wire messages an
ADBC driver sends — statement queries, bulk ingest with an exactly-once
transaction id, prepared statements with bound parameters, catalog
metadata — plus a federated external table joined against lakehouse data.

Run:  python examples/flight_sql_gateway.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service.flight_sql import FlightSqlClient, LakeSoulFlightSqlServer


def main() -> None:
    catalog = LakeSoulCatalog(tempfile.mkdtemp(prefix="lakesoul_wh_"))
    orders = catalog.create_table(
        "orders",
        pa.schema([("id", pa.int64()), ("region", pa.string()), ("amt", pa.float64())]),
        primary_keys=["id"],
        hash_bucket_num=4,
    )
    orders.write_arrow(
        pa.table(
            {
                "id": np.arange(1000),
                "region": np.where(np.arange(1000) % 3 == 0, "emea", "apac"),
                "amt": np.round(np.random.default_rng(0).random(1000) * 100, 2),
            }
        )
    )

    server = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0")
    try:
        client = FlightSqlClient(f"grpc://127.0.0.1:{server.port}")

        # connection probe, then a statement query
        assert client.execute("SELECT 1").num_rows == 1
        top = client.execute(
            "SELECT region, count(*) AS n, sum(amt) AS total FROM orders"
            " GROUP BY region ORDER BY total DESC"
        )
        print("regions:", top.to_pydict())

        # DML with a row count back in the DoPut metadata
        n = client.execute_update("UPDATE orders SET amt = 0 WHERE amt < 1")
        print("zeroed rows:", n)

        # bulk ingest; replaying the same transaction id is a no-op
        events = pa.table({"ts": np.arange(100), "kind": ["click"] * 100})
        txn = b"job-42:epoch-1"
        print("ingested:", client.ingest("events", events, transaction_id=txn))
        client.ingest("events", events, transaction_id=txn)  # exactly-once
        assert client.execute("SELECT count(*) AS c FROM events").column(
            "c"
        ).to_pylist() == [100]

        # prepared statement with positional parameters
        handle = client.prepare("SELECT amt FROM orders WHERE id = ?")
        for want in (3, 7):
            row = client.execute_prepared(handle, params=[want])
            print(f"order {want} amt:", row.column("amt").to_pylist())
        client.close_prepared(handle)

        # explicit transactions — the flow an ADBC driver with
        # autocommit=False issues: begin → staged ingest → commit; a
        # rolled-back transaction leaves no rows behind
        txn2 = client.begin_transaction()
        client.ingest(
            "events", pa.table({"ts": np.arange(100, 150), "kind": ["view"] * 50}),
            transaction_id=txn2,
        )
        assert client.execute("SELECT count(*) AS c FROM events").column(
            "c"
        ).to_pylist() == [100]  # staged, not visible yet
        client.commit(txn2)
        assert client.execute("SELECT count(*) AS c FROM events").column(
            "c"
        ).to_pylist() == [150]
        txn3 = client.begin_transaction()
        client.ingest(
            "events", pa.table({"ts": [999], "kind": ["oops"]}),
            transaction_id=txn3,
        )
        client.rollback(txn3)
        assert client.execute(
            "SELECT count(*) AS c FROM events WHERE ts = 999"
        ).column("c").to_pylist() == [0]
        print("transactions: commit visible, rollback clean")

        # the BI-tool surface: outer joins, CAST, OFFSET pagination
        page2 = client.execute(
            "SELECT cast(id AS string) AS sid, amt FROM orders"
            " ORDER BY id LIMIT 5 OFFSET 5"
        )
        assert page2.column("sid").to_pylist() == ["5", "6", "7", "8", "9"]
        client.execute_update(
            "CREATE TABLE regions (region string, mgr string)"
        )
        client.execute_update(
            "INSERT INTO regions VALUES ('emea', 'ana'), ('amer', 'bo')"
        )
        unmanaged = client.execute(
            "SELECT count(*) AS c FROM orders"
            " FULL OUTER JOIN regions ON orders.region = regions.region"
            " WHERE mgr IS NULL"
        )
        # every apac order has no manager row; amer has no orders
        assert unmanaged.column("c").to_pylist()[0] > 0
        print("outer join over the wire:", unmanaged.column("c").to_pylist())

        # catalog metadata, as a JDBC driver would browse it
        print("tables:", client.get_tables().column("table_name").to_pylist())
        print(
            "orders PK:",
            client.get_primary_keys("orders").column("column_name").to_pylist(),
        )

        # federation: an external source joins lakehouse tables server-side
        from lakesoul_tpu.sql import SqlSession

        session = SqlSession(catalog)
        session.register_external(
            "fx", pa.table({"region": ["emea", "apac"], "rate": [1.1, 0.9]})
        )
        fx = session.execute(
            "SELECT o.region, sum(amt * rate) AS usd FROM orders o"
            " JOIN fx ON o.region = fx.region GROUP BY o.region ORDER BY usd DESC"
        )
        print("fx-adjusted:", fx.to_pydict())
        client.close()
    finally:
        server.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
