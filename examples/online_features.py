"""Online feature pipeline e2e (BASELINE.json config 5).

Debezium-style CDC events stream into a feature table with exactly-once
checkpoints; a resumable follow() consumer turns each new commit into
device-resident feature updates — the Flink-CDC → online-features loop of
the reference, on the TPU stack.

Run: python examples/online_features.py [--warehouse DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lakesoul_tpu.utils import honor_platform_env

honor_platform_env()

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--warehouse", default=None)
    args = ap.parse_args()
    wh = args.warehouse or tempfile.mkdtemp(prefix="lakesoul_feat_")

    import jax.numpy as jnp

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.meta.client import (
        follow_cursors_from_json,
        follow_cursors_to_json,
    )
    from lakesoul_tpu.meta.entity import now_millis
    from lakesoul_tpu.streaming import DebeziumJsonConsumer

    catalog = LakeSoulCatalog(wh)
    consumer = DebeziumJsonConsumer(catalog, primary_keys={"user_features": ["uid"]})

    def ev(op, row):
        return {"op": op, "after": row, "source": {"table": "user_features"}}

    # epoch 1: initial facts
    rng = np.random.default_rng(0)
    for uid in range(32):
        consumer.consume(
            ev("c", {"uid": uid, "clicks": int(rng.integers(0, 50)),
                     "spend": round(float(rng.gamma(2.0, 5.0)), 2)})
        )
    consumer.checkpoint(1)

    table = catalog.table("user_features")
    cursors = catalog.client.init_follow_cursors("user_features", now_millis())
    feature_bank = jnp.zeros((32, 2))  # device-resident feature matrix

    stop = threading.Event()
    updates = {"rows": 0}

    cdc_col = table.info.cdc_column

    def serve():
        nonlocal feature_bank
        # with_cdc_deletes: consume row KINDS, not just surviving rows — a
        # delete must CLEAR its uid's features, not leave them stale
        for batch in table.scan().with_cdc_deletes().follow(
            poll_interval=0.05, stop_event=stop, cursors=cursors
        ):
            uids = np.asarray(batch.column("uid"))
            kinds = np.asarray(batch.column(cdc_col).to_pylist(), dtype=object)
            feats = np.stack(
                [
                    np.asarray(batch.column("clicks"), dtype=np.float32),
                    np.asarray(batch.column("spend"), dtype=np.float32),
                ],
                axis=1,
            )
            # grow the bank for new uids (jax .at[] would silently clamp
            # out-of-range indices onto the last row)
            top = int(uids.max()) + 1
            if top > feature_bank.shape[0]:
                pad = jnp.zeros((top - feature_bank.shape[0], 2))
                feature_bank = jnp.concatenate([feature_bank, pad])
            live = kinds != "delete"
            if live.any():
                feature_bank = feature_bank.at[uids[live]].set(jnp.asarray(feats[live]))
            if (~live).any():
                feature_bank = feature_bank.at[uids[~live]].set(0.0)
            updates["rows"] += len(uids)
            if updates["rows"] >= 9:
                stop.set()

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    # epoch 2: live updates + a delete arrive while the consumer runs
    for uid in (3, 7, 11, 19):
        consumer.consume(ev("u", {"uid": uid, "clicks": 999, "spend": 123.45}))
    for uid in (40, 41, 42, 43):
        consumer.consume(ev("c", {"uid": uid, "clicks": 1, "spend": 1.0}))
    consumer.consume(
        {"op": "d", "before": {"uid": 5, "clicks": 0, "spend": 0.0},
         "source": {"table": "user_features"}}
    )
    consumer.checkpoint(2)
    t.join(timeout=20)
    stop.set()

    # the stream position survives restarts alongside any app checkpoint
    state = follow_cursors_to_json(cursors)
    assert follow_cursors_from_json(state).keys() == cursors.keys()

    hot = float(feature_bank[3, 0])
    gone = float(feature_bank[5, 0])
    print(f"online features updated: {updates['rows']} rows streamed,"
          f" uid=3 clicks={hot:.0f}, deleted uid=5 clicks={gone:.0f}")
    assert hot == 999.0, "live update did not reach the feature bank"
    assert gone == 0.0, "delete did not clear the feature bank"


if __name__ == "__main__":
    main()
