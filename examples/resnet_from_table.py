"""ResNet training fed from an image table (BASELINE.json config 2 in
miniature): encoded image tensors stored in a hash-bucketed lakehouse table,
sharded over the data-parallel axis and streamed into a jitted ResNet train
step.

Run (CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/resnet_from_table.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lakesoul_tpu.utils import honor_platform_env

honor_platform_env()

import numpy as np
import pyarrow as pa

IMG = 32  # miniature "ImageNet" resolution
NUM_CLASSES = 10


def main() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.models.resnet import ResNetConfig, init_resnet_params
    from lakesoul_tpu.models.train import make_resnet_train_step
    from lakesoul_tpu.parallel.mesh import make_mesh

    plan = make_mesh(jax.devices())
    B = 4 * plan.dp  # data-parallel batch

    # image table: uint8-encoded pixels as fixed-size lists + labels
    catalog = LakeSoulCatalog(tempfile.mkdtemp(prefix="lakesoul_imgs_"))
    rng = np.random.default_rng(0)
    n = 128
    pixels = rng.integers(0, 256, (n, IMG * IMG * 3), dtype=np.uint8)
    schema = pa.schema(
        [
            ("image_id", pa.int64()),
            ("pixels", pa.list_(pa.uint8(), IMG * IMG * 3)),
            ("label", pa.int32()),
        ]
    )
    t = catalog.create_table("imagenet_mini", schema, primary_keys=["image_id"],
                             hash_bucket_num=4)
    t.write_arrow(
        pa.table(
            {
                "image_id": np.arange(n),
                "pixels": pa.FixedSizeListArray.from_arrays(pixels.reshape(-1), IMG * IMG * 3),
                "label": rng.integers(0, NUM_CLASSES, n).astype(np.int32),
            },
            schema=schema,
        )
    )

    cfg = ResNetConfig(num_classes=NUM_CLASSES, width=8, dtype="float32")
    params = init_resnet_params(cfg, jax.random.key(0))
    tx = optax.sgd(0.05)
    opt_state = tx.init(params)
    step = make_resnet_train_step(cfg, tx, plan)
    data_sharding = NamedSharding(plan.mesh, P("dp"))

    def transform(b):
        imgs = np.stack(b["pixels"]).reshape(-1, IMG, IMG, 3).astype(np.float32) / 255.0
        return {"x": imgs, "y": b["label"].astype(np.int32)}

    losses = []
    # auto_shard: on a multi-host pod each process reads only its scan units
    it = (
        t.scan().auto_shard().batch_size(B)
        .to_jax_iter(transform=transform, sharding=data_sharding)
    )
    for batch in it:
        params, opt_state, loss = step(params, opt_state, batch["x"], batch["y"])
        losses.append(float(loss))
    print(f"{len(losses)} steps, loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main()
