"""Titanic-style e2e example (BASELINE.json config 1): hash-partitioned
table → LakeSoulScan → to_jax_iter → 2-layer MLP train loop.

Run: python examples/titanic_mlp.py [--warehouse DIR]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lakesoul_tpu.utils import honor_platform_env

honor_platform_env()
import tempfile

import numpy as np
import pyarrow as pa


def make_synthetic_titanic(n: int = 2000, seed: int = 0) -> pa.Table:
    """Synthetic passengers with a survival rule the MLP can learn."""
    rng = np.random.default_rng(seed)
    pclass = rng.integers(1, 4, n).astype(np.int32)
    age = np.clip(rng.normal(30, 14, n), 1, 80).astype(np.float32)
    fare = (rng.gamma(2.0, 15.0, n) * (4 - pclass)).astype(np.float32)
    sex = rng.integers(0, 2, n).astype(np.int32)  # 1 = female
    logits = 1.8 * sex - 0.9 * (pclass - 2) - 0.02 * (age - 30) + 0.01 * fare
    survived = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.int32)
    return pa.table(
        {
            "passenger_id": np.arange(n, dtype=np.int64),
            "pclass": pclass,
            "age": age,
            "fare": fare,
            "sex": sex,
            "survived": survived,
        }
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--warehouse", default=None)
    parser.add_argument("--epochs", type=int, default=5)
    args = parser.parse_args()

    import jax
    import optax

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.models.mlp import init_mlp_params, mlp_forward
    from lakesoul_tpu.models.train import make_mlp_train_step

    warehouse = args.warehouse or tempfile.mkdtemp(prefix="lakesoul_titanic_")
    catalog = LakeSoulCatalog(warehouse)

    data = make_synthetic_titanic()
    if not catalog.table_exists("titanic"):
        t = catalog.create_table(
            "titanic", data.schema, primary_keys=["passenger_id"], hash_bucket_num=4
        )
        t.write_arrow(data)
        # a later correction wave exercises merge-on-read, like re-ingests do
        t.upsert(data.slice(0, 200))
    else:
        t = catalog.table("titanic")

    feature_cols = ["pclass", "age", "fare", "sex"]

    def transform(b):
        x = np.stack([b[c].astype(np.float32) for c in feature_cols], axis=1)
        x = (x - x.mean(0)) / (x.std(0) + 1e-6)
        return {"x": x, "y": b["survived"].astype(np.int32)}

    params = init_mlp_params(jax.random.key(0), len(feature_cols), hidden=64)
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    step, _ = make_mlp_train_step(tx)

    for epoch in range(args.epochs):
        losses = []
        scan = t.scan().batch_size(256).auto_shard()
        for batch in scan.to_jax_iter(transform=transform, drop_remainder=False):
            params, opt_state, loss = step(params, opt_state, batch["x"], batch["y"])
            losses.append(float(loss))
        print(f"epoch {epoch}: loss={np.mean(losses):.4f}")

    # final train accuracy
    full = transform(
        {c: data.column(c).to_numpy(zero_copy_only=False) for c in feature_cols + ["survived"]}
    )
    import jax.numpy as jnp

    preds = np.asarray(jnp.argmax(mlp_forward(params, jnp.asarray(full["x"])), axis=1))
    acc = (preds == full["y"]).mean()
    print(f"train accuracy: {acc:.3f}")
    assert acc > 0.7, "model failed to learn"


if __name__ == "__main__":
    main()
