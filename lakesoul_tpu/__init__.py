"""lakesoul_tpu — TPU-native lakehouse & AI data framework.

A brand-new framework with the capabilities of LakeSoul (ACID lakehouse tables on
object storage, PostgreSQL/SQLite-backed metadata, LSM-style upserts on
hash-bucketed primary-key tables with merge-on-read, compaction, snapshot and
incremental reads, CDC ingest, RBAC, and an IVF+RaBitQ ANN vector index),
designed idiomatically for JAX/XLA/Pallas on TPU:

- The data plane delivers merged Arrow RecordBatches straight into TPU HBM via
  double-buffered ``jax.device_put`` prefetch.
- Tables shard across a TPU pod by ``jax.process_index()`` over
  (range-partition, hash-bucket) scan units — no torch.distributed in the loop.
- The ANN vector scan (packed RaBitQ codes, brute force, top-k) runs on-chip
  via Pallas/XLA kernels on the MXU.
- Merge/bucketing hot loops run in a C++ native core with vectorized-numpy
  fallbacks; hashing is byte-compatible with Spark Murmur3 (seed 42) so tables
  interoperate with reference-written data.
"""

__version__ = "0.1.0"


def __getattr__(name):
    # lazy imports keep `import lakesoul_tpu` cheap (no jax/pyarrow load)
    if name in ("LakeSoulCatalog", "LakeSoulTable", "LakeSoulScan"):
        from lakesoul_tpu import catalog

        return getattr(catalog, name)
    raise AttributeError(name)

__all__ = [
    "LakeSoulCatalog",
    "LakeSoulTable",
    "LakeSoulScan",
    "__version__",
]
