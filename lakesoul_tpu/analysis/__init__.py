"""lakelint: project-native static analysis + runtime lock-order detection.

Three complementary layers:

- :mod:`engine` + :mod:`rules` — AST lint over the package with
  project-specific rules (thread discipline, lock-held blocking calls,
  stage determinism, reader lifetimes, env-var docs, metric naming, sqlite
  scope), a checked-in ``baseline.json`` and inline
  ``# lakelint: ignore[rule]`` pragmas.  CLI:
  ``python -m lakesoul_tpu.analysis`` (also installed as ``lakesoul-lint``
  and the console's ``lint`` command); CI gate:
  ``tests/test_analysis_clean.py``.
- :mod:`callgraph` + :mod:`dataflow` — the interprocedural layer: a
  project-wide call graph (conservative unknown edges for dynamic
  dispatch) and a forward taint framework, powering the whole-program
  rules (``rbac-gate-reachability``, ``taint-path-segments``,
  ``transitive-lock-held-call``, ``interprocedural-unclosed-reader``).
  Output/CI upgrades ride along: ``--format sarif`` (:mod:`sarif`) and the
  diff-aware ``--diff BASE`` gate (:mod:`gitdiff`).
- :mod:`lockgraph` — opt-in (``LAKESOUL_LOCKCHECK=1``) instrumented
  ``Lock``/``RLock`` that records the per-thread acquisition graph at
  runtime, flags lock-order cycles (potential deadlock) and
  lock-held-across-``pool.submit``; wired into the test suite via a
  conftest fixture.
"""

from lakesoul_tpu.analysis.engine import (
    Baseline,
    EngineError,
    Finding,
    Rule,
    default_baseline_path,
    run,
    run_repo,
)

__all__ = [
    "Baseline",
    "EngineError",
    "Finding",
    "Rule",
    "default_baseline_path",
    "run",
    "run_repo",
]
