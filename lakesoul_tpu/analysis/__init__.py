"""lakelint: project-native static analysis + runtime lock-order and
retrace detection.

Four complementary layers:

- :mod:`engine` + :mod:`rules` — AST lint over the package with
  project-specific rules (thread discipline, lock-held blocking calls,
  stage determinism, reader lifetimes, env-var docs, metric naming, sqlite
  scope), a checked-in ``baseline.json`` and inline
  ``# lakelint: ignore[rule]`` pragmas.  CLI:
  ``python -m lakesoul_tpu.analysis`` (also installed as ``lakesoul-lint``
  and the console's ``lint`` command); CI gate:
  ``tests/test_analysis_clean.py``.
- :mod:`callgraph` + :mod:`dataflow` — the interprocedural layer: a
  project-wide call graph (conservative unknown edges for dynamic
  dispatch) and a forward taint framework, powering the whole-program
  rules (``rbac-gate-reachability``, ``taint-path-segments``,
  ``transitive-lock-held-call``, ``interprocedural-unclosed-reader``).
  Output/CI upgrades ride along: ``--format sarif`` (:mod:`sarif`) and the
  diff-aware ``--diff BASE`` gate (:mod:`gitdiff`).
- :mod:`rules.jaxtpu` — the device pack: five JAX/TPU trace-safety rules
  (``trace-impure-call``, ``trace-host-sync``, ``tpu-dtype-width``,
  ``jit-static-arg-shape``, ``pallas-blockspec``) over a shared device
  index (jit entries, pallas kernels, the traced-function closure) and
  the taint framework's device-value lattice.
- :mod:`threadroots` + :mod:`rules.races` + :mod:`rules.lifetime` — the
  concurrency-soundness pack: thread-root inference over the call graph
  (Thread targets, pool submissions, pipeline stages, ``do_*`` handlers)
  feeding Eraser-style static locksets (``shared-state-race``,
  ``racy-check-then-act``) and the zero-copy buffer-lifetime rules
  (``view-escapes-release``, ``ring-aliasing``).
- :mod:`rules.boundedness` + :mod:`leakcheck` — the resource-boundedness
  pack: five lifecycle rules over the shared thread-root/call-graph
  indexes (``unbounded-queue``, ``unbounded-growth``,
  ``thread-lifecycle``, ``child-reap``, ``shm-debris``) paired with the
  runtime leak detector — ``LAKESOUL_LEAKCHECK=1`` patches the creation
  seams (``Thread.start``, ``Popen``, ``mkdtemp``, atomicio staging) and
  diffs per-scope fd/thread/child/artifact/heap inventories, reporting
  each leak with its creation stack; the ``benchmarks/micro.py soak``
  leg gates on flat slopes over repeated open→scan→serve→close cycles.
- :mod:`lockgraph` / :mod:`tracecheck` / :mod:`racecheck` /
  :mod:`fscheck` / :mod:`txncheck` — the opt-in runtime detectors:
  ``LAKESOUL_LOCKCHECK=1`` instruments ``Lock``/``RLock`` to record the
  per-thread acquisition graph (lock-order cycles,
  lock-held-across-``pool.submit``); ``LAKESOUL_TRACECHECK=1`` wraps jit
  entry points to count distinct abstract signatures per function and
  flags functions that recompile beyond their budget;
  ``LAKESOUL_RACECHECK=1`` runs Eraser lockset tracking on the
  instrumented hot classes' field writes and arms the collate ring's
  canary/poison mode; ``LAKESOUL_FSCHECK=1`` replays every publication's
  crash prefixes ALICE-style at teardown; ``LAKESOUL_TXNCHECK=1``
  replays committed metadata transactions under READ COMMITTED
  interleavings.  All are wired into the test suite via conftest
  fixtures, and all record violations rather than raise.
"""

from lakesoul_tpu.analysis.engine import (
    Baseline,
    EngineError,
    Finding,
    Rule,
    default_baseline_path,
    run,
    run_repo,
)

__all__ = [
    "Baseline",
    "EngineError",
    "Finding",
    "Rule",
    "default_baseline_path",
    "run",
    "run_repo",
]
