"""lakelint CLI.

::

    python -m lakesoul_tpu.analysis                 # lint the package
    python -m lakesoul_tpu.analysis --json          # machine-readable
    python -m lakesoul_tpu.analysis path/to/file.py # lint specific paths
    python -m lakesoul_tpu.analysis --write-baseline  # absorb current findings

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = bad usage.
Stale baseline entries (suppressions that no longer match anything) are
reported on stderr so the baseline only ever shrinks — they do not fail the
run, the CI gate test does that.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from lakesoul_tpu.analysis.engine import (
    Baseline,
    default_baseline_path,
    package_root,
    run,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="lakesoul-lint",
        description="project-native static analysis for lakesoul_tpu",
    )
    parser.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    parser.add_argument("--json", action="store_true", help="JSON findings on stdout")
    parser.add_argument(
        "--baseline",
        default=str(default_baseline_path()),
        help="baseline file (default: analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline entirely"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current findings into the baseline (reasons start as "
        "TODO and must be justified before review)",
    )
    args = parser.parse_args(argv)

    paths = [Path(p) for p in args.paths] or None
    baseline = (
        Baseline([]) if args.no_baseline else Baseline.load(Path(args.baseline))
    )

    if args.write_baseline:
        findings, _ = run(paths, baseline=Baseline([]))
        payload = {
            "version": 1,
            "suppressions": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "message": f.message,
                    "reason": "TODO: justify or fix",
                }
                for f in findings
            ],
        }
        Path(args.baseline).write_text(
            json.dumps(payload, indent=2) + "\n", encoding="utf-8"
        )
        print(f"wrote {len(findings)} suppressions to {args.baseline}")
        return 0

    findings, baseline = run(paths, baseline=baseline)

    if args.json:
        print(
            json.dumps(
                [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "line": f.line,
                        "message": f.message,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s)")
        else:
            print(f"clean: no unsuppressed findings under {package_root().name}/")

    for stale in baseline.stale_entries():
        print(
            "stale baseline entry (fixed? delete it): "
            f"[{stale['rule']}] {stale['path']}: {stale['message']}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
