"""lakelint CLI.

::

    python -m lakesoul_tpu.analysis                  # lint the package
    python -m lakesoul_tpu.analysis --format json    # machine-readable
    python -m lakesoul_tpu.analysis --format sarif   # SARIF 2.1.0 log
    python -m lakesoul_tpu.analysis --sarif          # alias for the above
    python -m lakesoul_tpu.analysis --rule raw-thread --rule sqlite-scope
    python -m lakesoul_tpu.analysis --diff origin/main   # changed lines only
    python -m lakesoul_tpu.analysis path/to/file.py  # lint specific paths
    python -m lakesoul_tpu.analysis --write-baseline # absorb current findings

Exit status contract (mirrored by the console ``lint`` command and relied
on by CI): 0 = no unsuppressed findings, 1 = findings, 2 = the analyzer
itself failed (unknown --rule id, unreadable baseline, git diff failure,
bad usage).  Stale baseline entries (suppressions that no longer match
anything) are reported on stderr so the baseline only ever shrinks — they
do not fail the run, the CI gate test does that.

``--diff BASE`` resolves findings against ``git diff BASE``: only findings
on changed/added lines are reported, so a new rule can gate strictly on
new code while legacy findings live in the baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from lakesoul_tpu.analysis.engine import (
    Baseline,
    EngineError,
    default_baseline_path,
    package_root,
    run,
)

FORMATS = ("text", "json", "sarif")


def _select_rules(rule_ids: list[str] | None):
    from lakesoul_tpu.analysis.rules import all_rules

    rules = all_rules()
    if not rule_ids:
        return rules
    known = {r.id for r in rules}
    unknown = [r for r in rule_ids if r not in known]
    if unknown:
        raise EngineError(
            f"unknown rule id(s): {', '.join(unknown)} — known rules: "
            + ", ".join(sorted(known))
        )
    wanted = set(rule_ids)
    return [r for r in rules if r.id in wanted]


def render(findings, rules, fmt: str) -> str:
    """Findings in the requested format (shared with the console's ``lint``
    command so both surfaces emit identical bytes)."""
    if fmt == "json":
        return json.dumps(
            [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            indent=2,
        )
    if fmt == "sarif":
        from lakesoul_tpu.analysis.sarif import to_sarif

        return json.dumps(to_sarif(findings, rules), indent=2)
    lines = [f.render() for f in findings]
    if findings:
        lines.append(f"\n{len(findings)} finding(s)")
    else:
        lines.append(f"clean: no unsuppressed findings under {package_root().name}/")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="lakesoul-lint",
        description="project-native static analysis for lakesoul_tpu",
    )
    parser.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    parser.add_argument(
        "--format", choices=FORMATS, default=None,
        help="findings format on stdout (default: text)",
    )
    parser.add_argument(
        "--json", action="store_true", help="alias for --format json"
    )
    parser.add_argument(
        "--sarif", action="store_true", help="alias for --format sarif"
    )
    parser.add_argument(
        "--rule", action="append", metavar="ID", dest="rules",
        help="run only this rule id (repeatable)",
    )
    parser.add_argument(
        "--diff", metavar="BASE", default=None,
        help="report only findings on lines changed since the git ref BASE "
        "(strict-on-new-code mode; legacy findings stay in the baseline)",
    )
    parser.add_argument(
        "--baseline",
        default=str(default_baseline_path()),
        help="baseline file (default: analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline entirely"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current findings into the baseline (reasons start as "
        "TODO and must be justified before review)",
    )
    args = parser.parse_args(argv)

    fmt = args.format or ("json" if args.json else "sarif" if args.sarif else "text")
    paths = [Path(p) for p in args.paths] or None

    try:
        rules = _select_rules(args.rules)
        baseline = (
            Baseline([]) if args.no_baseline else Baseline.load(Path(args.baseline))
        )

        if args.write_baseline:
            if args.rules:
                raise EngineError(
                    "--write-baseline with --rule would overwrite the "
                    "baseline with ONLY the filtered rule's findings, "
                    "deleting every other rule's justified suppressions — "
                    "run it without --rule"
                )
            findings, _ = run(paths, rules=rules, baseline=Baseline([]))
            payload = {
                "version": 1,
                "suppressions": [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "message": f.message,
                        "reason": "TODO: justify or fix",
                    }
                    for f in findings
                ],
            }
            Path(args.baseline).write_text(
                json.dumps(payload, indent=2) + "\n", encoding="utf-8"
            )
            print(f"wrote {len(findings)} suppressions to {args.baseline}")
            return 0

        findings, baseline = run(paths, rules=rules, baseline=baseline)

        if args.diff is not None:
            from lakesoul_tpu.analysis.gitdiff import filter_to_diff

            findings = filter_to_diff(
                findings, args.diff, package_root().parent
            )
    except EngineError as e:
        print(f"lakesoul-lint: engine error: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        print(f"lakesoul-lint: engine error: {e}", file=sys.stderr)
        return 2

    print(render(findings, rules, fmt))

    if not args.rules:  # a rule filter makes other rules' entries look stale
        for stale in baseline.stale_entries():
            print(
                "stale baseline entry (fixed? delete it): "
                f"[{stale['rule']}] {stale['path']}: {stale['message']}",
                file=sys.stderr,
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
