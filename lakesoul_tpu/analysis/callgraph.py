"""Project-wide call graph over the shared AST walk.

PR 3's lakelint sees one function at a time, so a Flight handler that
mutates the catalog through a helper that skips ``_check()`` lints clean.
This module gives rules whole-program reach: every module's defs (module
functions, class methods, nested functions) become nodes, and every call
site becomes an edge — *resolved* to a node when name/import/self analysis
can pin the target, or recorded as an **unknown** edge (dynamic dispatch,
duck-typed receivers, builtins) so rules can stay conservative instead of
silently wrong.

Resolution is deliberately syntactic, not a type system:

- plain names resolve through the enclosing function's nested defs, the
  module's top-level defs, then ``from x import y`` / ``import x as y``
  bindings into other *project* modules;
- ``ClassName(...)`` resolves to ``ClassName.__init__`` when defined;
- ``self.m(...)`` / ``cls.m(...)`` resolve through the enclosing class,
  then its project-resolvable base classes (the Flight SQL server's
  handlers call ``self._check`` defined on the base gateway class);
- ``modalias.f(...)`` resolves when ``modalias`` is an imported project
  module;
- everything else (``obj.method(...)`` on locals, attribute chains like
  ``self.catalog.create_table``) becomes an unknown edge that keeps the
  receiver text and attribute name, so rules can pattern-match what the
  resolver cannot prove.

Calls inside *nested* function bodies are attributed to the nested
function, not the enclosing one — a closure's body runs later, outside the
lexical context (lock held, RBAC gate passed) being analyzed.

The graph is built once per :class:`~lakesoul_tpu.analysis.engine.Project`
and cached (``Project.callgraph()``); with ~90 files it costs one extra
pass over the already-shared AST walks (~0.2 s, tracked by the
``benchmarks/micro.py lint`` leg's 10 s budget).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Iterator

from lakesoul_tpu.analysis.engine import Module, Project, dotted_name

__all__ = ["CallEdge", "FuncInfo", "CallGraph", "iter_calls_in_order"]


def _module_dotted(relpath: str) -> str:
    """``lakesoul_tpu/service/flight.py`` → ``lakesoul_tpu.service.flight``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def iter_calls_in_order(body: Iterable[ast.stmt]) -> Iterator[ast.Call]:
    """Calls lexically inside ``body`` in source order, NOT descending into
    nested function/lambda bodies (their calls belong to the nested node)."""
    calls: list[ast.Call] = []
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            calls.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return iter(calls)


@dataclass(frozen=True)
class CallEdge:
    """One call site.  ``callee`` is a qualified name (``relpath::Func`` or
    ``relpath::Class.method``) when resolved, else None with ``receiver``/
    ``attr`` preserving what the source said."""

    caller: str
    callee: str | None
    line: int
    col: int
    raw: str  # the dotted callee text as written ("self.catalog.create_table")
    receiver: str | None  # dotted receiver for attribute calls, else None
    attr: str  # terminal name being called ("create_table", "sleep", "f")
    node: ast.Call = field(compare=False, hash=False, repr=False)

    @property
    def resolved(self) -> bool:
        return self.callee is not None


@dataclass
class FuncInfo:
    """One function/method definition node in the graph."""

    qname: str  # "<relpath>::Outer.inner" — '.'-joined def chain
    relpath: str
    name: str  # the chain without the path ("Class.method", "f.helper")
    node: "ast.FunctionDef | ast.AsyncFunctionDef"
    class_qname: str | None  # "<relpath>::Class" for methods
    is_method: bool

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        if a.vararg:
            names.append(a.vararg.arg)
        names += [p.arg for p in a.kwonlyargs]
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


@dataclass
class _ClassInfo:
    qname: str
    relpath: str
    name: str
    node: ast.ClassDef
    methods: dict[str, str]  # method name → func qname
    base_names: list[str]  # raw base-class dotted names, resolved lazily


class CallGraph:
    """functions: qname → FuncInfo; edges: caller qname → [CallEdge].

    Module-level code is modeled as a pseudo-function ``<relpath>::<module>``
    so import-time calls still have a caller node.
    """

    def __init__(self) -> None:
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, _ClassInfo] = {}
        self.edges: dict[str, list[CallEdge]] = {}
        # module dotted name → relpath (project modules only)
        self._mod_by_dotted: dict[str, str] = {}
        # relpath → {local name: ("mod", dotted) | ("sym", dotted, symbol)}
        self._imports: dict[str, dict[str, tuple]] = {}
        # relpath → {top-level def/class name: qname}
        self._toplevel: dict[str, dict[str, str]] = {}
        self._resolved_bases: dict[str, list[str]] = {}

    # ------------------------------------------------------------- building

    @classmethod
    def build(cls, project: Project) -> "CallGraph":
        g = cls()
        for mod in project.modules:
            g._mod_by_dotted[_module_dotted(mod.relpath)] = mod.relpath
        for mod in project.modules:
            g._collect_defs(mod)
        for mod in project.modules:
            g._collect_edges(mod)
        return g

    def _collect_defs(self, mod: Module) -> None:
        rel = mod.relpath
        self._imports[rel] = imports = {}
        self._toplevel[rel] = top = {}
        pkg = _module_dotted(rel)

        def record_import(node: ast.AST) -> None:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    imports[local] = ("mod", target)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module's package
                    parts = pkg.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    imports[local] = ("sym", base, alias.name)

        def walk_defs(body: list[ast.stmt], prefix: str, class_q: str | None) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    record_import(stmt)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    chain = f"{prefix}.{stmt.name}" if prefix else stmt.name
                    q = f"{rel}::{chain}"
                    self.functions[q] = FuncInfo(
                        q, rel, chain, stmt, class_q, class_q is not None
                    )
                    if not prefix:
                        top[stmt.name] = q
                    if class_q is not None and "." not in chain.removeprefix(
                        class_q.split("::", 1)[1] + "."
                    ):
                        self.classes[class_q].methods.setdefault(stmt.name, q)
                    # nested defs: methods of nested classes / local helpers
                    walk_defs(stmt.body, chain, None)
                elif isinstance(stmt, ast.ClassDef):
                    chain = f"{prefix}.{stmt.name}" if prefix else stmt.name
                    cq = f"{rel}::{chain}"
                    bases = [b for b in (dotted_name(x) for x in stmt.bases) if b]
                    self.classes[cq] = _ClassInfo(cq, rel, chain, stmt, {}, bases)
                    if not prefix:
                        top[stmt.name] = cq
                    walk_defs(stmt.body, chain, cq)
                else:
                    # imports can hide inside try/if at module level
                    for sub in ast.walk(stmt):
                        if isinstance(sub, (ast.Import, ast.ImportFrom)):
                            record_import(sub)

        walk_defs(mod.tree.body, "", None)

    # ------------------------------------------------------------ resolving

    def _lookup_project_symbol(self, dotted_mod: str, symbol: str) -> str | None:
        rel = self._mod_by_dotted.get(dotted_mod)
        if rel is None:
            return None
        q = self._toplevel.get(rel, {}).get(symbol)
        if q is None:
            # re-exported through the target module's own from-imports
            tgt = self._imports.get(rel, {}).get(symbol)
            if tgt and tgt[0] == "sym":
                return self._lookup_project_symbol(tgt[1], tgt[2])
        return q

    def _resolve_local_name(self, rel: str, name: str) -> str | None:
        """Top-level def/class or import binding in module ``rel``."""
        q = self._toplevel.get(rel, {}).get(name)
        if q is not None:
            return q
        tgt = self._imports.get(rel, {}).get(name)
        if tgt is None:
            return None
        if tgt[0] == "sym":
            return self._lookup_project_symbol(tgt[1], tgt[2])
        return None  # a bare module binding is not callable

    def _callable_qname(self, q: str) -> str | None:
        """A resolved symbol as a function node: classes become __init__."""
        if q in self.functions:
            return q
        cls = self.classes.get(q)
        if cls is not None:
            return cls.methods.get("__init__")
        return None

    def class_mro(self, class_qname: str) -> list[str]:
        """The class plus its project-resolvable bases, depth-first (cycles
        guarded).  Non-project bases simply end the walk down that branch."""
        hit = self._resolved_bases.get(class_qname)
        if hit is not None:
            return hit
        out: list[str] = []
        seen: set[str] = set()

        def visit(cq: str) -> None:
            if cq in seen:
                return
            seen.add(cq)
            info = self.classes.get(cq)
            if info is None:
                return
            out.append(cq)
            for base in info.base_names:
                base_q = self._resolve_local_name(info.relpath, base.split(".")[0])
                if base_q is None and "." in base:
                    # modalias.Class base form
                    head, _, tail = base.rpartition(".")
                    tgt = self._imports.get(info.relpath, {}).get(head.split(".")[0])
                    if tgt and tgt[0] == "mod":
                        dotted = tgt[1] + base[len(head.split(".")[0]):-len(tail) - 1]
                        base_q = self._lookup_project_symbol(dotted, tail)
                if base_q is not None and base_q in self.classes:
                    visit(base_q)

        visit(class_qname)
        self._resolved_bases[class_qname] = out
        return out

    def resolve_method(self, class_qname: str, method: str) -> str | None:
        for cq in self.class_mro(class_qname):
            q = self.classes[cq].methods.get(method)
            if q is not None:
                return q
        return None

    def _resolve_call(self, mod: Module, caller: FuncInfo | None, call: ast.Call):
        """→ (callee qname | None, receiver, attr, raw)."""
        func = call.func
        raw = dotted_name(func) or (
            func.attr if isinstance(func, ast.Attribute) else "<dynamic>"
        )
        if isinstance(func, ast.Name):
            name = func.id
            # nested defs of the lexically enclosing chain first
            if caller is not None:
                chain = caller.name.split(".")
                for i in range(len(chain), 0, -1):
                    q = f"{mod.relpath}::{'.'.join(chain[:i])}.{name}"
                    if q in self.functions:
                        return q, None, name, raw
            q = self._resolve_local_name(mod.relpath, name)
            if q is not None:
                q = self._callable_qname(q)
            return q, None, name, raw
        if isinstance(func, ast.Attribute):
            attr = func.attr
            receiver = dotted_name(func.value)
            if (
                isinstance(func.value, ast.Call)
                and isinstance(func.value.func, ast.Name)
                and func.value.func.id == "super"
                and caller is not None
                and caller.class_qname
            ):
                for cq in self.class_mro(caller.class_qname)[1:]:
                    q = self.classes[cq].methods.get(attr)
                    if q is not None:
                        return q, "super()", attr, f"super().{attr}"
                return None, "super()", attr, f"super().{attr}"
            if receiver in ("self", "cls") and caller is not None and caller.class_qname:
                q = self.resolve_method(caller.class_qname, attr)
                return q, receiver, attr, raw
            if receiver is not None:
                head = receiver.split(".")[0]
                bound = self._resolve_local_name(mod.relpath, head)
                if bound is not None and bound in self.classes and "." not in receiver:
                    # ClassName.method(...) — unbound call
                    q = self.resolve_method(bound, attr)
                    return q, receiver, attr, raw
                tgt = self._imports.get(mod.relpath, {}).get(head)
                if tgt and tgt[0] == "mod":
                    dotted = tgt[1] + receiver[len(head):]
                    q = self._lookup_project_symbol(dotted, attr)
                    if q is not None:
                        q = self._callable_qname(q)
                    return q, receiver, attr, raw
            return None, receiver, attr, raw
        return None, None, raw, raw

    def _collect_edges(self, mod: Module) -> None:
        rel = mod.relpath
        module_caller = f"{rel}::<module>"

        def edges_for(caller_q: str, info: FuncInfo | None, body: list[ast.stmt]):
            out = self.edges.setdefault(caller_q, [])
            for call in iter_calls_in_order(body):
                callee, receiver, attr, raw = self._resolve_call(mod, info, call)
                out.append(
                    CallEdge(
                        caller_q, callee, call.lineno, call.col_offset,
                        raw, receiver, attr, call,
                    )
                )

        for q, info in self.functions.items():
            if info.relpath == rel:
                edges_for(q, info, info.node.body)
        edges_for(module_caller, None, mod.tree.body)

    def resolve_reference(self, relpath: str, caller: "FuncInfo | None",
                          dotted: str) -> str | None:
        """Resolve a *reference* to a project function by its dotted source
        text — same lookup order as call resolution (the caller's nested-def
        chain, module top-level, imports, ``modalias.symbol``,
        ``ClassName.method``) but usable where the function is an argument
        (``lax.scan(layer, ...)``) rather than the thing being called.
        Returns a function qname (classes resolve to ``__init__``), else
        None."""
        if not dotted or dotted.startswith(("self.", "cls.")):
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            if caller is not None:
                chain = caller.name.split(".")
                for i in range(len(chain), 0, -1):
                    q = f"{relpath}::{'.'.join(chain[:i])}.{head}"
                    if q in self.functions:
                        return q
            q = self._resolve_local_name(relpath, head)
            return self._callable_qname(q) if q is not None else None
        bound = self._resolve_local_name(relpath, head)
        if bound is not None and bound in self.classes and "." not in rest:
            return self.resolve_method(bound, rest)
        tgt = self._imports.get(relpath, {}).get(head)
        if tgt and tgt[0] == "mod":
            mod_dotted, _, symbol = (tgt[1] + "." + rest).rpartition(".")
            q = self._lookup_project_symbol(mod_dotted, symbol)
            return self._callable_qname(q) if q is not None else None
        return None

    # ------------------------------------------------------------- querying

    def callees(self, qname: str) -> list[CallEdge]:
        return self.edges.get(qname, [])

    def functions_in(self, relpath_suffixes: tuple[str, ...]) -> list[FuncInfo]:
        return [
            f for f in self.functions.values()
            if any(f.relpath.endswith(s) for s in relpath_suffixes)
        ]

    def reachable(self, start: str, max_hops: int) -> dict[str, list[CallEdge]]:
        """Resolved-edge BFS: reached qname → the edge path that got there
        (shortest, ≤ max_hops edges)."""
        paths: dict[str, list[CallEdge]] = {}
        frontier: list[tuple[str, list[CallEdge]]] = [(start, [])]
        for _ in range(max_hops):
            nxt: list[tuple[str, list[CallEdge]]] = []
            for q, path in frontier:
                for e in self.callees(q):
                    if e.callee is None or e.callee in paths or e.callee == start:
                        continue
                    paths[e.callee] = path + [e]
                    nxt.append((e.callee, path + [e]))
            frontier = nxt
            if not frontier:
                break
        return paths

    def stats(self) -> dict:
        n_edges = sum(len(v) for v in self.edges.values())
        n_resolved = sum(1 for v in self.edges.values() for e in v if e.resolved)
        return {
            "functions": len(self.functions),
            "classes": len(self.classes),
            "edges": n_edges,
            "resolved_edges": n_resolved,
            "unknown_edges": n_edges - n_resolved,
        }
