"""Small forward dataflow / taint framework over the call graph.

The security rules need to answer "can a request-derived string reach a
filesystem call without passing the sanitizer?" — a question about *flows*,
not single statements.  This module implements the minimal machinery for
that, tuned for low false positives rather than completeness:

- **Sources** are attribute reads (``self.path``, ``self.headers``,
  ``self.rfile``) plus instance attributes that any method of the class
  assigns from a tainted value (``self._query`` built from the URL —
  computed as a per-class fixpoint, flow-insensitive across methods).
- **Propagation** follows assignments, f-strings/concat/``%``, subscripts
  and attribute reads *of tainted values*, method calls on tainted
  receivers (``tainted.get(...)``), known string helpers
  (``urllib.parse.unquote`` …), and tuple unpacking.  A call whose callee
  is *not* a known propagator returns CLEAN (``int(...)`` launders by
  converting; a linter that tainted every call result would drown the
  gate in noise) — except project-resolved callees, which are analyzed.
- **Sanitizers** clear taint three ways: ``x = sanitize(y)`` (clean return
  value), ``if sanitize(x): <x clean here>`` (guard), and
  ``if not sanitize(x): return/raise`` (early-exit guard — x clean after).
- **Interprocedural**: a tainted argument to a call-graph-resolved project
  function analyzes the callee with that parameter tainted (memoized,
  depth-bounded); sink hits inside the callee are reported with the call
  chain, and tainted returns flow back to the caller.

Nested function bodies are skipped (they execute outside the analyzed
flow); a tainted value captured by a closure is out of scope here, as are
taints stored into containers (``lst.append(tainted)``).  Those are
recorded limitations, not silent ones — see ARCHITECTURE.md §Analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from lakesoul_tpu.analysis.callgraph import CallGraph, FuncInfo, iter_calls_in_order
from lakesoul_tpu.analysis.engine import dotted_name

__all__ = ["TaintConfig", "SinkHit", "TaintAnalysis"]

_MAX_DEPTH = 4

# calls that pass string taint through (terminal dotted-name match)
_PROPAGATOR_CALLS = {
    "str", "repr", "format",
    "urllib.parse.unquote", "parse.unquote", "unquote",
    "urllib.parse.quote", "parse.quote", "quote",
    "urllib.parse.urlsplit", "parse.urlsplit", "urlsplit",
    "urllib.parse.urlparse", "parse.urlparse", "urlparse",
    "urllib.parse.parse_qs", "parse.parse_qs", "parse_qs",
    "urllib.parse.parse_qsl", "parse.parse_qsl", "parse_qsl",
    "os.path.join", "posixpath.join", "ntpath.join",
    "os.path.normpath", "posixpath.normpath",
    "sorted", "list", "tuple", "reversed",
}


@dataclass
class TaintConfig:
    """What a rule considers source, sanitizer, and sink.

    Two lattice policies share this machinery.  The *string-taint* rules
    (path traversal) use the defaults: unknown calls launder (``int(x)``
    converts), self-attr reads are the sources.  The *value-tracking*
    rules (device arrays, 64-bit dtypes) invert both knobs:
    ``propagate_all_calls`` keeps taint flowing through the jnp ops that
    make up traced code, ``attr_sanitizers`` (``.shape``/``.dtype``) are
    the only reads that step a device value back down to a static host
    value, and ``source_call_predicate``/``receiver_sinks`` let a rule
    taint call *results* (``np.asarray(x, np.int64)``) and flag tainted
    *receivers* (``x.item()``)."""

    # self.<attr> reads that are taint roots
    source_self_attrs: frozenset[str] = frozenset({"path", "headers", "rfile"})
    # terminal callable names that return/prove clean values
    sanitizers: frozenset[str] = frozenset()
    sanitizer_prefixes: tuple[str, ...] = ("sanitize",)
    # terminal NAME calls → index of the path-like positional arg
    sink_functions: dict = field(default_factory=dict)
    # attribute calls (any receiver) → index of the path-like positional arg
    #   the receiver itself is never the sink (fs.open(p): p is, fs is not)
    sink_methods: dict = field(default_factory=dict)
    # keyword names that are sinks on those same calls
    sink_keywords: frozenset[str] = frozenset()
    # full dotted-name calls → index of the sink positional arg
    # (``np.asarray`` must sink while ``jnp.asarray`` stays a device op —
    # terminal-name matching cannot tell them apart)
    sink_calls: dict = field(default_factory=dict)
    # terminal names where EVERY positional argument is a sink (calls into
    # jit entry points: any tainted arg crosses the device boundary)
    sink_all_args_names: frozenset[str] = frozenset()
    # attribute READS on a tainted base that return a clean value
    # (x.shape, x.dtype: static metadata of a device value)
    attr_sanitizers: frozenset[str] = frozenset()
    # method calls whose TAINTED RECEIVER is itself the sink (x.item())
    receiver_sinks: frozenset[str] = frozenset()
    # predicate(call, dotted_name) → True when the call RESULT is a source
    # (np.float64(...), np.asarray(x, dtype=np.int64), ...)
    source_call_predicate: "object | None" = None
    # unknown calls with tainted args return tainted (device-value lattice:
    # every jnp op keeps the result on device) instead of laundering
    propagate_all_calls: bool = False

    def is_sanitizer(self, terminal: str) -> bool:
        return terminal in self.sanitizers or any(
            terminal.lstrip("_").startswith(p) for p in self.sanitizer_prefixes
        )


@dataclass(frozen=True)
class SinkHit:
    """A tainted expression reaching a sink argument."""

    relpath: str
    line: int
    sink: str  # rendered call text ("filesystem_for")
    source_desc: str  # what was tainted ("self._query['uploadId']")
    chain: tuple[str, ...]  # function names from entry to the sink's owner


class _FuncState:
    """Per-analysis mutable environment for one function body walk."""

    def __init__(self, tainted: set[str], attr_sink: "set[str] | None" = None):
        self.tainted = tainted  # local names currently tainted
        # when set, `self.<attr> = <tainted>` assignments record the attr
        # here (the class-attribute fixpoint); shared across branch copies
        # on purpose — attr taint is additive across paths
        self.attr_sink = attr_sink

    def copy(self) -> "_FuncState":
        return _FuncState(set(self.tainted), self.attr_sink)


class TaintAnalysis:
    """Run taint over the functions of the modules in ``scope``."""

    def __init__(self, graph: CallGraph, config: TaintConfig):
        self.graph = graph
        self.config = config
        # (qname, frozenset tainted params) → (returns_tainted, [SinkHit])
        self._summaries: dict[tuple, tuple[bool, list[SinkHit]]] = {}
        self._in_progress: set[tuple] = set()
        # class qname → names of tainted instance attributes
        self._class_attrs: dict[str, frozenset[str]] = {}
        # qname → {id(call node): edge} — resolved per function ONCE; a
        # linear edge scan per lookup would make the walk O(calls²)
        self._edges_by_node: dict[str, dict[int, object]] = {}
        # id(expr) → [ast.Call] in source order — the sink scan visits the
        # same statement expressions once per fixpoint pass AND once per
        # memoized call-site summary; re-walking the subtree each time
        # dominated the read-modify-write rule's wall time (the AST nodes
        # live as long as the Project, so id() keys are stable)
        self._calls_cache: dict[int, list] = {}
        # qname → whether the function body lexically contains a raw taint
        # source (a source_self_attrs read or a source_call_predicate hit).
        # With no tainted parameters, taint can ONLY enter through one of
        # those (a call with clean arguments never returns taint — see
        # _call_tainted), so source-free functions are skipped by both the
        # attr fixpoint and the entry pass.
        self._has_source: dict[str, bool] = {}

    # ------------------------------------------------------------- entry

    def run(self, scope: tuple[str, ...]) -> list[SinkHit]:
        # converge every in-scope class's attribute-taint fixpoint FIRST,
        # then drop summaries memoized against the not-yet-converged sets —
        # the checking pass must see only final attr taint
        for fn in self.graph.functions_in(scope):
            self._tainted_attrs(fn.class_qname)
        self._summaries.clear()
        hits: list[SinkHit] = []
        for fn in self.graph.functions_in(scope):
            # a function with no tainted params acquires taint only from a
            # lexical source or a tainted attr of its own class — everything
            # else is summary-clean by construction and need not be walked
            if not self._raw_source_in(fn) and not self._tainted_attrs(
                fn.class_qname
            ):
                continue
            _, fn_hits = self._analyze(fn, frozenset(), depth=0)
            hits.extend(fn_hits)
        # dedupe: the same sink inside a shared helper is reported once per
        # (location, source), keeping the shortest chain
        best: dict[tuple, SinkHit] = {}
        for h in hits:
            key = (h.relpath, h.line, h.sink)
            if key not in best or len(h.chain) < len(best[key].chain):
                best[key] = h
        return sorted(best.values(), key=lambda h: (h.relpath, h.line))

    def analyze_entry(self, qname: str,
                      tainted_params: frozenset[str]) -> list[SinkHit]:
        """Analyze ONE function with the given parameters tainted — the
        entry form the device rules use (a jit boundary's array arguments
        are the sources, not any self-attribute)."""
        fn = self.graph.functions[qname]
        _, hits = self._analyze(fn, tainted_params, depth=0)
        return hits

    # ---------------------------------------------------- class attr taint

    def _tainted_attrs(self, class_qname: str | None) -> frozenset[str]:
        """Instance attributes assigned from tainted values anywhere in the
        class — fixpoint over methods so ``self._query`` (built from
        ``self.path``) taints its readers in *other* methods."""
        if class_qname is None:
            return frozenset()
        hit = self._class_attrs.get(class_qname)
        if hit is not None:
            return hit
        self._class_attrs[class_qname] = frozenset()  # cycle guard
        methods = [
            f for f in self.graph.functions.values()
            if f.class_qname == class_qname
        ]
        if not any(self._raw_source_in(f) for f in methods):
            # attr taint must START at a lexical source in SOME method of
            # the class (the fixpoint begins with zero tainted attrs and a
            # clean-arg call never returns taint) — a source-free class
            # converges to ∅ without the 8-pass walk
            return frozenset()
        attrs: set[str] = set()
        for _ in range(8):  # fixpoint: attr taint can chain attr→attr
            before = set(attrs)
            self._class_attrs[class_qname] = frozenset(attrs)
            for fn in methods:
                # the REAL walker runs the scan: source order, sanitizer
                # guards and clean-reassignment semantics must match the
                # checking pass or `self._x = sanitized` stays tainted
                state = _FuncState(set(), attr_sink=attrs)
                self._walk_block(fn.node.body, fn, state, [], _MAX_DEPTH)
            if attrs == before:
                break
        self._class_attrs[class_qname] = frozenset(attrs)
        return self._class_attrs[class_qname]

    def _raw_source_in(self, fn: FuncInfo) -> bool:
        """Whether ``fn``'s body lexically contains a raw taint source.
        Conservative over-approximation (nested defs are included even
        though the walkers skip them) — used only to SKIP provably clean
        work, never to report."""
        hit = self._has_source.get(fn.qname)
        if hit is not None:
            return hit
        cfg = self.config
        found = False
        for node in ast.walk(fn.node):
            if (
                cfg.source_call_predicate is not None
                and isinstance(node, ast.Call)
                and cfg.source_call_predicate(node, dotted_name(node.func))
            ):
                found = True
                break
            if (
                cfg.source_self_attrs
                and isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in cfg.source_self_attrs
            ):
                found = True
                break
        self._has_source[fn.qname] = found
        return found

    # ------------------------------------------------------ function bodies

    def _analyze(self, fn: FuncInfo, tainted_params: frozenset[str],
                 depth: int) -> tuple[bool, list[SinkHit]]:
        key = (fn.qname, tainted_params)
        hit = self._summaries.get(key)
        if hit is not None:
            return hit
        if key in self._in_progress or depth > _MAX_DEPTH:
            return False, []  # recursion/depth bound: assume clean
        self._in_progress.add(key)
        try:
            state = _FuncState(set(tainted_params))
            hits: list[SinkHit] = []
            returns = self._walk_block(fn.node.body, fn, state, hits, depth)
            result = (returns, hits)
            self._summaries[key] = result
            return result
        finally:
            self._in_progress.discard(key)

    def _walk_block(self, body: list, fn: FuncInfo, state: _FuncState,
                    hits: list[SinkHit], depth: int) -> bool:
        """Walk statements, mutate ``state``, collect sink hits; returns
        True when a ``return``/``yield`` in this block carries taint."""
        returns_tainted = False
        for stmt in body:
            returns_tainted |= self._walk_stmt(stmt, fn, state, hits, depth)
        return returns_tainted

    def _walk_stmt(self, stmt, fn: FuncInfo, state: _FuncState,
                   hits: list[SinkHit], depth: int) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return False  # nested bodies run outside this flow
        if isinstance(stmt, ast.If):
            self._check_expr(stmt.test, fn, state, hits, depth)
            then_state = state.copy()
            else_state = state.copy()
            cleaned = self._guard_cleans(stmt.test)
            if cleaned is not None:
                name, positive = cleaned
                if positive:
                    then_state.tainted.discard(name)
                elif _terminates(stmt.body):
                    # `if not sanitize(x): return` — x clean afterwards
                    else_state.tainted.discard(name)
            rt = self._walk_block(stmt.body, fn, then_state, hits, depth)
            re_ = self._walk_block(stmt.orelse, fn, else_state, hits, depth)
            fall_through = []
            if not _terminates(stmt.body):
                fall_through.append(then_state)
            if not _terminates(stmt.orelse):
                fall_through.append(else_state)
            state.tainted = set().union(*(s.tainted for s in fall_through)) \
                if fall_through else set()
            return rt or re_
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter, fn, state, hits, depth)
            if self._expr_tainted(stmt.iter, fn, state):
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        state.tainted.add(n.id)
            rt = False
            for _ in range(2):  # loop-carried taint needs one extra pass
                rt |= self._walk_block(stmt.body, fn, state, hits, depth)
            rt |= self._walk_block(stmt.orelse, fn, state, hits, depth)
            return rt
        if isinstance(stmt, ast.While):
            self._check_expr(stmt.test, fn, state, hits, depth)
            rt = False
            for _ in range(2):
                rt |= self._walk_block(stmt.body, fn, state, hits, depth)
            return rt
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr, fn, state, hits, depth)
                if item.optional_vars is not None and self._expr_tainted(
                    item.context_expr, fn, state
                ):
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            state.tainted.add(n.id)
            return self._walk_block(stmt.body, fn, state, hits, depth)
        if isinstance(stmt, ast.Try):
            rt = self._walk_block(stmt.body, fn, state, hits, depth)
            for handler in stmt.handlers:
                rt |= self._walk_block(handler.body, fn, state, hits, depth)
            rt |= self._walk_block(stmt.orelse, fn, state, hits, depth)
            rt |= self._walk_block(stmt.finalbody, fn, state, hits, depth)
            return rt
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is None:
                return False
            self._check_expr(value, fn, state, hits, depth)
            tainted = self._expr_tainted(value, fn, state, depth=depth)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for tgt in targets:
                if (
                    state.attr_sink is not None
                    and isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and tainted
                ):
                    state.attr_sink.add(tgt.attr)
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        if tainted:
                            state.tainted.add(n.id)
                        else:
                            state.tainted.discard(n.id)
            return False
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._check_expr(stmt.value, fn, state, hits, depth)
                return self._expr_tainted(stmt.value, fn, state, depth=depth)
            return False
        if isinstance(stmt, ast.Expr):
            self._check_expr(stmt.value, fn, state, hits, depth)
            if isinstance(stmt.value, (ast.Yield, ast.YieldFrom)):
                v = stmt.value.value
                if v is not None and self._expr_tainted(v, fn, state, depth=depth):
                    return True
            return False
        # default: still scan contained expressions for sinks
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._check_expr(node, fn, state, hits, depth)
        return False

    # ----------------------------------------------------------- expressions

    def _guard_cleans(self, test: ast.expr) -> tuple[str, bool] | None:
        """``sanitize(x)`` → (x, True); ``not sanitize(x)`` → (x, False)."""
        positive = True
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            positive = False
            test = test.operand
        if not isinstance(test, ast.Call) or not test.args:
            return None
        name = dotted_name(test.func)
        if name is None:
            return None
        if not self.config.is_sanitizer(name.rsplit(".", 1)[-1]):
            return None
        arg = test.args[0]
        if isinstance(arg, ast.Name):
            return arg.id, positive
        return None

    def _expr_tainted(self, expr: ast.expr, fn: FuncInfo, state: _FuncState,
                      *, depth: int = _MAX_DEPTH) -> bool:
        cfg = self.config
        if isinstance(expr, ast.Name):
            return expr.id in state.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in cfg.attr_sanitizers:
                return False  # static metadata of a tainted value (.shape)
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                if expr.attr in cfg.source_self_attrs:
                    return True
                if expr.attr in self._tainted_attrs(fn.class_qname):
                    return True
                return False
            return self._expr_tainted(base, fn, state, depth=depth)
        if isinstance(expr, ast.Subscript):
            return self._expr_tainted(expr.value, fn, state, depth=depth)
        if isinstance(expr, ast.JoinedStr):
            return any(
                self._expr_tainted(v.value, fn, state, depth=depth)
                for v in expr.values
                if isinstance(v, ast.FormattedValue)
            )
        if isinstance(expr, ast.BinOp):
            return (
                self._expr_tainted(expr.left, fn, state, depth=depth)
                or self._expr_tainted(expr.right, fn, state, depth=depth)
            )
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(
                self._expr_tainted(e, fn, state, depth=depth) for e in expr.elts
            )
        if isinstance(expr, ast.IfExp):
            return (
                self._expr_tainted(expr.body, fn, state, depth=depth)
                or self._expr_tainted(expr.orelse, fn, state, depth=depth)
            )
        if isinstance(expr, ast.BoolOp):
            return any(
                self._expr_tainted(v, fn, state, depth=depth) for v in expr.values
            )
        if isinstance(expr, ast.Starred):
            return self._expr_tainted(expr.value, fn, state, depth=depth)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # coarse: tainted if any referenced name/source inside is tainted
            for node in ast.walk(expr):
                if isinstance(node, ast.Name) and node.id in state.tainted:
                    return True
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and (
                        node.attr in cfg.source_self_attrs
                        or node.attr in self._tainted_attrs(fn.class_qname)
                    )
                ):
                    return True
            return False
        if isinstance(expr, ast.Call):
            return self._call_tainted(expr, fn, state, depth)
        return False

    def _call_tainted(self, call: ast.Call, fn: FuncInfo, state: _FuncState,
                      depth: int) -> bool:
        cfg = self.config
        name = dotted_name(call.func)
        terminal = (name or "").rsplit(".", 1)[-1]
        if cfg.source_call_predicate is not None and cfg.source_call_predicate(
            call, name
        ):
            return True
        if name is not None and cfg.is_sanitizer(terminal):
            return False
        args_tainted = any(
            self._expr_tainted(a, fn, state, depth=depth) for a in call.args
        ) or any(
            kw.value is not None
            and self._expr_tainted(kw.value, fn, state, depth=depth)
            for kw in call.keywords
        )
        # method call on a tainted receiver: tainted.get(...), tainted[0].split()
        if isinstance(call.func, ast.Attribute) and self._expr_tainted(
            call.func.value, fn, state, depth=depth
        ):
            return True
        # "x".join(tainted_parts) — str-constant receiver propagates
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "join"
            and isinstance(call.func.value, ast.Constant)
            and args_tainted
        ):
            return True
        if name in _PROPAGATOR_CALLS and args_tainted:
            return True
        # project function: its return taint is its summary's
        edge_callee = self._resolved_callee(call, fn)
        if edge_callee is not None and args_tainted:
            callee = self.graph.functions[edge_callee]
            tainted_params = self._map_tainted_params(call, callee, fn, state, depth)
            returns, _ = self._analyze(callee, tainted_params, depth + 1)
            return returns
        if cfg.propagate_all_calls and args_tainted:
            return True  # device-value lattice: jnp ops keep taint flowing
        return False

    def _resolved_callee(self, call: ast.Call, fn: FuncInfo) -> str | None:
        # resolve through the graph's edges for this caller (edges keep the
        # ast node, so identity lookup is exact)
        by_node = self._edges_by_node.get(fn.qname)
        if by_node is None:
            by_node = {id(e.node): e for e in self.graph.callees(fn.qname)}
            self._edges_by_node[fn.qname] = by_node
        edge = by_node.get(id(call))
        return edge.callee if edge is not None else None

    def _map_tainted_params(self, call: ast.Call, callee: FuncInfo,
                            fn: FuncInfo, state: _FuncState,
                            depth: int) -> frozenset[str]:
        params = callee.params
        offset = 1 if callee.is_method and params and params[0] in ("self", "cls") \
            else 0
        tainted: set[str] = set()
        for i, a in enumerate(call.args):
            idx = i + offset
            if idx < len(params) and self._expr_tainted(a, fn, state, depth=depth):
                tainted.add(params[idx])
        for kw in call.keywords:
            if kw.arg and kw.arg in params and self._expr_tainted(
                kw.value, fn, state, depth=depth
            ):
                tainted.add(kw.arg)
        return frozenset(tainted)

    # ---------------------------------------------------------------- sinks

    def _calls_in(self, expr: ast.expr) -> list:
        calls = self._calls_cache.get(id(expr))
        if calls is None:
            calls = list(iter_calls_in_order([ast.Expr(value=expr)]))
            self._calls_cache[id(expr)] = calls
        return calls

    def _check_expr(self, expr: ast.expr, fn: FuncInfo, state: _FuncState,
                    hits: list[SinkHit], depth: int) -> None:
        if state.attr_sink is not None:
            # attr-fixpoint pass: its hits are discarded and sink scanning
            # has no effect on taint state — only the checking pass pays
            # for the per-call-site descent
            return
        cfg = self.config
        for call in self._calls_in(expr):
            name = dotted_name(call.func)
            terminal = (name or "").rsplit(".", 1)[-1]
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in cfg.receiver_sinks
                and self._expr_tainted(call.func.value, fn, state, depth=depth)
            ):
                hits.append(SinkHit(
                    fn.relpath, call.lineno, name or terminal,
                    _render(call.func.value), (fn.name,),
                ))
            sink_idx = None
            if isinstance(call.func, ast.Name) and call.func.id in cfg.sink_functions:
                sink_idx = cfg.sink_functions[call.func.id]
            elif isinstance(call.func, ast.Attribute) and call.func.attr in cfg.sink_methods:
                sink_idx = cfg.sink_methods[call.func.attr]
            elif name in cfg.sink_calls:
                sink_idx = cfg.sink_calls[name]
            if sink_idx is not None or terminal in cfg.sink_all_args_names:
                exprs = []
                if terminal in cfg.sink_all_args_names:
                    exprs.extend(call.args)
                    exprs += [kw.value for kw in call.keywords]
                elif sink_idx is not None and sink_idx < len(call.args):
                    exprs.append(call.args[sink_idx])
                exprs += [
                    kw.value for kw in call.keywords
                    if kw.arg in cfg.sink_keywords
                ]
                for arg in exprs:
                    if self._expr_tainted(arg, fn, state, depth=depth):
                        hits.append(SinkHit(
                            fn.relpath, call.lineno, name or terminal,
                            _render(arg), (fn.name,),
                        ))
            # interprocedural: tainted args into a resolved project callee
            callee_q = self._resolved_callee(call, fn)
            if callee_q is not None:
                callee = self.graph.functions[callee_q]
                tainted_params = self._map_tainted_params(
                    call, callee, fn, state, depth
                )
                if tainted_params:
                    _, callee_hits = self._analyze(callee, tainted_params, depth + 1)
                    for h in callee_hits:
                        hits.append(SinkHit(
                            h.relpath, h.line, h.sink, h.source_desc,
                            (fn.name,) + h.chain,
                        ))


def _terminates(body: list) -> bool:
    """Block always leaves the enclosing flow (return/raise/continue/break).
    An absent else-branch falls through (with the entry state) — not
    terminating."""
    if not body:
        return False
    last = body[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _render(expr: ast.expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:  # pragma: no cover — unparse covers all 3.9+ nodes
        return "<expr>"
