"""lakelint engine: AST-based, project-native static analysis.

Generic linters can't know that ``runtime/pool.py`` is the only legal thread
substrate, that parallel pipeline stages must be deterministic, or that the
``:memory:`` sqlite connection is only safe behind ``meta/store.py``'s RLock.
Those are *project* invariants — the ones that caused real outages (the
nested-pool deadlock class, the shared-cursor race) — so they get a
project-native checker that runs as a CI gate (tests/test_analysis_clean.py).

Moving parts:

- :class:`Rule` — one invariant.  ``check(module)`` yields findings for a
  single file; ``finalize(project)`` yields cross-file findings (env vars vs
  the README table, metric-kind consistency) after every module was visited.
- :class:`Module` / :class:`Project` — parsed source handed to rules; the
  tree is parsed ONCE per file and shared by all rules.
- Suppression, two ways:
  (1) an inline pragma on the offending line::

          t = threading.Thread(...)  # lakelint: ignore[raw-thread] pump thread

      for code that is *allowed* to break the rule by design;
  (2) ``analysis/baseline.json`` for pre-existing findings that should not
      block the gate — every entry carries a human ``reason`` and entries
      that stop matching anything are reported as stale so the baseline
      only ever shrinks.

Baseline keys are ``rule::path::message`` (no line numbers — they drift on
every edit; messages are stable because rules phrase them around symbols).
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

__all__ = [
    "EngineError",
    "Finding",
    "Module",
    "Project",
    "Rule",
    "Baseline",
    "run",
    "run_repo",
    "package_root",
    "default_baseline_path",
]


class EngineError(Exception):
    """The analyzer itself failed (bad rule id, unreadable baseline, git
    diff failure) — distinct from "the code has findings": the CLI maps
    findings to exit 1 and EngineError to exit 2 so CI can tell a broken
    gate from a failing one."""

_PRAGMA_RE = re.compile(r"#\s*lakelint:\s*ignore\[([a-z0-9_,\- ]+)\]")

# generated files are not held to hand-written invariants
_EXCLUDED_FILE_RE = re.compile(r"_pb2\.py$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Module:
    """One parsed source file (parse once, share across rules).  ``walk()``
    and ``parents()`` are computed once and shared — with ~90 files and 7
    rules, per-rule re-walks dominated analyzer wall time before caching."""

    path: Path
    relpath: str
    source: str
    lines: list[str]
    tree: ast.Module
    _nodes: "list[ast.AST] | None" = field(default=None, repr=False)
    _parents: "dict[ast.AST, ast.AST] | None" = field(default=None, repr=False)

    def walk(self) -> "list[ast.AST]":
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def parents(self) -> "dict[ast.AST, ast.AST]":
        if self._parents is None:
            parents: dict[ast.AST, ast.AST] = {}
            for node in self.walk():
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    @classmethod
    def load(cls, path: Path, root: Path) -> "Module | None":
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError, ValueError):
            return None  # unreadable/unparsable: not this linter's business
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:  # outside the root: keep a stable absolute key
            rel = path.resolve().as_posix()
        return cls(path, rel, source, source.splitlines(), tree)

    def pragma_rules(self, line: int) -> set[str]:
        """Rule ids suppressed by an inline pragma on ``line`` (1-based)."""
        if not 1 <= line <= len(self.lines):
            return set()
        m = _PRAGMA_RE.search(self.lines[line - 1])
        if not m:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}


@dataclass
class Project:
    """Everything a rule may look at: the parsed modules plus repo docs."""

    root: Path
    modules: list[Module] = field(default_factory=list)
    _callgraph: "object | None" = field(default=None, repr=False)
    # the device pack's shared jit/pallas index, cached by
    # rules.jaxtpu.device_index() with the same build-once contract
    _device_index: "object | None" = field(default=None, repr=False)
    # the concurrency pack's shared indexes (threadroots.thread_roots(),
    # rules.races class-access index), same build-once contract
    _thread_roots: "object | None" = field(default=None, repr=False)
    _race_index: "object | None" = field(default=None, repr=False)
    # the durability pack's per-function filesystem-op index
    # (rules.durability._op_index), same build-once contract
    _durability_index: "object | None" = field(default=None, repr=False)
    # the isolation pack's per-module SQL/transaction index
    # (rules.isolation._sql_index), same build-once contract
    _isolation_index: "object | None" = field(default=None, repr=False)
    # the boundedness pack's per-class resource-lifecycle index
    # (rules.boundedness._class_index), same build-once contract
    _boundedness_index: "object | None" = field(default=None, repr=False)

    def callgraph(self):
        """The project call graph, built ONCE and shared by every
        interprocedural rule (building it is a full extra pass over the
        shared AST walks — four rules must not pay it four times)."""
        if self._callgraph is None:
            from lakesoul_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph.build(self)
        return self._callgraph

    def readme_text(self) -> str:
        for name in ("README.md", "README.rst", "README"):
            p = self.root / name
            if p.is_file():
                try:
                    return p.read_text(encoding="utf-8")
                except OSError:
                    return ""
        return ""


class Rule:
    """Base class: one project invariant.  Subclasses set ``id``/``title``
    and override ``check`` (per-file) and/or ``finalize`` (cross-file)."""

    id: str = ""
    title: str = ""

    def check(self, module: Module) -> Iterable[Finding]:
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()


class Baseline:
    """Checked-in suppression list (``analysis/baseline.json``).

    Schema: ``{"version": 1, "suppressions": [{"rule", "path", "message",
    "reason"}, ...]}``.  ``reason`` is mandatory — a suppression nobody can
    justify is a bug with a paper trail."""

    def __init__(self, entries: list[dict]):
        self.entries = entries
        self._keys = {
            f"{e['rule']}::{e['path']}::{e['message']}": e for e in entries
        }
        self._used: set[str] = set()

    @classmethod
    def load(cls, path: Path | str | None) -> "Baseline":
        if path is None:
            return cls([])
        p = Path(path)
        if not p.is_file():
            return cls([])
        data = json.loads(p.read_text(encoding="utf-8"))
        entries = data.get("suppressions", [])
        for e in entries:
            missing = {"rule", "path", "message", "reason"} - set(e)
            if missing:
                raise ValueError(
                    f"baseline entry {e!r} is missing {sorted(missing)} — "
                    "every suppression must be justified"
                )
        return cls(entries)

    def suppresses(self, finding: Finding) -> bool:
        hit = finding.key in self._keys
        if hit:
            self._used.add(finding.key)
        return hit

    def stale_entries(self) -> list[dict]:
        """Entries that matched nothing this run — fixed findings whose
        suppression should be deleted."""
        return [e for k, e in self._keys.items() if k not in self._used]


# ------------------------------------------------------------------ discovery


def package_root() -> Path:
    """The installed ``lakesoul_tpu`` package directory."""
    return Path(__file__).resolve().parents[1]


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def _iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(
                f for f in p.rglob("*.py") if not _EXCLUDED_FILE_RE.search(f.name)
            )
        elif p.suffix == ".py":
            yield p


# -------------------------------------------------------------------- running


def run(
    paths: Iterable[Path | str] | None = None,
    *,
    root: Path | str | None = None,
    rules: Iterable[Rule] | None = None,
    baseline: Baseline | None = None,
    timings: "dict[str, float] | None" = None,
) -> tuple[list[Finding], Baseline]:
    """Analyse ``paths`` (default: the whole package) and return
    ``(unsuppressed findings, baseline)`` — the baseline is returned so
    callers can ask it for stale entries.  Pass a dict as ``timings`` to
    receive per-rule wall seconds (check + finalize; a shared index — call
    graph, device index, thread roots — bills to the first rule that
    builds it, which the lint bench leg notes when attributing cost)."""
    from lakesoul_tpu.analysis.rules import all_rules

    if paths is None:
        paths = [package_root()]
    root = Path(root) if root is not None else package_root().parent
    rules = list(rules) if rules is not None else all_rules()
    baseline = baseline if baseline is not None else Baseline([])

    project = Project(root=root)
    for f in _iter_py_files(Path(p) for p in paths):
        mod = Module.load(f, root)
        if mod is not None:
            project.modules.append(mod)

    def clocked(rule_id: str, started: float) -> None:
        if timings is not None:
            timings[rule_id] = (
                timings.get(rule_id, 0.0) + time.perf_counter() - started
            )

    findings: list[Finding] = []
    for rule in rules:
        t0 = time.perf_counter()
        for mod in project.modules:
            for finding in rule.check(mod):
                if rule.id not in mod.pragma_rules(finding.line):
                    findings.append(finding)
        clocked(rule.id, t0)
    by_rel = {m.relpath: m for m in project.modules}
    for rule in rules:
        t0 = time.perf_counter()
        for finding in rule.finalize(project):
            mod = by_rel.get(finding.path)
            if mod is not None and rule.id in mod.pragma_rules(finding.line):
                continue
            findings.append(finding)
        clocked(rule.id, t0)

    findings = [f for f in findings if not baseline.suppresses(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, baseline


def run_repo(
    baseline_path: Path | str | None = "default",
    *,
    timings: "dict[str, float] | None" = None,
) -> tuple[list[Finding], Baseline]:
    """The CI-gate entry point: whole package, checked-in baseline."""
    if baseline_path == "default":
        baseline_path = default_baseline_path()
    return run(baseline=Baseline.load(baseline_path), timings=timings)


# ----------------------------------------------------------- shared AST utils
# (used by several rules; kept here so rules stay small)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def enclosing_function_bodies(tree: ast.Module):
    """Yield ``(scope_node, body)`` for the module and every function —
    scopes a rule may search for cleanup calls without crossing into nested
    closures' runtime."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body


def walk_stopping_at_functions(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements WITHOUT descending into nested function/lambda bodies
    (their code runs later — outside the lexical context being checked)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # the def statement is visible; its body is not
        stack.extend(ast.iter_child_nodes(node))
