"""Runtime crash-prefix replay for publications (opt-in: ``LAKESOUL_FSCHECK=1``).

The static durability rules (rules/durability.py) prove every publication
*routes through* runtime/atomicio; this half proves the protocol itself is
crash-safe.  :func:`enable` interposes ``builtins.open`` (write modes),
``os.fsync``, ``os.replace``/``os.rename``, ``os.unlink``/``os.remove``
and ``os.open`` (directory fsync tracking) and records a per-artifact
persisted-ops trace for every warehouse/spool publication path — spool
segments + sidecars, session manifests, obs fleet docs, spill segments +
CRC sidecars, vector/plane store blobs and pointers, oracle docs.  Paths
are classified by artifact *shape* (basename patterns, tmp suffixes
stripped), not by watched roots, so unrelated IO (sqlite journals, test
scratch) stays untraced.

:func:`replay` is the ALICE-style harness (Pillai et al., OSDI'14): for
every prefix of the recorded op sequence it materializes the crashed
filesystem state in a scratch dir — only fsynced bytes survive; a rename
applies atomically in order; bytes written but never fsynced materialize
as missing/empty/half-written variants — then runs the REAL readers
(session manifest parse, spool range consistency, obs aggregator merge,
manifest-store pointer chase, ``AnnPlane.open``, spill CRC verification)
and asserts each sees an old-complete or new-complete state, never a torn
one.  Two online checks mirror the static rules under real dynamics:
a rename of a never-fsynced artifact, and a CRC sidecar landing before
its data is durable.

Violations are *recorded* (the producing op's stack + the failing reader
+ the offending prefix), never raised — the data path must not change
behavior under instrumentation; the conftest fixture fails the test at
teardown, exactly like lockgraph/racecheck.
"""

from __future__ import annotations

import builtins
import json
import os
import re
import shutil
import tempfile
import threading
import traceback
import zlib
from dataclasses import dataclass, field

__all__ = [
    "Artifact",
    "FsOp",
    "Violation",
    "classify",
    "enable",
    "disable",
    "reset",
    "violations",
    "enabled",
    "env_requested",
    "ops",
    "replay",
    "watch",
]

_ENV = "LAKESOUL_FSCHECK"

# originals captured at import: the detector's own IO must never recurse
# through the wrappers
_REAL_OPEN = builtins.open
_REAL_OS_OPEN = os.open
_REAL_FSYNC = os.fsync
_REAL_REPLACE = os.replace
_REAL_RENAME = os.rename
_REAL_UNLINK = os.unlink
_REAL_REMOVE = os.remove

# ``<name>.tmp-<holder>`` (atomicio/spool/obs) and bare ``<name>.tmp``
_TMP_RE = re.compile(r"\.tmp(-[^/]*)?$")

# artifact shapes, matched against the tmp-stripped basename.  Order
# matters: first match wins (the spill CRC must beat the generic json).
_PATTERNS: "tuple[tuple[str, re.Pattern], ...]" = (
    ("spill-crc", re.compile(r"^range-\d+\.arrow\.crc$")),
    ("range-segment", re.compile(r"^range-\d+\.arrow$")),
    ("range-sidecar", re.compile(r"^range-\d+\.json$")),
    ("session-manifest", re.compile(r"^manifest\.json$")),
    ("obs-doc", re.compile(r"^(member|recorder)-.+\.json$")),
    ("store-pointer", re.compile(r"^(LATEST|PLANE)$")),
    ("store-record", re.compile(r"^(manifest-\d+[^/]*\.json|plane-\d+-\d+c?\.json)$")),
    ("store-segment", re.compile(r"^cluster_\d+[^/]*\.seg$")),
    ("spill-probe", re.compile(r"^probe-.+\.json$")),
    ("json-doc", re.compile(r"^(oracle|follower)[^/]*\.json$")),
)

# store blobs live one level under the store root (manifests/, plane/,
# segments/); everything else replays against its own directory
_NESTED_DIRS = {"manifests", "plane", "segments"}


@dataclass(frozen=True)
class Artifact:
    kind: str
    path: str  # final (tmp-stripped) absolute path
    root: str  # the directory the replay readers run against


@dataclass(frozen=True)
class FsOp:
    kind: str  # "write" | "fsync" | "replace" | "unlink" | "fsyncdir"
    path: str  # as-issued absolute path (tmp names retained)
    dst: "str | None"  # replace/rename target
    data: "bytes | None"  # durable (fsync) or rename-time content
    stack: str


@dataclass
class Violation:
    kind: str  # "torn-state" | "unfsynced-rename" | "barrier-before-data"
    message: str
    stacks: "tuple[str, ...]" = ()
    prefix: int = 0  # offending op index (1-based; 0 = online check)

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for s in self.stacks:
            out.append(s.rstrip())
        return "\n".join(out)


def strip_tmp(path: str) -> "tuple[str, bool]":
    final, n = _TMP_RE.subn("", path)
    return final, bool(n)


def classify(path: str) -> "Artifact | None":
    """The publication artifact a path belongs to, or None for unrelated
    IO.  Tmp suffixes are stripped first, so staged files trace to their
    final artifact."""
    final, _ = strip_tmp(os.path.abspath(path))
    base = os.path.basename(final)
    for kind, pat in _PATTERNS:
        if pat.match(base):
            parent = os.path.dirname(final)
            root = parent
            if kind in ("store-record", "store-segment") and (
                os.path.basename(parent) in _NESTED_DIRS
            ):
                root = os.path.dirname(parent)
            return Artifact(kind, final, root)
    return None


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = False
        self.ops: list[FsOp] = []
        self.fd_paths: dict[int, str] = {}  # write fds of traced files
        self.dir_fds: dict[int, str] = {}  # os.open'd directories
        self.pre: dict[str, "bytes | None"] = {}  # first-touch snapshots
        self.violations: list[Violation] = []
        self.reported: set = set()


_STATE = _State()
_TLS = threading.local()


def _suppressed() -> bool:
    return bool(getattr(_TLS, "suppress", False))


class _suppress:
    def __enter__(self):
        self._prev = getattr(_TLS, "suppress", False)
        _TLS.suppress = True
        return self

    def __exit__(self, *exc):
        _TLS.suppress = self._prev
        return False


def _stack_summary() -> str:
    frames = [
        fr
        for fr in traceback.extract_stack()
        if "lakesoul_tpu/analysis/fscheck" not in fr.filename.replace("\\", "/")
    ]
    return "\n".join(
        f"  {fr.filename}:{fr.lineno} in {fr.name}" for fr in frames[-8:]
    )


def _read_disk(path: str) -> "bytes | None":
    try:
        with _REAL_OPEN(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _snapshot_pre(path: str) -> None:
    final, _ = strip_tmp(path)
    if final not in _STATE.pre:
        _STATE.pre[final] = _read_disk(final)


def _record(op: FsOp) -> None:
    with _STATE.lock:
        _STATE.ops.append(op)


def _tracing(path) -> "str | None":
    """abspath(path) when tracing should record it, else None."""
    if not _STATE.enabled or _suppressed():
        return None
    if not isinstance(path, (str, os.PathLike)):
        return None
    try:
        p = os.path.abspath(os.fspath(path))
    except (TypeError, ValueError):
        return None
    return p if classify(p) is not None else None


def _add_violation(kind: str, message: str, stacks: tuple, key, prefix: int = 0) -> None:
    with _STATE.lock:
        if key in _STATE.reported:
            return
        _STATE.reported.add(key)
        _STATE.violations.append(Violation(kind, message, stacks, prefix))


# ------------------------------------------------------------ interposition


def _mode_writes(mode) -> bool:
    return isinstance(mode, str) and any(c in mode for c in "wxa")


def _wrapped_open(file, *args, **kwargs):
    mode = kwargs.get("mode", args[0] if args else "r")
    if _mode_writes(mode):
        p = _tracing(file)
        if p is not None:
            try:
                _snapshot_pre(p)
            except Exception:
                pass
            f = _REAL_OPEN(file, *args, **kwargs)
            try:
                _STATE.fd_paths[f.fileno()] = p
                _record(FsOp("write", p, None, None, _stack_summary()))
            except Exception:
                pass
            return f
    return _REAL_OPEN(file, *args, **kwargs)


def _fd_matches(fd: int, path: str) -> bool:
    try:
        return os.fstat(fd).st_ino == os.stat(path).st_ino
    except OSError:
        return False


def _wrapped_fsync(fd):
    _REAL_FSYNC(fd)
    if not _STATE.enabled or _suppressed():
        return
    try:
        p = _STATE.fd_paths.get(fd)
        if p is not None:
            if _fd_matches(fd, p):
                _record(FsOp("fsync", p, None, _read_disk(p), _stack_summary()))
                return
            _STATE.fd_paths.pop(fd, None)  # stale entry: the fd was reused
        d = _STATE.dir_fds.get(fd)
        if d is not None:
            if _fd_matches(fd, d):
                _record(FsOp("fsyncdir", d, None, None, _stack_summary()))
            else:
                _STATE.dir_fds.pop(fd, None)
    except Exception:
        pass


def _durable_in_trace(path: str) -> bool:
    """Does the trace (or the pre-existing tree) make ``path``'s bytes
    durable-or-published: an fsync on it, a rename landing on it, or no
    trace ops at all while the file exists on disk."""
    touched = False
    ok = False
    with _STATE.lock:
        snapshot = list(_STATE.ops)
    for op in snapshot:
        if op.path == path or op.dst == path:
            touched = True
            if op.kind == "fsync" and op.path == path:
                ok = True
            elif op.kind == "replace" and op.dst == path:
                ok = True
            elif op.kind in ("write", "unlink") and op.path == path:
                ok = False
    if not touched:
        return os.path.exists(path)
    return ok


def _rename_common(src, dst, real):
    psrc = _tracing(src)
    pdst = _tracing(dst)
    if psrc is None and pdst is None:
        return real(src, dst)
    try:
        if pdst is not None:
            _snapshot_pre(pdst)
        rp = psrc or os.path.abspath(os.fspath(src))
        data = _read_disk(rp)
        # online check 1: renaming bytes this trace wrote but never fsynced
        wrote = fsynced = False
        with _STATE.lock:
            for op in _STATE.ops:
                if op.path == rp:
                    if op.kind == "write":
                        wrote = True
                    elif op.kind == "fsync":
                        fsynced = True
        stack = _stack_summary()
    except Exception:
        real(src, dst)
        return
    real(src, dst)
    try:
        rdst = pdst or os.path.abspath(os.fspath(dst))
        _record(FsOp("replace", rp, rdst, data, stack))
        if wrote and not fsynced:
            _add_violation(
                "unfsynced-rename",
                f"rename of {rp} published bytes the producing flow never "
                "fsynced — a host crash can land the final name on an "
                "empty inode",
                (stack,),
                ("unfsynced", rp, rdst),
            )
        # online check 2: a CRC sidecar is a barrier — its data must be
        # durable before the sidecar name exists
        art = classify(rdst)
        if art is not None and art.kind == "spill-crc":
            data_path = art.path[: -len(".crc")]
            if not _durable_in_trace(data_path):
                _add_violation(
                    "barrier-before-data",
                    f"CRC sidecar {rdst} published before its data "
                    f"{data_path} is durable — a crash between the two "
                    "leaves a barrier naming bytes that never landed",
                    (stack,),
                    ("barrier", rdst),
                )
    except Exception:
        pass


def _wrapped_replace(src, dst, *, src_dir_fd=None, dst_dir_fd=None):
    if src_dir_fd is not None or dst_dir_fd is not None:
        return _REAL_REPLACE(src, dst, src_dir_fd=src_dir_fd, dst_dir_fd=dst_dir_fd)
    return _rename_common(src, dst, _REAL_REPLACE)


def _wrapped_rename(src, dst, *, src_dir_fd=None, dst_dir_fd=None):
    if src_dir_fd is not None or dst_dir_fd is not None:
        return _REAL_RENAME(src, dst, src_dir_fd=src_dir_fd, dst_dir_fd=dst_dir_fd)
    return _rename_common(src, dst, _REAL_RENAME)


def _unlink_common(path, real):
    p = _tracing(path)
    if p is None:
        return real(path)
    try:
        _snapshot_pre(p)
        stack = _stack_summary()
    except Exception:
        return real(path)
    real(path)
    _record(FsOp("unlink", p, None, None, stack))


def _wrapped_unlink(path, *, dir_fd=None):
    if dir_fd is not None:
        return _REAL_UNLINK(path, dir_fd=dir_fd)
    return _unlink_common(path, _REAL_UNLINK)


def _wrapped_remove(path, *, dir_fd=None):
    if dir_fd is not None:
        return _REAL_REMOVE(path, dir_fd=dir_fd)
    return _unlink_common(path, _REAL_REMOVE)


def _wrapped_os_open(path, flags, mode=0o777, *, dir_fd=None):
    if dir_fd is not None:
        return _REAL_OS_OPEN(path, flags, mode, dir_fd=dir_fd)
    fd = _REAL_OS_OPEN(path, flags, mode)
    if _STATE.enabled and not _suppressed():
        try:
            p = os.path.abspath(os.fspath(path))
            if os.path.isdir(p):
                _STATE.dir_fds[fd] = p
        except Exception:
            pass
    return fd


# ----------------------------------------------------------------- control


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def violations() -> list[Violation]:
    with _STATE.lock:
        return list(_STATE.violations)


def ops() -> list[FsOp]:
    with _STATE.lock:
        return list(_STATE.ops)


def reset() -> None:
    with _STATE.lock:
        _STATE.ops.clear()
        _STATE.fd_paths.clear()
        _STATE.dir_fds.clear()
        _STATE.pre.clear()
        _STATE.violations.clear()
        _STATE.reported.clear()


def enable() -> None:
    """Interpose the filesystem surface.  Idempotent."""
    if _STATE.enabled:
        return
    builtins.open = _wrapped_open
    os.fsync = _wrapped_fsync
    os.replace = _wrapped_replace
    os.rename = _wrapped_rename
    os.unlink = _wrapped_unlink
    os.remove = _wrapped_remove
    os.open = _wrapped_os_open
    _STATE.enabled = True


def disable() -> None:
    """Restore the real filesystem surface.  Recorded state stays for
    inspection/replay until :func:`reset`."""
    if not _STATE.enabled:
        return
    builtins.open = _REAL_OPEN
    os.fsync = _REAL_FSYNC
    os.replace = _REAL_REPLACE
    os.rename = _REAL_RENAME
    os.unlink = _REAL_UNLINK
    os.remove = _REAL_REMOVE
    os.open = _REAL_OS_OPEN
    _STATE.enabled = False


class Watch:
    def __init__(self, mark: int):
        self._mark = mark

    @property
    def violations(self) -> list[Violation]:
        return violations()[self._mark:]


class watch:
    """``with watch() as w:`` — enable for the block; call :func:`replay`
    (before or after exit) and inspect ``w.violations``."""

    def __enter__(self) -> Watch:
        self._was_enabled = _STATE.enabled
        enable()
        return Watch(len(violations()))

    def __exit__(self, *exc):
        if not self._was_enabled:
            disable()
        return False


# ------------------------------------------------------------------- replay

# crash-state entries: ("durable", bytes) | ("torn", bytes|None) | ("absent",)
_ABSENT = ("absent", None)


def _simulate(ops_prefix: "list[FsOp]") -> "dict[str, tuple]":
    """Persisted state after a crash at the end of ``ops_prefix``: only
    fsynced bytes are guaranteed; metadata ops (rename/unlink) apply in
    order; written-but-unfsynced content is torn."""
    state: dict[str, tuple] = {}
    for path, pre in _STATE.pre.items():
        state[path] = ("durable", pre) if pre is not None else _ABSENT
    for op in ops_prefix:
        if op.kind == "write":
            state[op.path] = ("torn", None)
        elif op.kind == "fsync":
            state[op.path] = ("durable", op.data)
        elif op.kind == "replace":
            entry = state.pop(op.path, None)
            if entry is None or entry[0] == "absent":
                # pre-existing source outside the trace: its bytes were
                # already durable, captured at rename time
                entry = ("durable", op.data)
            elif entry[0] == "torn":
                entry = ("torn", op.data)
            state[op.dst] = entry
        elif op.kind == "unlink":
            state[op.path] = _ABSENT
    return state


def _torn_variant(data: "bytes | None", mode: str) -> "bytes | None":
    if mode == "missing":
        return None
    if mode == "empty":
        return b""
    return (data or b"")[: max(0, len(data or b"") // 2)] or b""


def _materialize(scratch: str, root: str, state: "dict[str, tuple]", mode: str) -> None:
    """Write the crash state for every traced path under ``root`` into the
    scratch mirror (untouched live files were copied once as context)."""
    for path, entry in state.items():
        if not path.startswith(root + os.sep) and path != root:
            continue
        dst = os.path.join(scratch, os.path.relpath(path, root))
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.lexists(dst):
            _REAL_UNLINK(dst)
        kind, data = entry[0], entry[1]
        if kind == "torn":
            data = _torn_variant(data, mode)
        if kind == "absent" or data is None:
            continue
        with _REAL_OPEN(dst, "wb") as f:
            f.write(data)


def _copy_context(root: str, scratch: str, touched: "set[str]") -> None:
    """Mirror the live tree under ``root`` minus traced paths — the stable
    context (other sessions' files, shard stores built before the watch)
    the readers may legitimately depend on."""
    for dirpath, _dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        out_dir = scratch if rel == "." else os.path.join(scratch, rel)
        os.makedirs(out_dir, exist_ok=True)
        for name in filenames:
            src = os.path.join(dirpath, name)
            if src in touched or strip_tmp(src)[0] in touched:
                continue
            dst = os.path.join(out_dir, name)
            try:
                os.link(src, dst)
            except OSError:
                try:
                    shutil.copy2(src, dst)
                except OSError:
                    pass


# ------------------------------------------------------------------ readers


def _check_json_doc(path: str) -> None:
    data = _read_disk(path)
    if data is None:
        return  # absent = old-complete
    doc = json.loads(data.decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: torn doc (not an object)")


def _check_session_manifest(path: str) -> None:
    data = _read_disk(path)
    if data is None:
        return
    from lakesoul_tpu.scanplane.session import ScanSession

    ScanSession.from_json(data.decode("utf-8"))


def _check_obs_spool(scratch: str, path: str) -> None:
    _check_json_doc(path)
    from lakesoul_tpu.obs.fleet import FleetAggregator

    agg = FleetAggregator(scratch)
    agg.members()
    agg.recorders()


def _check_ranges(scratch: str, spill: bool = False) -> None:
    """Spool/spill range consistency over the whole scratch dir: a visible
    segment name implies a parseable sidecar and decodable batches; a
    visible CRC sidecar implies fully-landed, checksum-exact data.  In a
    spill prefix (``spill=True``) segments have no JSON sidecar — the CRC
    doc published LAST is their only contract, so a bare segment is just
    an unfinished upload nobody reads yet."""
    import pyarrow as pa

    for name in sorted(os.listdir(scratch)):
        full = os.path.join(scratch, name)
        if _TMP_RE.search(name):
            continue  # tmp debris: swept by the next producer, never read
        if name.endswith(".arrow.crc"):
            doc = json.loads(_read_disk(full).decode("utf-8"))
            seg = os.path.join(scratch, os.path.basename(doc["path"]))
            payload = _read_disk(seg)
            if payload is None:
                raise ValueError(f"{name}: CRC sidecar without its segment")
            if (
                zlib.crc32(payload) & 0xFFFFFFFF != int(doc["crc32"])
                or len(payload) != int(doc["nbytes"])
            ):
                raise ValueError(f"{name}: CRC mismatch on spilled segment")
        elif name.endswith(".arrow"):
            payload = _read_disk(full)
            with pa.ipc.open_file(pa.BufferReader(payload)) as reader:
                rows = sum(
                    reader.get_batch(i).num_rows
                    for i in range(reader.num_record_batches)
                )
            if spill or os.path.exists(full + ".crc"):
                continue  # spill rung: the CRC doc above is its contract
            sidecar = os.path.join(scratch, name[: -len(".arrow")] + ".json")
            side_raw = _read_disk(sidecar)
            if side_raw is None:
                raise ValueError(f"{name}: published segment without sidecar")
            side = json.loads(side_raw.decode("utf-8"))
            if int(side["rows"]) != rows:
                raise ValueError(
                    f"{name}: sidecar rows {side['rows']} != segment rows {rows}"
                )


def _check_store(scratch: str) -> None:
    """Pointer-chase the manifest store(s) in scratch with the real
    readers: a visible pointer must name a complete, CRC-exact record."""
    from lakesoul_tpu.errors import VectorIndexError
    from lakesoul_tpu.vector.manifest import ManifestStore, _crc_unwrap

    if os.path.exists(os.path.join(scratch, "PLANE")):
        from lakesoul_tpu.annplane.manifest import PlaneManifestStore

        manifest = PlaneManifestStore(scratch).read()
        if manifest is not None and manifest.get("complete"):
            from lakesoul_tpu.annplane.search import AnnPlane

            try:
                AnnPlane.open(scratch)
            except VectorIndexError as exc:
                if "mid-build" not in str(exc) and "no ANN plane" not in str(exc):
                    raise
    if os.path.exists(os.path.join(scratch, "LATEST")):
        store = ManifestStore(scratch)
        manifest = store.read_manifest()
        for rel in manifest.get("base_segments", []):
            _crc_unwrap(store._read_blob(rel), rel)
        for entry in manifest.get("delta_segments", []):
            _crc_unwrap(store._read_blob(entry["path"]), entry["path"])


# ``kinds`` is every artifact kind the trace touched under the same replay
# root — a segment in a spill prefix (kinds include spill-crc, never
# range-sidecar) plays by the CRC-doc contract, not the spool sidecar one
_READERS = {
    "session-manifest": lambda scratch, art, kinds: _check_session_manifest(
        os.path.join(scratch, os.path.basename(art.path))
    ),
    "range-segment": lambda scratch, art, kinds: _check_ranges(
        scratch, spill="spill-crc" in kinds and "range-sidecar" not in kinds
    ),
    "range-sidecar": lambda scratch, art, kinds: _check_ranges(scratch),
    "spill-crc": lambda scratch, art, kinds: _check_ranges(scratch, spill=True),
    "obs-doc": lambda scratch, art, kinds: _check_obs_spool(
        scratch, os.path.join(scratch, os.path.basename(art.path))
    ),
    "store-pointer": lambda scratch, art, kinds: _check_store(scratch),
    "store-record": lambda scratch, art, kinds: _check_store(scratch),
    "store-segment": lambda scratch, art, kinds: _check_store(scratch),
    "spill-probe": lambda scratch, art, kinds: _check_json_doc(
        os.path.join(scratch, os.path.basename(art.path))
    ),
    "json-doc": lambda scratch, art, kinds: _check_json_doc(
        os.path.join(scratch, os.path.basename(art.path))
    ),
}


def replay(tmp_root: "str | None" = None) -> list[Violation]:
    """Crash-prefix replay over every recorded publication: for each op
    prefix, materialize the crash state in a scratch mirror and run the
    affected artifact's real reader.  New violations are recorded (and
    returned) — never raised."""
    with _STATE.lock:
        trace = list(_STATE.ops)
    if not trace:
        return []
    mark = len(violations())
    with _suppress():
        base = tempfile.mkdtemp(prefix="fscheck-", dir=tmp_root)
        try:
            _replay_into(trace, base)
        finally:
            shutil.rmtree(base, ignore_errors=True)
    return violations()[mark:]


def _replay_into(trace: "list[FsOp]", base: str) -> None:
    # every traced path, final AND tmp form, per replay root — excluded
    # from the context mirror, defined purely by simulation
    touched: dict[str, set] = {}
    root_kinds: dict[str, set] = {}
    roots: dict[str, str] = {}  # root -> scratch dir
    for op in trace:
        for p in (op.path, op.dst):
            if p is None:
                continue
            art = classify(p)
            if art is None:
                continue
            touched.setdefault(art.root, set()).update((p, art.path))
            root_kinds.setdefault(art.root, set()).add(art.kind)
    for i, root in enumerate(sorted(touched)):
        scratch = os.path.join(base, f"root-{i:02d}")
        _copy_context(root, scratch, touched[root])
        roots[root] = scratch

    # not a retry loop: every prefix is replayed exactly once and every
    # reader failure is recorded as a violation, not retried away
    for k in range(1, len(trace) + 1):  # lakelint: ignore[ad-hoc-retry] replay
        op = trace[k - 1]
        anchor = op.dst if op.kind == "replace" else op.path
        art = classify(anchor) if anchor else None
        if art is None or art.root not in roots:
            continue
        reader = _READERS.get(art.kind)
        if reader is None:
            continue
        state = _simulate(trace[:k])
        has_torn = any(
            e[0] == "torn"
            for p, e in state.items()
            if p.startswith(art.root + os.sep)
        )
        modes = ("missing", "empty", "half") if has_torn else ("exact",)
        for mode in modes:  # lakelint: ignore[ad-hoc-retry] torn fan-out
            scratch = roots[art.root]
            _materialize(scratch, art.root, state, mode)
            try:
                reader(scratch, art, root_kinds.get(art.root, set()))
            except Exception as exc:
                _add_violation(
                    "torn-state",
                    f"crash at prefix {k}/{len(trace)} (op {op.kind} "
                    f"{os.path.basename(anchor)}, torn-mode {mode}) leaves "
                    f"{art.kind} at {art.path} neither old-complete nor "
                    f"new-complete: reader failed with "
                    f"{type(exc).__name__}: {exc}",
                    (
                        f"publishing op:\n{op.stack}",
                        "reader:\n" + "".join(
                            traceback.format_exception(
                                type(exc), exc, exc.__traceback__, limit=6
                            )
                        ),
                    ),
                    ("torn", art.path, k, mode, type(exc).__name__),
                    prefix=k,
                )
