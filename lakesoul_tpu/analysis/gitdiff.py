"""Diff-aware finding resolution: ``--diff BASE`` mode.

New interprocedural rules must be able to land *strict on new code* while
pre-existing findings live in ``baseline.json``.  The mechanism: run
``git diff BASE --unified=0`` over the repo, parse the post-image hunk
ranges, and keep only findings whose line falls on a changed/added line of
a changed file.  A finding an edit merely *moved* still fires (its line is
in a hunk); a finding in untouched code does not block the gate.

``git`` failures (not a repo, unknown BASE, missing binary) raise
:class:`~lakesoul_tpu.analysis.engine.EngineError` — the CLI maps that to
exit 2 so CI can distinguish "your diff has findings" from "the gate
itself is broken".
"""

from __future__ import annotations

import re
import subprocess
from pathlib import Path

from lakesoul_tpu.analysis.engine import EngineError, Finding

__all__ = ["changed_lines", "filter_to_diff"]

_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def changed_lines(base: str, root: Path) -> dict[str, set[int]]:
    """``{repo-relative posix path: {changed post-image line, ...}}`` for
    ``git diff <base>`` under ``root``.  Zero-length post-hunks (pure
    deletions) contribute no lines — nothing new to lint there."""
    try:
        # pin the prefix and disable external diff drivers: a user's
        # diff.mnemonicprefix/diff.noprefix config would change the '+++'
        # prefix and silently empty the changed-line map (a vacuously
        # green strict-on-new-code gate)
        proc = subprocess.run(  # lakelint: ignore[raw-process] git CLI is the diff oracle: a bounded, reaped, check=False invocation — not a serving/worker process
            [
                "git", "-c", "diff.mnemonicprefix=false",
                "-c", "diff.noprefix=false", "diff", "--no-ext-diff",
                "--unified=0", "--no-color", base, "--", "*.py",
            ],
            cwd=str(root),
            capture_output=True,
            text=True,
            timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise EngineError(f"git diff {base!r} failed to run: {e}")
    if proc.returncode not in (0, 1):  # 1 = differences found (fine)
        raise EngineError(
            f"git diff {base!r} exited {proc.returncode}: "
            f"{proc.stderr.strip() or proc.stdout.strip()}"
        )
    out: dict[str, set[int]] = {}
    current: set[int] | None = None
    for line in proc.stdout.splitlines():
        if line.startswith("+++ "):
            target = line[4:].strip()
            if target == "/dev/null":  # deleted file: nothing to lint
                current = None
                continue
            if target.startswith("b/"):
                target = target[2:]
            current = out.setdefault(target, set())
            continue
        m = _HUNK_RE.match(line)
        if m and current is not None:
            start = int(m.group(1))
            count = int(m.group(2)) if m.group(2) is not None else 1
            current.update(range(start, start + count))
    return out


def filter_to_diff(
    findings: list[Finding], base: str, root: Path
) -> list[Finding]:
    """Findings that touch lines changed since ``base``."""
    changed = changed_lines(base, root)
    return [
        f for f in findings
        if f.line in changed.get(f.path, ())
    ]
