"""Runtime resource-leak detector (opt-in: ``LAKESOUL_LEAKCHECK=1``).

The boundedness rules (``rules/boundedness.py``) prove lexical lifecycle
discipline; this half of the pack watches the *actual* resources.  The
static rules can't see a thread leaked through dynamic dispatch, an fd
held by a C extension, or spool debris created via a path the resolver
couldn't pin — so :func:`enable` patches the creation seams themselves:

- ``threading.Thread.start`` — the creation stack rides on the thread
  object, so a leak report names the line that started it;
- ``subprocess.Popen`` — every child is registered with its spawn stack;
- ``runtime.atomicio.stage_stream`` — every staged tmp file is tracked
  until commit/abort unlinks it (a surviving ``.tmp-*`` IS debris);
- ``tempfile.mkdtemp`` — scratch dirs are tracked so a scope that made
  one and never pruned it gets the creating stack back.

:func:`snapshot` captures the per-process resource inventory —
``/proc/self/fd`` (with readlink targets), live threads, tracked child
pids, tracked artifacts still on disk, and the tracemalloc-traced heap
when tracing is on — and :func:`diff` compares two snapshots and records
a :class:`Violation` per leaked resource, each with its creation stack
when the seam saw it.  The :class:`scope` context manager snapshots on
enter and diffs on exit; the conftest autouse fixture wraps each armed
test in one (test_runtime, test_scanplane, test_fleet, test_resilience,
test_freshness), and the ``benchmarks/micro.py soak`` leg wraps whole
open→scan→serve→close cycles.

Violations are *recorded*, not raised — same contract as lockgraph:
instrumentation must not change data-path behavior; the fixture fails
the test at teardown.

Deliberate scope limits: fd leaks are only reported for targets under
/dev/shm, a spool prefix, or a staged ``.tmp-`` path — a process-wide
cache legitimately holding a warehouse fd open across tests is not a
leak, while ANY surviving tmpfs handle is.  Threads of the sanctioned
process-wide pool singleton (``lakesoul-rt*``) are exempt: the pool
outlives every test by design.  The raw fd/thread counts still ride on
every snapshot so the soak leg can gate on their slope.
"""

from __future__ import annotations

import os
import subprocess
import threading
import traceback
import weakref
from dataclasses import dataclass, field

from lakesoul_tpu.analysis.lockgraph import real_lock

__all__ = [
    "Violation",
    "Snapshot",
    "snapshot",
    "diff",
    "scope",
    "enable",
    "disable",
    "reset",
    "violations",
    "enabled",
    "env_requested",
]

_ENV = "LAKESOUL_LEAKCHECK"

# process-wide singletons whose threads legitimately outlive any scope
_SANCTIONED_THREAD_PREFIXES = ("lakesoul-rt",)

# fd targets that are ALWAYS a leak when they survive a scope; anything
# else (warehouse files, sockets, sqlite dbs) may be a legitimate cache
_DEBRIS_FD_MARKERS = ("/dev/shm/", "lakesoul-scanplane-", ".tmp-")


@dataclass
class Violation:
    kind: str  # "thread-leak" | "child-leak" | "fd-leak" | "debris" | "heap-growth"
    message: str
    stacks: tuple[str, ...] = ()

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for s in self.stacks:
            out.append(s.rstrip())
        return "\n".join(out)


def _stack_summary() -> str:
    frames = traceback.extract_stack()[:-2]
    keep = [
        f"  {fr.filename}:{fr.lineno} in {fr.name}"
        for fr in frames[-8:]
        if "lakesoul_tpu/analysis/leakcheck" not in fr.filename.replace("\\", "/")
    ]
    return "\n".join(keep)


class _State:
    def __init__(self):
        self.lock = real_lock()
        self.enabled = False
        # pid -> (weakref to Popen, creation stack)
        self.children: dict[int, tuple] = {}
        # artifact path -> creation stack (staged tmps, mkdtemp dirs)
        self.artifacts: dict[str, str] = {}
        self.violations: list[Violation] = []
        self.reported: set = set()


_STATE = _State()


# ------------------------------------------------------------ seam patches
# Originals are captured at patch time and restored on disable; each patch
# marks itself so a double enable() can't wrap twice.

_REAL_THREAD_START = None
_REAL_POPEN_INIT = None
_REAL_STAGE_STREAM = None
_REAL_MKDTEMP = None


def _patched_thread_start(self):
    if _STATE.enabled:
        self._leakcheck_stack = _stack_summary()
    return _REAL_THREAD_START(self)


def _patched_popen_init(self, *args, **kwargs):
    _REAL_POPEN_INIT(self, *args, **kwargs)
    if _STATE.enabled:
        stack = _stack_summary()
        with _STATE.lock:
            _STATE.children[self.pid] = (weakref.ref(self), stack)


def _patched_stage_stream(path, write_fn, **kwargs):
    staged = _REAL_STAGE_STREAM(path, write_fn, **kwargs)
    if _STATE.enabled:
        with _STATE.lock:
            _STATE.artifacts[staged.tmp] = _stack_summary()
    return staged


def _patched_mkdtemp(*args, **kwargs):
    d = _REAL_MKDTEMP(*args, **kwargs)
    # pytest's basetemp tree is mkdtemp-created and *retained by design*
    # (the last runs stay on disk for debugging) — not debris
    if _STATE.enabled and "pytest-" not in d:
        with _STATE.lock:
            _STATE.artifacts[d] = _stack_summary()
    return d


def _instrument() -> None:
    global _REAL_THREAD_START, _REAL_POPEN_INIT
    global _REAL_STAGE_STREAM, _REAL_MKDTEMP
    import tempfile

    from lakesoul_tpu.runtime import atomicio

    if _REAL_THREAD_START is None:
        _REAL_THREAD_START = threading.Thread.start
        threading.Thread.start = _patched_thread_start
    if _REAL_POPEN_INIT is None:
        _REAL_POPEN_INIT = subprocess.Popen.__init__
        subprocess.Popen.__init__ = _patched_popen_init
    if _REAL_STAGE_STREAM is None:
        _REAL_STAGE_STREAM = atomicio.stage_stream
        atomicio.stage_stream = _patched_stage_stream
    if _REAL_MKDTEMP is None:
        _REAL_MKDTEMP = tempfile.mkdtemp
        tempfile.mkdtemp = _patched_mkdtemp


def _restore() -> None:
    global _REAL_THREAD_START, _REAL_POPEN_INIT
    global _REAL_STAGE_STREAM, _REAL_MKDTEMP
    import tempfile

    from lakesoul_tpu.runtime import atomicio

    if _REAL_THREAD_START is not None:
        threading.Thread.start = _REAL_THREAD_START
        _REAL_THREAD_START = None
    if _REAL_POPEN_INIT is not None:
        subprocess.Popen.__init__ = _REAL_POPEN_INIT
        _REAL_POPEN_INIT = None
    if _REAL_STAGE_STREAM is not None:
        atomicio.stage_stream = _REAL_STAGE_STREAM
        _REAL_STAGE_STREAM = None
    if _REAL_MKDTEMP is not None:
        tempfile.mkdtemp = _REAL_MKDTEMP
        _REAL_MKDTEMP = None


# --------------------------------------------------------------- snapshots


@dataclass(frozen=True)
class Snapshot:
    """One resource inventory.  ``fd_targets`` maps fd → readlink target
    for post-hoc attribution; ``heap`` is the tracemalloc-traced current
    bytes (None when tracing is off — tracing is the caller's choice, the
    soak leg turns it on, the per-test fixture does not pay for it)."""

    fds: frozenset
    fd_targets: "dict[int, str]" = field(compare=False, default_factory=dict)
    threads: frozenset = frozenset()
    children: frozenset = frozenset()
    artifacts: frozenset = frozenset()
    heap: "int | None" = None

    @property
    def fd_count(self) -> int:
        return len(self.fds)

    @property
    def thread_count(self) -> int:
        return len(self.threads)


def _fd_inventory() -> "tuple[frozenset, dict]":
    fds = []
    targets = {}
    try:
        names = os.listdir("/proc/self/fd")
    except OSError:
        return frozenset(), {}
    for name in names:
        try:
            fd = int(name)
        except ValueError:
            continue
        try:
            targets[fd] = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue  # closed between listdir and readlink
        fds.append(fd)
    return frozenset(fds), targets


def _live_tracked_children() -> frozenset:
    with _STATE.lock:
        items = list(_STATE.children.items())
    live = []
    for pid, (ref, _stack) in items:
        proc = ref()
        if proc is not None and proc.poll() is None:
            live.append(pid)
    return frozenset(live)


def _existing_artifacts() -> frozenset:
    with _STATE.lock:
        paths = list(_STATE.artifacts)
    return frozenset(p for p in paths if os.path.exists(p))


def snapshot() -> Snapshot:
    import tracemalloc

    fds, targets = _fd_inventory()
    return Snapshot(
        fds=fds,
        fd_targets=targets,
        threads=frozenset(t.ident for t in threading.enumerate()),
        children=_live_tracked_children(),
        artifacts=_existing_artifacts(),
        heap=(
            tracemalloc.get_traced_memory()[0]
            if tracemalloc.is_tracing()
            else None
        ),
    )


def _record(v: Violation, key) -> None:
    with _STATE.lock:
        if key in _STATE.reported:
            return
        _STATE.reported.add(key)
        _STATE.violations.append(v)


def diff(before: Snapshot, *, label: str = "scope",
         heap_budget: "int | None" = None,
         join_grace_s: float = 0.5) -> "list[Violation]":
    """Compare now against ``before`` and record one violation per leaked
    resource.  Leak candidates that are merely *slow* get grace: new
    threads are joined up to ``join_grace_s`` before being reported (a
    stop path that raced the snapshot is not a leak)."""
    found: list[Violation] = []

    # threads: new, still alive, not sanctioned
    for t in threading.enumerate():
        if t.ident in before.threads or t is threading.current_thread():
            continue
        if t.name.startswith(_SANCTIONED_THREAD_PREFIXES):
            continue
        t.join(timeout=join_grace_s)
        if not t.is_alive():
            continue
        stack = getattr(t, "_leakcheck_stack", None)
        v = Violation(
            "thread-leak",
            f"{label}: thread {t.name!r} (daemon={t.daemon}) started during "
            "the scope is still running at scope end — nothing joined or "
            "stopped it",
            (stack,) if stack else (),
        )
        _record(v, ("thread", t.ident))
        found.append(v)

    # children: tracked pids spawned during the scope, still running
    with _STATE.lock:
        tracked = list(_STATE.children.items())
    for pid, (ref, stack) in tracked:
        if pid in before.children:
            continue
        proc = ref()
        if proc is None or proc.poll() is not None:
            continue
        v = Violation(
            "child-leak",
            f"{label}: child pid {pid} spawned during the scope is still "
            "running at scope end — no wait/terminate reached it",
            (stack,),
        )
        _record(v, ("child", pid))
        found.append(v)

    # artifacts: staged tmps / scratch dirs created during the scope that
    # still exist (commit renames, abort unlinks, pruners rmtree — a
    # survivor means none of them ran)
    now_artifacts = _existing_artifacts()
    with _STATE.lock:
        stacks = dict(_STATE.artifacts)
    for path in sorted(now_artifacts - before.artifacts):
        v = Violation(
            "debris",
            f"{label}: scratch path {path} created during the scope still "
            "exists at scope end — it never flowed into a commit, abort, "
            "or prune seam",
            (stacks.get(path, ""),),
        )
        _record(v, ("debris", path))
        found.append(v)

    # fds: new descriptors whose target is unambiguously scratch state
    fds, targets = _fd_inventory()
    for fd in sorted(fds - before.fds):
        target = targets.get(fd, "")
        if not any(m in target for m in _DEBRIS_FD_MARKERS):
            continue
        v = Violation(
            "fd-leak",
            f"{label}: fd {fd} → {target} opened during the scope is still "
            "open at scope end",
        )
        _record(v, ("fd", fd, target))
        found.append(v)

    # heap: only a violation when the caller set a budget (the soak leg
    # gates on slope instead; per-test scopes just carry the numbers)
    if heap_budget is not None and before.heap is not None:
        import tracemalloc

        if tracemalloc.is_tracing():
            now_heap = tracemalloc.get_traced_memory()[0]
            growth = now_heap - before.heap
            if growth > heap_budget:
                v = Violation(
                    "heap-growth",
                    f"{label}: traced heap grew {growth} bytes over the "
                    f"scope (budget {heap_budget})",
                )
                _record(v, ("heap", label))
                found.append(v)
    return found


class scope:
    """``with scope("test_x"):`` — snapshot on enter, diff on exit; every
    leak becomes a recorded violation carrying its creation stack."""

    def __init__(self, label: str = "scope",
                 heap_budget: "int | None" = None):
        self.label = label
        self.heap_budget = heap_budget
        self.before: "Snapshot | None" = None
        self.leaks: "list[Violation]" = []

    def __enter__(self) -> "scope":
        self.before = snapshot()
        return self

    def __exit__(self, *exc):
        if self.before is not None:
            self.leaks = diff(
                self.before, label=self.label, heap_budget=self.heap_budget
            )
        return False


# ----------------------------------------------------------------- control


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def violations() -> "list[Violation]":
    with _STATE.lock:
        return list(_STATE.violations)


def reset() -> None:
    """Drop recorded registries and violations."""
    with _STATE.lock:
        _STATE.children.clear()
        _STATE.artifacts.clear()
        _STATE.violations.clear()
        _STATE.reported.clear()


def enable() -> None:
    """Patch the creation seams.  Idempotent."""
    if _STATE.enabled:
        return
    _instrument()
    _STATE.enabled = True


def disable() -> None:
    """Restore the real seams; recording stops."""
    if not _STATE.enabled:
        return
    _restore()
    _STATE.enabled = False
