"""Runtime lock-order / race detector (opt-in: ``LAKESOUL_LOCKCHECK=1``).

Static rules can't see dynamic lock ordering, so this half of lakelint
instruments the locks themselves: :func:`enable` patches
``threading.Lock``/``threading.RLock`` with checked wrappers (locks created
*before* enabling are untouched — the detector targets per-object data-path
locks, not interpreter internals) and hooks
:meth:`~lakesoul_tpu.runtime.pool.WorkerPool.submit`.

What it catches:

- **Lock-order cycles.**  Every thread keeps its held-lock stack; acquiring
  B while holding A records the global edge A→B with the acquiring stack.
  An acquisition that would close a cycle (B→…→A already recorded from any
  thread) is a potential deadlock even if this run got lucky with timing —
  exactly the class that's unreproducible under pytest and fatal in
  production.
- **Lock-held-across-``pool.submit``.**  Submitting pool work while holding
  a lock is the nested-pool deadlock shape: a worker that needs that lock
  parks, the submitter blocks on the worker, the pool wedges.  (The static
  ``lock-held-call`` rule catches the lexical version; this catches it
  through any call depth.)

Violations are *recorded*, not raised — the data path must not change
behavior under instrumentation; the conftest fixture fails the test at
teardown instead.  Per-thread state is bookkept unconditionally on checked
locks so enable/disable cycles can't desync the stacks; only violation
*recording* is gated on the enabled flag.
"""

from __future__ import annotations

import itertools
import os
import threading
import traceback
from dataclasses import dataclass

__all__ = [
    "Violation",
    "enable",
    "disable",
    "reset",
    "violations",
    "enabled",
    "env_requested",
    "watch",
    "current_held",
    "instrument_locks",
    "uninstrument_locks",
    "real_lock",
]

_ENV = "LAKESOUL_LOCKCHECK"

# originals captured at import: the wrappers and the detector's own state
# must keep working while threading.Lock/RLock point at the factories
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


@dataclass
class Violation:
    kind: str  # "lock-cycle" | "submit-while-locked"
    message: str
    stacks: tuple[str, ...] = ()

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for s in self.stacks:
            out.append(s.rstrip())
        return "\n".join(out)


class _State:
    def __init__(self):
        self.lock = _REAL_LOCK()
        # (serial_a, serial_b) -> (name_a, name_b, acquiring stack summary).
        # Keyed by per-wrapper monotonic serials, NOT id(): a GC'd lock's
        # address gets reused and would inherit the dead lock's edges,
        # producing false cycles on correctly ordered code.
        self.edges: dict[tuple[int, int], tuple[str, str, str]] = {}
        self.successors: dict[int, set[int]] = {}
        self.violations: list[Violation] = []
        self.reported: set[tuple] = set()
        self.enabled = False


_STATE = _State()
_TLS = threading.local()


def _held_stack() -> list:
    stack = getattr(_TLS, "held", None)
    if stack is None:
        stack = _TLS.held = []
    return stack


def _site(depth_skip: int = 3) -> str:
    frames = traceback.extract_stack()[:-depth_skip]
    for fr in reversed(frames):
        if "lakesoul_tpu/analysis/lockgraph" not in fr.filename.replace("\\", "/"):
            return f"{fr.filename}:{fr.lineno} in {fr.name}"
    return "<unknown>"


def _stack_summary() -> str:
    frames = traceback.extract_stack()[:-3]
    keep = [
        f"  {fr.filename}:{fr.lineno} in {fr.name}"
        for fr in frames[-8:]
        if "lakesoul_tpu/analysis/lockgraph" not in fr.filename.replace("\\", "/")
    ]
    return "\n".join(keep)


def _path_exists(src: int, dst: int) -> bool:
    """DFS over recorded edges: is there a held-before path src →* dst?"""
    seen = set()
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_STATE.successors.get(cur, ()))
    return False


def _before_acquire(lock: "_CheckedBase") -> None:
    held = _held_stack()
    if any(entry[0] is lock for entry in held):
        return  # re-entrant acquire: no new ordering information
    if not held or not _STATE.enabled:
        return
    with _STATE.lock:
        for held_lock, _count in held:
            a, b = held_lock.serial, lock.serial
            if a == b:
                continue
            if (a, b) not in _STATE.edges:
                # would acquiring b while holding a close a cycle b →* a?
                if _STATE.enabled and _path_exists(b, a):
                    key = ("cycle", frozenset((a, b)))
                    if key not in _STATE.reported:
                        _STATE.reported.add(key)
                        back = next(
                            (
                                e
                                for (x, y), e in _STATE.edges.items()
                                if x == b and y == a
                            ),
                            None,
                        )
                        stacks = [f"second order ({held_lock.name} -> {lock.name}):\n{_stack_summary()}"]
                        if back is not None:
                            stacks.insert(
                                0,
                                f"first order ({back[0]} -> {back[1]}):\n{back[2]}",
                            )
                        _STATE.violations.append(
                            Violation(
                                "lock-cycle",
                                f"acquiring {lock.name} while holding "
                                f"{held_lock.name} inverts an existing "
                                "lock order — potential deadlock",
                                tuple(stacks),
                            )
                        )
                _STATE.edges[(a, b)] = (
                    held_lock.name,
                    lock.name,
                    _stack_summary(),
                )
                _STATE.successors.setdefault(a, set()).add(b)


def _on_acquired(lock: "_CheckedBase", n: int = 1) -> None:
    held = _held_stack()
    for entry in held:
        if entry[0] is lock:
            entry[1] += n
            return
    held.append([lock, n])
    # remember WHICH thread's stack holds this lock: a plain Lock may
    # legally be released from another thread (handoff/gate pattern), and
    # the release must clear the acquirer's entry, not leave a phantom hold
    lock._hold_lists.append(held)


def _drop_entry(held: list, lock: "_CheckedBase", n: int) -> bool:
    for i in range(len(held) - 1, -1, -1):  # lakelint: ignore[ad-hoc-retry] reverse index scan with a concurrent-remove guard, returns on first hit — not a retry loop
        if held[i][0] is lock:
            held[i][1] -= n
            if held[i][1] <= 0:
                del held[i]
                try:
                    lock._hold_lists.remove(held)
                except ValueError:
                    pass
            return True
    return False


def _on_released(lock: "_CheckedBase", n: int = 1) -> None:
    if _drop_entry(_held_stack(), lock, n):
        return
    # not held by this thread: cross-thread release — clear the hold from
    # whichever thread acquired it
    for held in list(lock._hold_lists):
        if _drop_entry(held, lock, n):
            return


class _CheckedBase:
    """Duck-typed Lock/RLock wrapper: bookkeeping around the real primitive.
    ``__getattr__`` falls through so hasattr-probing callers (Condition)
    see exactly the inner lock's capabilities."""

    _serials = itertools.count(1)  # never reused, unlike id()

    def __init__(self, inner):
        self._inner = inner
        self.serial = next(_CheckedBase._serials)
        self._hold_lists: list = []  # held-stacks currently containing us
        self.name = f"{type(inner).__name__.lstrip('_')}@{_site()}"

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _on_acquired(self)
        return ok

    def release(self):
        self._inner.release()
        _on_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, item):
        # hasattr probes (threading.Condition) must see exactly the inner
        # primitive's capabilities; guard against recursion before _inner set
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(item)
        return getattr(inner, item)

    def __repr__(self):
        return f"<checked {self.name}>"


class CheckedLock(_CheckedBase):
    def locked(self):
        return self._inner.locked()


class CheckedRLock(_CheckedBase):
    # Condition(lock) binds these if present; the bookkeeping must ride
    # along or cond.wait() would leave a phantom hold on the stack
    def _release_save(self):
        state = self._inner._release_save()
        # an RLock _release_save drops EVERY recursion level
        count = state[0] if isinstance(state, tuple) else 1
        _on_released(self, n=count)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        count = state[0] if isinstance(state, tuple) else 1
        _on_acquired(self, n=count)

    def _is_owned(self):
        return self._inner._is_owned()


def _make_lock():
    return CheckedLock(_REAL_LOCK())


def _make_rlock():
    return CheckedRLock(_REAL_RLOCK())


# ------------------------------------------------------- lock instrumentation
# The checked-lock wrappers serve TWO detectors: this module's lock-order
# graph and racecheck's per-field lockset tracking (it reads current_held()).
# Both may be armed independently per test, so the threading.Lock/RLock
# patch is refcounted — the real primitives come back only when the last
# detector lets go.

_PATCH_COUNT = 0


def real_lock():
    """An UNchecked lock for detector-internal state — the detectors must
    never trace their own bookkeeping locks."""
    return _REAL_LOCK()


def instrument_locks() -> None:
    """Patch ``threading.Lock``/``threading.RLock`` to checked wrappers
    (refcounted; see above)."""
    global _PATCH_COUNT
    _PATCH_COUNT += 1
    if _PATCH_COUNT == 1:
        threading.Lock = _make_lock
        threading.RLock = _make_rlock


def uninstrument_locks() -> None:
    global _PATCH_COUNT
    if _PATCH_COUNT == 0:
        return
    _PATCH_COUNT -= 1
    if _PATCH_COUNT == 0:
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK


# --------------------------------------------------------------- pool hook


def _patched_submit(orig):
    def submit(self, fn, /, *args, **kwargs):
        if _STATE.enabled:
            held = current_held()
            if held:
                with _STATE.lock:
                    key = ("submit", tuple(l.name for l in held))
                    if key not in _STATE.reported:
                        _STATE.reported.add(key)
                        _STATE.violations.append(
                            Violation(
                                "submit-while-locked",
                                "pool.submit while holding "
                                + ", ".join(l.name for l in held)
                                + " — a worker needing that lock deadlocks "
                                "the pool",
                                (_stack_summary(),),
                            )
                        )
        return orig(self, fn, *args, **kwargs)

    submit._lockgraph_orig = orig
    return submit


# ----------------------------------------------------------------- control


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def current_held() -> list:
    """Checked locks the CURRENT thread holds right now."""
    return [entry[0] for entry in _held_stack()]


def violations() -> list[Violation]:
    with _STATE.lock:
        return list(_STATE.violations)


def reset() -> None:
    """Drop recorded edges and violations (held stacks stay — they mirror
    real lock state)."""
    with _STATE.lock:
        _STATE.edges.clear()
        _STATE.successors.clear()
        _STATE.violations.clear()
        _STATE.reported.clear()


def enable() -> None:
    """Patch lock construction + pool submit.  Idempotent."""
    if _STATE.enabled:
        return
    instrument_locks()
    from lakesoul_tpu.runtime.pool import WorkerPool

    if not hasattr(WorkerPool.submit, "_lockgraph_orig"):
        WorkerPool.submit = _patched_submit(WorkerPool.submit)
    _STATE.enabled = True


def disable() -> None:
    """Restore the real primitives.  Checked locks already handed out keep
    working (bookkeeping stays consistent); recording stops."""
    if not _STATE.enabled:
        return
    uninstrument_locks()
    from lakesoul_tpu.runtime.pool import WorkerPool

    orig = getattr(WorkerPool.submit, "_lockgraph_orig", None)
    if orig is not None:
        WorkerPool.submit = orig
    _STATE.enabled = False


class Watch:
    """Handle yielded by :func:`watch`: the violations recorded since the
    watch began."""

    def __init__(self, mark: int):
        self._mark = mark

    @property
    def violations(self) -> list[Violation]:
        return violations()[self._mark :]


class watch:
    """``with watch() as w:`` — enable for the block, inspect
    ``w.violations`` after (detector state is NOT reset on exit so nested
    watches compose; call :func:`reset` between independent scenarios)."""

    def __enter__(self) -> Watch:
        self._was_enabled = _STATE.enabled
        enable()
        return Watch(len(violations()))

    def __exit__(self, *exc):
        if not self._was_enabled:
            disable()
        return False
