"""Runtime race detector (opt-in: ``LAKESOUL_RACECHECK=1``).

The static lockset rules (``shared-state-race``/``racy-check-then-act``)
see lexical lock scopes and resolved call edges; this is their runtime
half, in the :mod:`~lakesoul_tpu.analysis.lockgraph` mold: instrument the
hot classes themselves and run **Eraser's lockset algorithm** on what the
threads actually do.

Mechanics:

- :func:`enable` patches ``__setattr__`` on the instrumented hot classes
  (:data:`HOT_CLASSES`: the rebatcher, the admission controller and
  circuit breaker, the pipeline iterator, the lease heartbeat, the ANN
  endpoint) and shares the lockgraph's checked-lock machinery
  (``instrument_locks()``) so every attribute write knows which locks the
  writing thread holds.
- Per ``(object, field)``, Eraser's state machine: the first writing
  thread owns the field exclusively (the init phase — construction
  happens-before publication).  The moment a SECOND thread writes, the
  field's candidate lockset is initialized to the locks held at that
  write and intersected at every write after; an empty intersection is a
  :class:`Violation` carrying **both access stacks** (the first owner's
  and the racing writer's).  Reads are not tracked (that would need
  ``__getattribute__`` interception on every access — the write-write
  detector is the 90% case and costs ~nothing when disarmed).
- **Ring canary/poison mode**: ``_BufferRing.next_slot`` is patched so
  every slot hand-out first checks, per buffer, that no borrower still
  holds a reference (the slot's arrays must be referenced by the slot
  dict alone — a live delivered batch means the consumer violated the
  ``LAKESOUL_COLLATE_REUSE`` contract and is about to read overwritten
  bytes), then fills the buffers with a poison byte pattern so any stale
  read that does survive is loud garbage instead of plausible training
  data.  Collate overwrites every row of the slot, so poisoning is
  invisible to conforming consumers (byte-identity preserved).

Violations are *recorded*, not raised — instrumentation must never change
program behavior; the conftest fixture arms the detector for
``test_runtime``/``test_resilience``/``test_topology`` and fails the test
at teardown, exactly like the lockgraph and tracecheck detectors.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import traceback
import weakref
from dataclasses import dataclass, field

from lakesoul_tpu.analysis import lockgraph

__all__ = [
    "HOT_CLASSES",
    "Violation",
    "enable",
    "disable",
    "enabled",
    "env_requested",
    "instrument_class",
    "reset",
    "violations",
    "watch",
]

_ENV = "LAKESOUL_RACECHECK"

# (module, class): the shared-state hot spots of the concurrent data path —
# instance scalars/flags whose torn updates are silent corruption
HOT_CLASSES = (
    ("lakesoul_tpu.data.jax_iter", "_Rebatcher"),
    ("lakesoul_tpu.data.jax_iter", "LoaderStats"),
    ("lakesoul_tpu.runtime.pipeline", "PipelineIterator"),
    ("lakesoul_tpu.runtime.resilience", "AdmissionController"),
    ("lakesoul_tpu.runtime.resilience", "CircuitBreaker"),
    ("lakesoul_tpu.compaction.service", "_LeaseHeartbeat"),
    ("lakesoul_tpu.vector.serving", "AnnEndpoint"),
)

_RING_MODULE = "lakesoul_tpu.data.jax_iter"
_RING_CLASS = "_BufferRing"
_POISON = 0xAB


@dataclass
class Violation:
    kind: str  # "shared-state-write" | "ring-use-after-release"
    message: str
    stacks: tuple[str, ...] = ()

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for s in self.stacks:
            out.append(s.rstrip())
        return "\n".join(out)


class _FieldState:
    """Eraser per-field state: owner thread(s) + candidate lockset."""

    __slots__ = ("owners", "lockset", "reported")

    def __init__(self):
        self.owners: dict[int, str] = {}  # thread id -> first-write stack
        self.lockset: "set | None" = None  # None until the field is shared
        self.reported = False


class _State:
    def __init__(self):
        self.lock = lockgraph.real_lock()
        self.enabled = False
        # WeakKeyDictionary keeps dead objects from pinning state AND from
        # donating their recycled id() to a fresh object (the lockgraph
        # serial lesson)
        self.fields: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.violations: list[Violation] = []
        self.patched: list[tuple] = []  # (cls, attr, original)


_STATE = _State()

# per-thread identity that is NEVER recycled: threading.get_ident() values
# are reused after a join, which would conflate two sequential short-lived
# pump threads into one "owner" and silently pass a real race — a
# thread-local serial dies with its thread and the next thread draws fresh
_THREAD_TLS = threading.local()
_THREAD_SERIALS = itertools.count(1)


def _thread_token() -> int:
    token = getattr(_THREAD_TLS, "token", None)
    if token is None:
        token = _THREAD_TLS.token = next(_THREAD_SERIALS)
    return token


def _stack_summary() -> str:
    frames = traceback.extract_stack()[:-3]
    keep = [
        f"  {fr.filename}:{fr.lineno} in {fr.name}"
        for fr in frames[-8:]
        if "lakesoul_tpu/analysis/racecheck" not in fr.filename.replace("\\", "/")
    ]
    return "\n".join(keep)


def _held_locks() -> frozenset:
    return frozenset(
        (l.serial, l.name) for l in lockgraph.current_held()
    )


def _record_write(label: str, obj, name: str) -> None:
    tid = _thread_token()
    held = _held_locks()
    with _STATE.lock:
        if not _STATE.enabled:
            return
        try:
            per_obj = _STATE.fields.setdefault(obj, {})
        except TypeError:
            return  # unhashable/unweakrefable instance: skip, don't break it
        st = per_obj.get(name)
        if st is None:
            st = per_obj[name] = _FieldState()
        first_of_thread = tid not in st.owners
        if first_of_thread:
            st.owners[tid] = _stack_summary() if len(st.owners) < 8 else ""
        if len(st.owners) == 1:
            return  # exclusive (init phase): no lock discipline required yet
        # shared: Eraser lockset refinement, initialized at the first write
        # that makes the field shared (the exclusive phase set no constraint)
        if st.lockset is None:
            st.lockset = set(held)
        else:
            st.lockset &= held
        if not st.lockset and not st.reported:
            st.reported = True
            other = next(
                (s for t, s in st.owners.items() if t != tid and s), ""
            )
            stacks = []
            if other:
                stacks.append(f"first writer:\n{other}")
            stacks.append(f"racing writer (thread {tid}):\n{_stack_summary()}")
            _STATE.violations.append(Violation(
                "shared-state-write",
                f"{label}.{name} written by {len(st.owners)} threads with no "
                "common lock — interleaved updates can tear/corrupt it",
                tuple(stacks),
            ))


def _checked_setattr(orig, label: str):
    def __setattr__(self, name, value):
        if _STATE.enabled:
            _record_write(label, self, name)
        orig(self, name, value)

    __setattr__._racecheck_orig = orig
    return __setattr__


# ------------------------------------------------------------- ring canary


def _checked_next_slot(orig):
    def next_slot(self):
        slot = orig(self)
        if _STATE.enabled:
            _canary_check(slot)
        return slot

    next_slot._racecheck_orig = orig
    return next_slot


def _canary_check(slot: dict) -> None:
    for name in list(slot.keys()):
        # a slot buffer about to be overwritten must be referenced by the
        # slot dict alone: dict entry + getrefcount's argument = 2.  More
        # means a borrower still holds the previous window's batch.
        if sys.getrefcount(slot[name]) > 2:
            with _STATE.lock:
                if _STATE.enabled:
                    _STATE.violations.append(Violation(
                        "ring-use-after-release",
                        f"collate ring slot buffer {name!r} is being reused "
                        "while a borrowed view is still live — the consumer "
                        "holds more batches than the ring covers "
                        "(LAKESOUL_COLLATE_REUSE contract: copy out before "
                        "the ring wraps)",
                        (_stack_summary(),),
                    ))
        arr = slot[name]
        try:
            arr.view("uint8")[...] = _POISON  # poison: stale reads go loud
        except (TypeError, ValueError, AttributeError):
            pass  # non-contiguous/odd dtype: detection still stands


# ----------------------------------------------------------------- control


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def violations() -> list[Violation]:
    with _STATE.lock:
        return list(_STATE.violations)


def reset() -> None:
    """Drop per-field state and recorded violations (instrumentation
    stays) — call between independent scenarios."""
    with _STATE.lock:
        _STATE.fields = weakref.WeakKeyDictionary()
        _STATE.violations.clear()


def instrument_class(cls) -> None:
    """Wrap ``cls.__setattr__`` with the Eraser write hook.  Idempotent;
    public so tests can instrument fixture classes."""
    current = cls.__dict__.get("__setattr__", cls.__setattr__)
    if hasattr(current, "_racecheck_orig"):
        return
    had_own = "__setattr__" in cls.__dict__
    cls.__setattr__ = _checked_setattr(current, cls.__name__)
    _STATE.patched.append((cls, "__setattr__", current if had_own else None))


def _instrument_hot_classes() -> None:
    import importlib

    for modname, clsname in HOT_CLASSES:
        mod = importlib.import_module(modname)
        cls = getattr(mod, clsname, None)
        if cls is not None:
            instrument_class(cls)
    ring_mod = importlib.import_module(_RING_MODULE)
    ring = getattr(ring_mod, _RING_CLASS, None)
    if ring is not None and not hasattr(ring.next_slot, "_racecheck_orig"):
        orig = ring.next_slot
        ring.next_slot = _checked_next_slot(orig)
        _STATE.patched.append((ring, "next_slot", orig))


def enable() -> None:
    """Instrument the hot classes + share the checked-lock machinery.
    Idempotent."""
    if _STATE.enabled:
        return
    lockgraph.instrument_locks()
    _instrument_hot_classes()
    _STATE.enabled = True


def disable() -> None:
    """Restore the instrumented classes and release the lock patch.
    Recording stops; instances keep working."""
    if not _STATE.enabled:
        return
    for cls, attr, orig in reversed(_STATE.patched):
        if orig is None:
            try:
                delattr(cls, attr)
            except AttributeError:
                pass
        else:
            setattr(cls, attr, orig)
    _STATE.patched.clear()
    lockgraph.uninstrument_locks()
    _STATE.enabled = False


class Watch:
    """Handle yielded by :func:`watch`: violations recorded since entry."""

    def __init__(self, mark: int):
        self._mark = mark

    @property
    def violations(self) -> list[Violation]:
        return violations()[self._mark :]


class watch:
    """``with watch() as w:`` — enable for the block, inspect
    ``w.violations`` after (state is NOT reset on exit so nested watches
    compose; call :func:`reset` between independent scenarios)."""

    def __enter__(self) -> Watch:
        self._was_enabled = _STATE.enabled
        enable()
        return Watch(len(violations()))

    def __exit__(self, *exc):
        if not self._was_enabled:
            disable()
        return False
