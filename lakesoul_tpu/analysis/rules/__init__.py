"""lakelint rule catalog.

Every rule encodes one invariant this codebase has already been burned by
(or will be at production scale).  The catalog, with rationale, lives in
ARCHITECTURE.md §Analysis; adding a rule = subclass
:class:`~lakesoul_tpu.analysis.engine.Rule` in a module here and list it in
:func:`all_rules`.
"""

from __future__ import annotations

from lakesoul_tpu.analysis.engine import Rule

from lakesoul_tpu.analysis.rules.concurrency import (
    LockHeldCallRule,
    RawThreadRule,
    SqliteScopeRule,
)
from lakesoul_tpu.analysis.rules.conventions import (
    MetricNameRule,
    UndocumentedEnvRule,
)
from lakesoul_tpu.analysis.rules.determinism import StageNondeterminismRule
from lakesoul_tpu.analysis.rules.resources import UnclosedReaderRule

__all__ = ["all_rules"]


def all_rules() -> list[Rule]:
    return [
        RawThreadRule(),
        LockHeldCallRule(),
        StageNondeterminismRule(),
        UnclosedReaderRule(),
        UndocumentedEnvRule(),
        MetricNameRule(),
        SqliteScopeRule(),
    ]
