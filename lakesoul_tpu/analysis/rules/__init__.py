"""lakelint rule catalog.

Every rule encodes one invariant this codebase has already been burned by
(or will be at production scale).  The catalog, with rationale, lives in
ARCHITECTURE.md §Analysis; adding a rule = subclass
:class:`~lakesoul_tpu.analysis.engine.Rule` in a module here and list it in
:func:`all_rules`.

Two generations: the PR 3 per-function rules (``check(module)`` over one
file's shared AST) and the interprocedural rules (``finalize(project)``
over the shared project call graph — ``Project.callgraph()``).  On top of
those ride the themed packs — device (jit/pallas trace safety),
concurrency (thread-root locksets + buffer lifetimes), durability (atomic
publication), isolation (READ COMMITTED portability), and boundedness
(resource budgets + thread/child/scratch lifecycles) — 40 rules total.
"""

from __future__ import annotations

from lakesoul_tpu.analysis.engine import Rule

from lakesoul_tpu.analysis.rules.concurrency import (
    LockHeldCallRule,
    RawThreadRule,
    SqliteScopeRule,
    TransitiveLockHeldCallRule,
)
from lakesoul_tpu.analysis.rules.conventions import (
    MetricNameRule,
    UndocumentedEnvRule,
)
from lakesoul_tpu.analysis.rules.determinism import StageNondeterminismRule
from lakesoul_tpu.analysis.rules.durability import (
    BarrierOrderRule,
    TornPublishRule,
    UnfsyncedRenameRule,
)
from lakesoul_tpu.analysis.rules.boundedness import (
    ChildReapRule,
    ShmDebrisRule,
    ThreadLifecycleRule,
    UnboundedGrowthRule,
    UnboundedQueueRule,
)
from lakesoul_tpu.analysis.rules.endpoint import HardcodedEndpointRule
from lakesoul_tpu.analysis.rules.identity import FleetIdentityLabelRule
from lakesoul_tpu.analysis.rules.isolation import (
    CasGuardRule,
    ReadModifyWriteRule,
    SqliteIsmRule,
    TxnBoundaryRule,
)
from lakesoul_tpu.analysis.rules.lifetime import (
    RingAliasingRule,
    ViewEscapesReleaseRule,
)
from lakesoul_tpu.analysis.rules.loops import UnstoppableLoopRule
from lakesoul_tpu.analysis.rules.perf import HotPathMaterializeRule
from lakesoul_tpu.analysis.rules.process import RawProcessRule
from lakesoul_tpu.analysis.rules.races import (
    RacyCheckThenActRule,
    SharedStateRaceRule,
)
from lakesoul_tpu.analysis.rules.replay import ReplayHostRoundtripRule
from lakesoul_tpu.analysis.rules.jaxtpu import (
    JitStaticArgShapeRule,
    PallasBlockSpecRule,
    TpuDtypeWidthRule,
    TraceHostSyncRule,
    TraceImpureCallRule,
)
from lakesoul_tpu.analysis.rules.resources import (
    InterproceduralUnclosedReaderRule,
    UnclosedReaderRule,
)
from lakesoul_tpu.analysis.rules.robustness import AdHocRetryRule
from lakesoul_tpu.analysis.rules.security import (
    RbacGateReachabilityRule,
    TaintPathSegmentsRule,
)
from lakesoul_tpu.analysis.rules.wallclock import WallClockLeaseRule

__all__ = ["all_rules", "rule_ids"]


def all_rules() -> list[Rule]:
    return [
        # per-function (PR 3)
        RawThreadRule(),
        LockHeldCallRule(),
        StageNondeterminismRule(),
        UnclosedReaderRule(),
        UndocumentedEnvRule(),
        MetricNameRule(),
        SqliteScopeRule(),
        AdHocRetryRule(),
        WallClockLeaseRule(),
        HotPathMaterializeRule(),
        RawProcessRule(),
        UnstoppableLoopRule(),
        ReplayHostRoundtripRule(),
        FleetIdentityLabelRule(),
        HardcodedEndpointRule(),
        # interprocedural (call graph + dataflow)
        RbacGateReachabilityRule(),
        TaintPathSegmentsRule(),
        TransitiveLockHeldCallRule(),
        InterproceduralUnclosedReaderRule(),
        # concurrency-soundness pack (thread roots + locksets + lifetimes)
        SharedStateRaceRule(),
        RacyCheckThenActRule(),
        ViewEscapesReleaseRule(),
        RingAliasingRule(),
        # device pack (jit/pallas trace safety)
        TraceImpureCallRule(),
        TraceHostSyncRule(),
        TpuDtypeWidthRule(),
        JitStaticArgShapeRule(),
        PallasBlockSpecRule(),
        # durability pack (atomic-publication discipline)
        TornPublishRule(),
        UnfsyncedRenameRule(),
        BarrierOrderRule(),
        # isolation pack (READ COMMITTED portability of the metadata path)
        CasGuardRule(),
        ReadModifyWriteRule(),
        TxnBoundaryRule(),
        SqliteIsmRule(),
        # boundedness pack (resource budgets + lifecycles for soak runs)
        UnboundedQueueRule(),
        UnboundedGrowthRule(),
        ThreadLifecycleRule(),
        ChildReapRule(),
        ShmDebrisRule(),
    ]


def rule_ids() -> list[str]:
    return [r.id for r in all_rules()]
