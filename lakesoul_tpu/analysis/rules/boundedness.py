"""Resource-boundedness & lifecycle rules — what a soak run dies of.

Every prior pack proved a *safety* property (locks, traces, races,
durability, isolation).  None proved the property an hours-long mixed
workload needs: **bounded memory and clean resource lifecycles**.
Sustained-throughput pipelines die of unbounded queues and leaked handles,
not crashes (arxiv 2604.21275) — slowly, in production, where pytest
never runs long enough to notice.  Five rules make the discipline
mechanical, riding the cached thread-root and call-graph indexes
(:mod:`~lakesoul_tpu.analysis.threadroots`) plus one per-class lifecycle
index built once per run:

- ``unbounded-queue``: ``Queue()``/``deque()`` constructed without
  ``maxsize``/``maxlen`` in the data-path, serving, scanplane, fleet, and
  freshness modules.  Backpressure must be structural — an unbounded
  buffer between a fast producer and a slow consumer is RAM with a fuse.
- ``unbounded-growth``: append/add/setitem on a ``self.`` container
  inside a background-thread-reachable service loop with no eviction,
  clear, or rebind path anywhere in the class — the slow-leak shape that
  kills soaks.
- ``thread-lifecycle``: every started ``Thread`` must have a reachable
  ``join`` or stop-event wiring (an ``Event`` the class both constructs
  and ``.set()``s).  A thread nobody can stop outlives its owner and
  races teardown; sanctioned daemon publishers carry pragmas.
- ``child-reap``: every ``Popen`` in scanplane/fleet/compaction must
  reach ``wait``/``poll``/``kill`` on all exits — try/finally or a
  registered reaper — so the autoscaler can never orphan (or zombie) a
  worker.  A terminated-but-never-waited child is a zombie until *its
  parent* exits.
- ``shm-debris``: paths created under /dev/shm, the spool, or via
  ``mkdtemp``/``mkstemp`` must flow into a registered prune/unlink seam
  (``rmtree``/``unlink``/``atexit.register``/``sweep``/``prune``) — a
  SIGKILLed owner must not leave tmpfs debris nobody sweeps.

Known limits, on purpose (low false positives over completeness): join
detection is name-based over the module (a ``.join()`` on an attribute of
the right name anywhere satisfies the thread site — false-negative
leaning); growth is only flagged inside a lexical ``while`` loop reachable
from a background root (a handler that grows per request is the runtime
leakcheck's job); and cleanup seams are matched lexically in the creating
function or its class.  The runtime half of this pack
(:mod:`~lakesoul_tpu.analysis.leakcheck`) catches what the lexical
approximations miss, with creation stacks.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Project,
    Rule,
    dotted_name,
    enclosing_function_bodies,
    walk_stopping_at_functions,
)
from lakesoul_tpu.analysis.threadroots import MAIN_ROOT, thread_roots

# the package scope the repo gate runs with; fixtures override
SCOPE = ("lakesoul_tpu/",)

# modules where queue boundedness is load-bearing (data path, serving,
# process planes, freshness) — a bounded queue elsewhere is still good
# style, but these are where an unbounded one takes the soak down
QUEUE_SCOPE = (
    "runtime/", "service/", "vector/", "scanplane/", "fleet/", "freshness/",
)

# Popen supervision scope: the layers allowed to spawn (rules/process.py)
# minus runtime/ (its parallelism is threads, not children)
CHILD_SCOPE = ("scanplane/", "fleet/", "compaction/")

_QUEUE_CTOR_TERMINALS = {"Queue", "LifoQueue", "PriorityQueue"}
_GROW_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add"}
_SHRINK_MUTATORS = {
    "pop", "popleft", "popitem", "clear", "remove", "discard",
}
_CONTAINER_CTOR_TERMINALS = {
    "list", "dict", "set", "deque", "OrderedDict", "defaultdict", "Counter",
}
_CLEANUP_TERMINALS = {
    "rmtree", "unlink", "remove", "removedirs", "rmdir", "cleanup",
    "register", "sweep_tmp_debris", "sweep", "prune", "prune_stale_spools",
}
_TMPFILE_CTOR_TERMINALS = {"mkdtemp", "mkstemp"}
_DEBRIS_TERMINALS = _TMPFILE_CTOR_TERMINALS | {"mkdir", "makedirs"}


def _terminal(func: ast.expr) -> str:
    return (dotted_name(func) or "").rsplit(".", 1)[-1]


def _self_attr(node: ast.AST) -> "str | None":
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _in_scope(relpath: str, scope: tuple) -> bool:
    return any(s in relpath for s in scope)


# ----------------------------------------------------------- unbounded-queue


def _queue_bound(call: ast.Call, terminal: str) -> bool:
    """Whether the queue/deque construction carries a structural bound."""
    if terminal == "deque":
        if len(call.args) >= 2:
            return not (
                isinstance(call.args[1], ast.Constant)
                and call.args[1].value in (None, 0)
            )
        for kw in call.keywords:
            if kw.arg == "maxlen":
                return not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value in (None, 0)
                )
        return False
    # queue.Queue family: first positional / maxsize kw; <=0 means infinite
    cap = call.args[0] if call.args else None
    if cap is None:
        for kw in call.keywords:
            if kw.arg == "maxsize":
                cap = kw.value
    if cap is None:
        return False
    if isinstance(cap, ast.Constant):
        return isinstance(cap.value, (int, float)) and cap.value > 0
    return True  # a computed capacity is a bound the author chose


class UnboundedQueueRule(Rule):
    id = "unbounded-queue"
    title = "Queue()/deque() without maxsize/maxlen in a bounded-path module"

    def __init__(self, scope: tuple = QUEUE_SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not _in_scope(module.relpath, self.scope):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            terminal = _terminal(node.func)
            if terminal == "SimpleQueue":
                yield Finding(
                    self.id, module.relpath, node.lineno,
                    "SimpleQueue() cannot be bounded — a fast producer "
                    "grows it until the process dies; use Queue(maxsize=N) "
                    "so backpressure is structural",
                )
                continue
            if terminal not in _QUEUE_CTOR_TERMINALS and terminal != "deque":
                continue
            if _queue_bound(node, terminal):
                continue
            what = "deque() without maxlen" if terminal == "deque" else \
                f"{terminal}() without maxsize"
            yield Finding(
                self.id, module.relpath, node.lineno,
                f"{what} on the data path — an unbounded buffer between a "
                "fast producer and a slow consumer grows until the soak "
                "dies of RSS; pass a capacity (or pragma naming the "
                "structural bound)",
            )


# ------------------------------------------------------- per-class lifecycle
# One walk over every in-scope class collects everything the three
# cross-file rules need: container growth/shrink sites, thread creations
# and join/stop wiring, child spawns and reap wiring.  Built once per
# (project, scope) and cached on the Project, the same contract as the
# race/durability/isolation indexes.


@dataclass(frozen=True)
class _Growth:
    method: str  # qname
    terminal: str  # method name as written
    attr: str
    line: int
    in_while: bool


@dataclass(frozen=True)
class _ThreadSite:
    method: str
    terminal: str
    line: int
    binding: str  # "anonymous" | "local:<name>" | "attr:<name>"


@dataclass(frozen=True)
class _ChildSite:
    method: str
    terminal: str
    line: int
    binding: str  # "local:<name>" | "attr:<name>" | "anonymous"


@dataclass
class _ClassInfo:
    qname: str
    relpath: str
    name: str
    container_attrs: set = field(default_factory=set)  # unbounded ctors
    bounded_attrs: set = field(default_factory=set)  # deque(maxlen=N) etc.
    growth: list = field(default_factory=list)  # [_Growth]
    shrink_attrs: set = field(default_factory=set)  # evicted/cleared/rebound
    threads: list = field(default_factory=list)  # [_ThreadSite]
    children: list = field(default_factory=list)  # [_ChildSite]
    event_attrs: set = field(default_factory=set)  # threading.Event() attrs
    set_attrs: set = field(default_factory=set)  # self.<a>.set() called
    reaped_attrs: set = field(default_factory=set)  # wait/poll/kill reaches
    child_attrs: set = field(default_factory=set)  # Popen registries
    zombies: list = field(default_factory=list)  # [(method, terminal, line)]


@dataclass
class _BoundedIndex:
    classes: dict = field(default_factory=dict)  # class qname -> _ClassInfo
    # relpath -> attr names something .join()s on (any receiver — module-
    # wide so a handle stored on a server object still counts)
    joined_attrs: dict = field(default_factory=dict)
    # function qname -> thread/child sites defined OUTSIDE classes
    free_threads: list = field(default_factory=list)
    free_children: list = field(default_factory=list)


_REAP_TERMINALS = {"wait", "poll", "kill"}


def _iter_alias(expr: ast.expr) -> "tuple[set, set]":
    """(self attrs, local names) referenced anywhere in an iterable
    expression — ``list(self._threads)`` aliases to ``_threads``."""
    attrs: set = set()
    names: set = set()
    for sub in ast.walk(expr):
        a = _self_attr(sub)
        if a is not None:
            attrs.add(a)
        elif isinstance(sub, ast.Name):
            names.add(sub.id)
    return attrs, names


class _FnScan:
    """One pass over a function body collecting lifecycle facts."""

    def __init__(self):
        self.thread_locals: dict = {}  # name -> creation line
        self.child_locals: dict = {}
        self.popped_children: dict = {}  # name -> source attr
        self.joined_locals: set = set()
        self.joined_attrs: set = set()
        self.reaped_locals: set = set()
        self.terminated_locals: dict = {}  # name -> line
        self.registered_locals: dict = {}  # name -> attr appended into
        self.assign_alias: dict = {}  # local -> (attrs, names) it was built from
        self.for_vars: dict = {}  # loop var -> (attrs, names) iterated

    def resolve_to_attrs(self, name: str, depth: int = 3) -> set:
        """Self-attrs a local name transitively aliases (one or two hops:
        ``threads = list(self._threads); for t in threads: ...``)."""
        out: set = set()
        seen: set = set()
        frontier = {name}
        for _ in range(depth):
            nxt: set = set()
            for n in frontier:
                if n in seen:
                    continue
                seen.add(n)
                for src in (self.assign_alias, self.for_vars):
                    hit = src.get(n)
                    if hit is not None:
                        out |= hit[0]
                        nxt |= hit[1]
            frontier = nxt
        return out


def _scan_function(fn_node, scan: _FnScan, cls: "_ClassInfo | None",
                   qname: str, terminal_name: str) -> None:
    """Collect thread/child/join/reap facts from one function body."""

    def visit(node: ast.AST, in_while: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.While):
            for child in ast.iter_child_nodes(node):
                visit(child, True)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.target, ast.Name):
                scan.for_vars[node.target.id] = _iter_alias(node.iter)
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # `[p for p in self._children if p.poll() ...]` — the reap-by-
            # comprehension idiom aliases exactly like a for statement
            for gen in node.generators:
                if isinstance(gen.target, ast.Name):
                    scan.for_vars[gen.target.id] = _iter_alias(gen.iter)
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)
            return
        if isinstance(node, ast.Assign):
            value = node.value
            term = _terminal(value.func) if isinstance(value, ast.Call) else ""
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if isinstance(tgt, ast.Name):
                    if term == "Thread":
                        scan.thread_locals[tgt.id] = value.lineno
                    elif term == "Popen":
                        scan.child_locals[tgt.id] = value.lineno
                    elif (isinstance(value, ast.Call)
                          and isinstance(value.func, ast.Attribute)
                          and value.func.attr in ("pop", "popleft")):
                        src = _self_attr(value.func.value)
                        if src is not None:
                            scan.popped_children[tgt.id] = src
                    elif isinstance(value, (ast.Call, ast.Name, ast.Attribute,
                                            ast.ListComp, ast.List)):
                        scan.assign_alias[tgt.id] = _iter_alias(value)
                elif attr is not None and cls is not None:
                    if term == "Thread":
                        cls.threads.append(_ThreadSite(
                            qname, terminal_name, value.lineno, f"attr:{attr}",
                        ))
                    elif term == "Popen":
                        cls.children.append(_ChildSite(
                            qname, terminal_name, value.lineno, f"attr:{attr}",
                        ))
                        cls.child_attrs.add(attr)
                    elif term == "Event":
                        cls.event_attrs.add(attr)
                    elif terminal_name != "__init__":
                        # non-init rebind of a container attr is a reset path
                        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                                              ast.ListComp, ast.DictComp,
                                              ast.SetComp, ast.Subscript,
                                              ast.Call)):
                            cls.shrink_attrs.add(attr)
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                attr = _self_attr(base)
                if attr is not None and cls is not None:
                    cls.shrink_attrs.add(attr)
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            m = node.func.attr
            recv_attr = _self_attr(node.func.value)
            recv_name = (node.func.value.id
                         if isinstance(node.func.value, ast.Name) else None)
            if m == "join" and not isinstance(node.func.value, ast.Constant):
                # thread-handle join (str-constant receivers are str.join)
                if isinstance(node.func.value, ast.Attribute):
                    scan.joined_attrs.add(node.func.value.attr)
                elif recv_name is not None:
                    scan.joined_locals.add(recv_name)
            elif m in _REAP_TERMINALS:
                if recv_attr is not None:
                    if cls is not None:
                        cls.reaped_attrs.add(recv_attr)
                elif recv_name is not None:
                    scan.reaped_locals.add(recv_name)
            elif m == "terminate" and recv_name is not None:
                scan.terminated_locals.setdefault(recv_name, node.lineno)
            elif m == "set" and recv_attr is not None and cls is not None:
                cls.set_attrs.add(recv_attr)
            elif m in ("append", "add") and node.args:
                # registering a handle into a self container
                tgt_attr = _self_attr(node.func.value)
                if tgt_attr is not None and isinstance(node.args[0], ast.Name):
                    scan.registered_locals[node.args[0].id] = tgt_attr
            if cls is not None:
                if m in _GROW_MUTATORS and recv_attr is not None:
                    cls.growth.append(_Growth(
                        qname, terminal_name, recv_attr, node.lineno, in_while,
                    ))
                elif m in _SHRINK_MUTATORS and recv_attr is not None:
                    cls.shrink_attrs.add(recv_attr)
                elif m == "setdefault" and recv_attr is not None:
                    cls.growth.append(_Growth(
                        qname, terminal_name, recv_attr, node.lineno, in_while,
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)
            return
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store,)
        ):
            attr = _self_attr(node.value)
            if attr is not None and cls is not None:
                cls.growth.append(_Growth(
                    qname, terminal_name, attr, node.lineno, in_while,
                ))
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, in_while)

    for stmt in fn_node.body:
        visit(stmt, False)


def _anonymous_sites(fn_node) -> "list[tuple[str, int]]":
    """Thread(...)/Popen(...) whose result is consumed without a binding —
    ``Thread(...).start()`` or a bare expression: no handle, no lifecycle."""
    out = []
    for node in walk_stopping_at_functions(fn_node.body):
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(node.func)
        if term in ("Thread", "Popen"):
            continue  # bindings handled by _scan_function
        # a Thread(...) used as a receiver (Thread(...).start()) or passed
        # bare shows up as the .value of an Attribute / an Expr statement
        if isinstance(node.func, ast.Attribute) and isinstance(
            node.func.value, ast.Call
        ):
            inner = _terminal(node.func.value.func)
            if inner in ("Thread", "Popen"):
                out.append((inner, node.func.value.lineno))
    for stmt in walk_stopping_at_functions(fn_node.body):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            if _terminal(stmt.value.func) in ("Thread", "Popen"):
                out.append((_terminal(stmt.value.func), stmt.value.lineno))
    return out


def _class_container_attrs(graph, cls_info) -> "tuple[set, set]":
    """(unbounded container attrs, bounded container attrs) over every
    method's ``self.<attr> = <container ctor>`` assignment."""
    unbounded: set = set()
    bounded: set = set()
    for mq in cls_info.methods.values():
        fn = graph.functions[mq]
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            is_ctor = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp))
            is_bounded = False
            if isinstance(value, ast.Call):
                term = _terminal(value.func)
                if term in _CONTAINER_CTOR_TERMINALS:
                    is_ctor = True
                    if term == "deque" and _queue_bound(value, "deque"):
                        is_bounded = True
            if not is_ctor:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                (bounded if is_bounded else unbounded).add(attr)
    return unbounded, bounded


def _build_index(project: Project, scope: tuple) -> _BoundedIndex:
    graph = project.callgraph()
    idx = _BoundedIndex()

    # module-wide joined attrs + free-function thread/child sites
    per_fn_scans: dict = {}
    for fq, fn in graph.functions.items():
        if not _in_scope(fn.relpath, scope):
            continue
        cls = None
        if fn.class_qname is not None:
            cls = idx.classes.get(fn.class_qname)
            if cls is None:
                cinfo = graph.classes.get(fn.class_qname)
                if cinfo is None:
                    continue
                cls = _ClassInfo(fn.class_qname, cinfo.relpath, cinfo.name)
                ub, b = _class_container_attrs(graph, cinfo)
                cls.container_attrs = ub
                cls.bounded_attrs = b
                idx.classes[fn.class_qname] = cls
        scan = _FnScan()
        terminal = fn.name.rsplit(".", 1)[-1]
        _scan_function(fn.node, scan, cls, fq, terminal)
        per_fn_scans[fq] = scan
        mod_joined = idx.joined_attrs.setdefault(fn.relpath, set())
        mod_joined |= scan.joined_attrs
        # joins on bare names count too (a shutdown closure joining the
        # handle it closed over), and for-vars / aliases resolve back to
        # the attrs they iterate — name-based, the documented limit
        mod_joined |= scan.joined_locals
        for name in scan.joined_locals:
            mod_joined |= scan.resolve_to_attrs(name)
        for name in scan.reaped_locals:
            attrs = scan.resolve_to_attrs(name)
            attrs |= {scan.popped_children[name]} \
                if name in scan.popped_children else set()
            if cls is not None:
                cls.reaped_attrs |= attrs
        # local Thread()/Popen() handles
        for name, line in scan.thread_locals.items():
            reg = scan.registered_locals.get(name)
            binding = f"attr:{reg}" if reg is not None else f"local:{name}"
            site = _ThreadSite(fq, terminal, line, binding)
            joined_here = (
                name in scan.joined_locals
                or any(name in hit[1]
                       for hit in scan.for_vars.values())
            )
            if joined_here:
                continue  # joined in the creating function: done
            (cls.threads if cls is not None else idx.free_threads).append(site)
        for name, line in scan.child_locals.items():
            reg = scan.registered_locals.get(name)
            binding = f"attr:{reg}" if reg is not None else f"local:{name}"
            site = _ChildSite(fq, terminal, line, binding)
            if name in scan.reaped_locals:
                continue
            (cls.children if cls is not None else idx.free_children).append(site)
        # zombie shape: popped child terminated but never waited in-method
        for name, line in scan.terminated_locals.items():
            if name not in scan.popped_children:
                continue
            if name in scan.reaped_locals:
                continue
            if name in scan.registered_locals:
                continue  # handed to another registry — its reaper's job
            if cls is not None:
                cls.zombies.append((fq, terminal, line, name,
                                    scan.popped_children[name]))
        # anonymous Thread(...).start() / bare Popen(...)
        for kind, line in _anonymous_sites(fn.node):
            site_cls = cls
            if kind == "Thread":
                t = _ThreadSite(fq, terminal, line, "anonymous")
                (site_cls.threads if site_cls is not None
                 else idx.free_threads).append(t)
            else:
                c = _ChildSite(fq, terminal, line, "anonymous")
                (site_cls.children if site_cls is not None
                 else idx.free_children).append(c)
    return idx


def _bounded_index(project: Project, scope: tuple) -> _BoundedIndex:
    cache = project._boundedness_index
    if cache is None:
        cache = project._boundedness_index = {}
    hit = cache.get(scope)
    if hit is None:
        hit = cache[scope] = _build_index(project, scope)
    return hit


# ---------------------------------------------------------- unbounded-growth


class UnboundedGrowthRule(Rule):
    id = "unbounded-growth"
    title = "self-container grows in a background service loop with no eviction"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        idx = _bounded_index(project, self.scope)
        roots = thread_roots(project)
        for cls in idx.classes.values():
            seen: set = set()
            for g in cls.growth:
                if not g.in_while:
                    continue
                if g.attr not in cls.container_attrs:
                    continue  # bounded deque or not a builtin container
                if g.attr in cls.shrink_attrs or g.attr in cls.bounded_attrs:
                    continue
                rts = roots.roots_of(g.method)
                background = [r for r in rts if r != MAIN_ROOT]
                if not background:
                    continue
                key = (g.attr, g.line)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    self.id, cls.relpath, g.line,
                    f"self.{g.attr} of {cls.name} grows inside {g.terminal}'s "
                    "service loop (reachable from "
                    f"{', '.join(sorted(roots.render(r) for r in background))}) "
                    "and nothing in the class ever evicts, clears, or "
                    "rebinds it — the slow leak that kills a soak; bound it "
                    "(deque(maxlen=...)), add an eviction path, or pragma "
                    "the structural budget",
                )


# --------------------------------------------------------- thread-lifecycle


class ThreadLifecycleRule(Rule):
    id = "thread-lifecycle"
    title = "started Thread with no reachable join or stop-event wiring"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        idx = _bounded_index(project, self.scope)
        for cls in idx.classes.values():
            stop_wired = bool(cls.event_attrs & cls.set_attrs)
            mod_joined = idx.joined_attrs.get(cls.relpath, set())
            for t in cls.threads:
                yield from self._judge(t, cls.relpath, mod_joined, stop_wired)
        for t in idx.free_threads:
            relpath = t.method.split("::", 1)[0]
            mod_joined = idx.joined_attrs.get(relpath, set())
            yield from self._judge(t, relpath, mod_joined, False)

    def _judge(self, t: _ThreadSite, relpath: str, mod_joined: set,
               stop_wired: bool) -> Iterable[Finding]:
        if t.binding == "anonymous":
            yield Finding(
                self.id, relpath, t.line,
                f"{t.terminal} starts a Thread without keeping the handle — "
                "nothing can ever join or stop it, so it outlives its owner "
                "and races teardown; keep the handle and join it on the "
                "shutdown path (or wire a stop event)",
            )
            return
        kind, _, name = t.binding.partition(":")
        if kind == "attr":
            if name in mod_joined or stop_wired:
                return
            yield Finding(
                self.id, relpath, t.line,
                f"thread handle self.{name} (started in {t.terminal}) is "
                "never joined and the class has no stop-event wiring — the "
                "shutdown path cannot prove the thread exited; join it (or "
                "construct an Event the stop path .set()s)",
            )
            return
        # local handle that escaped the creating function un-joined
        if name in mod_joined:
            return
        yield Finding(
            self.id, relpath, t.line,
            f"Thread bound to {name!r} in {t.terminal} is started but never "
            "joined on any path — store the handle where the shutdown path "
            "can join it, or wire a stop event",
        )


# --------------------------------------------------------------- child-reap


class ChildReapRule(Rule):
    id = "child-reap"
    title = "spawned child process with no wait/poll/kill on some exit path"

    def __init__(self, scope: tuple = CHILD_SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        idx = _bounded_index(project, self.scope)
        for cls in idx.classes.values():
            for c in cls.children:
                kind, _, name = c.binding.partition(":")
                if kind == "attr" and name in cls.reaped_attrs:
                    continue
                if c.binding == "anonymous":
                    msg = (
                        f"{c.terminal} spawns a child without keeping the "
                        "Popen handle — it can never be waited, killed, or "
                        "reaped; keep the handle in a registry a reaper "
                        "drains"
                    )
                else:
                    msg = (
                        f"child registry self.{name} (spawned in "
                        f"{c.terminal}) never reaches wait/poll/kill in "
                        f"{cls.name} — a crashed or SIGKILLed worker stays "
                        "a zombie and a live one is orphaned at shutdown; "
                        "add a reap path over the registry"
                    )
                yield Finding(self.id, cls.relpath, c.line, msg)
            for (mq, terminal, line, name, src) in cls.zombies:
                yield Finding(
                    self.id, cls.relpath, line,
                    f"{terminal} pops a child from self.{src} and "
                    f"terminates it, but {name!r} is never waited/polled "
                    "in that method and no longer lives in any reaped "
                    "registry — the exit makes a zombie that survives "
                    "until this process dies; wait it (with a kill "
                    "fallback) or hand it to a reaped retire list",
                )
        for c in idx.free_children:
            relpath = c.method.split("::", 1)[0]
            yield Finding(
                self.id, relpath, c.line,
                f"{c.terminal} spawns a child whose handle never reaches "
                "wait/poll/kill — try/finally the wait or register the "
                "child with a reaper",
            )


# --------------------------------------------------------------- shm-debris


class ShmDebrisRule(Rule):
    id = "shm-debris"
    title = "tmpfs/spool/tempdir creation with no registered prune seam"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not _in_scope(module.relpath, self.scope):
            return
        # cheap prefilter on the shared walk: most modules never touch a
        # tmpfile ctor, so skip the per-scope re-walks entirely
        if not any(
            isinstance(n, ast.Call) and _terminal(n.func) in _DEBRIS_TERMINALS
            for n in module.walk()
        ):
            return
        parents = None
        for scope_node, body in enclosing_function_bodies(module.tree):
            cleans: "bool | None" = None  # computed lazily per scope
            for node in walk_stopping_at_functions(body):
                if not isinstance(node, ast.Call):
                    continue
                terminal = _terminal(node.func)
                hit = None
                if terminal in _TMPFILE_CTOR_TERMINALS:
                    hit = f"{terminal}(...)"
                elif terminal in ("mkdir", "makedirs"):
                    if any(self._shm_str(a) for a in
                           list(node.args) + [kw.value for kw in node.keywords]):
                        hit = f"{terminal}(...) under /dev/shm"
                if hit is None:
                    continue
                if cleans is None:
                    if parents is None:
                        parents = module.parents()
                    cleans = self._scope_cleans(scope_node, module, parents)
                if cleans:
                    continue
                yield Finding(
                    self.id, module.relpath, node.lineno,
                    f"{hit} creates scratch state but neither this function "
                    "nor its class references a prune/unlink seam "
                    "(rmtree/unlink/atexit.register/sweep/prune) — a "
                    "SIGKILLed owner leaves tmpfs debris nobody sweeps; "
                    "register the path with a pruner that survives crashes",
                )

    def _scope_cleans(self, scope_node, module: Module, parents: dict) -> bool:
        """Cleanup referenced in the creating function (nested closures
        count — a teardown lambda registered from here still prunes) or in
        any lexically enclosing class (its stop/close path owns the dir)."""
        if scope_node is module.tree:
            # module-level creation: only sibling module-level code counts
            return any(
                isinstance(n, ast.Call) and _terminal(n.func) in _CLEANUP_TERMINALS
                for n in walk_stopping_at_functions(module.tree.body)
            )
        if self._has_cleanup(scope_node):
            return True
        node = scope_node
        while node in parents:
            node = parents[node]
            if isinstance(node, ast.ClassDef) and self._has_cleanup(node):
                return True
        return False

    @staticmethod
    def _shm_str(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if "/dev/shm" in sub.value:
                    return True
        return False

    @staticmethod
    def _has_cleanup(scope_node: ast.AST) -> bool:
        for node in ast.walk(scope_node):
            if isinstance(node, ast.Call):
                if _terminal(node.func) in _CLEANUP_TERMINALS:
                    return True
        return False
