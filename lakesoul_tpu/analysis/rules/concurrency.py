"""Concurrency-discipline rules.

The three invariants that keep the threaded data path deadlock- and
race-free:

- ``raw-thread``: all parallelism flows through ``runtime/pool.py`` so the
  host's thread budget stays one knob and ``in_worker()`` can break nested
  blocking submits.  A stray ``ThreadPoolExecutor`` reintroduces exactly the
  oversubscription + nested-pool deadlock class PR 2 removed.
- ``lock-held-call``: no blocking call (pool submit/result, join, wait,
  sleep, file open) while holding a lock — a worker parked on a lock that a
  blocked submitter holds is the canonical pool deadlock.
- ``sqlite-scope``: sqlite connections/cursors only inside ``meta/store.py``
  whose RLock serializes the shared ``:memory:`` connection (the
  "Cursor needed to be reset" race fixed in PR 2 stays fixed).
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

# the one module allowed to construct raw thread primitives
_POOL_MODULE = "runtime/pool.py"

_THREAD_CTORS = {
    "threading.Thread",
    "Thread",
    "concurrent.futures.ThreadPoolExecutor",
    "futures.ThreadPoolExecutor",
    "ThreadPoolExecutor",
}


class RawThreadRule(Rule):
    id = "raw-thread"
    title = "raw threading.Thread / ThreadPoolExecutor outside runtime/pool.py"

    def check(self, module: Module) -> Iterable[Finding]:
        if module.relpath.endswith(_POOL_MODULE):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _THREAD_CTORS:
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    f"{name}(...) bypasses the shared worker pool "
                    "(runtime/pool.py); use get_pool()/pipeline stages, or "
                    "justify with an inline pragma / baseline entry",
                )


# method names that block the calling thread; attribute calls only, so
# ubiquitous non-blocking names (dict.get, …) stay out
_BLOCKING_METHODS = {"submit", "result", "join", "wait", "sleep"}
_BLOCKING_FUNCS = {"open"}

# receivers whose .join is string/path assembly, never a blocking wait
_JOIN_SAFE_PREFIXES = ("os.path", "posixpath", "ntpath", "pathlib")


def _is_blocking_join(call: ast.Call, receiver: str | None) -> bool:
    """``.join`` is only a blocking wait on thread-like receivers:
    ``str.join``/``os.path.join`` always take positional arguments while
    ``Thread.join`` takes none (timeouts are keyword in this codebase), so
    a positional-arg join is string/path assembly unless the receiver name
    says otherwise."""
    if receiver and any(
        receiver == p or receiver.startswith(p + ".") for p in _JOIN_SAFE_PREFIXES
    ):
        return False
    if not call.args:
        return True
    terminal = (receiver or "").rsplit(".", 1)[-1].lower()
    return any(hint in terminal for hint in ("thread", "proc", "worker", "pump"))


def _is_lock_expr(node: ast.expr) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1]
    return "lock" in terminal.lower()


class LockHeldCallRule(Rule):
    id = "lock-held-call"
    title = "blocking call or pool.submit while holding a lock"

    def check(self, module: Module) -> Iterable[Finding]:
        for node in module.walk():
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_names = [
                dotted_name(item.context_expr)
                for item in node.items
                if _is_lock_expr(item.context_expr)
            ]
            if not lock_names:
                continue
            held = lock_names[0]
            for inner in walk_stopping_at_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                func = inner.func
                if isinstance(func, ast.Attribute) and func.attr in _BLOCKING_METHODS:
                    if isinstance(func.value, ast.Constant):
                        continue  # ", ".join(...) — a str method, not a thread
                    receiver = dotted_name(func.value)
                    if func.attr == "join" and not _is_blocking_join(inner, receiver):
                        continue
                    called = dotted_name(func) or func.attr
                elif isinstance(func, ast.Name) and func.id in _BLOCKING_FUNCS:
                    called = func.id
                else:
                    continue
                yield Finding(
                    self.id,
                    module.relpath,
                    inner.lineno,
                    f"{called}(...) can block while holding {held} — the "
                    "nested-pool deadlock class; move the blocking work "
                    "outside the critical section",
                )


_STORE_MODULE = "meta/store.py"
_SQLITE_MARKERS = {"sqlite3.connect", "sqlite3.Connection", "sqlite3.Cursor"}


class SqliteScopeRule(Rule):
    id = "sqlite-scope"
    title = "direct sqlite use outside the serialized meta/store.py path"

    def check(self, module: Module) -> Iterable[Finding]:
        if module.relpath.endswith(_STORE_MODULE):
            return
        for node in module.walk():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "sqlite3":
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            "import sqlite3 outside meta/store.py — all "
                            "sqlite access must go through the store's "
                            "RLock-serialized connection",
                        )
            elif isinstance(node, ast.ImportFrom) and node.module == "sqlite3":
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "from sqlite3 import … outside meta/store.py — all "
                    "sqlite access must go through the store's "
                    "RLock-serialized connection",
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in _SQLITE_MARKERS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "cursor"
                    and (dotted_name(node.func) or "").split(".")[-2:-1]
                    in (["conn"], ["connection"], ["db"], ["_conn"], ["_db"])
                ):
                    yield Finding(
                        self.id,
                        module.relpath,
                        node.lineno,
                        f"{name or 'cursor'}(...) outside meta/store.py — "
                        "the shared :memory: connection races without the "
                        "store's RLock (the 'Cursor needed to be reset' bug)",
                    )
