"""Concurrency-discipline rules.

The three invariants that keep the threaded data path deadlock- and
race-free:

- ``raw-thread``: all parallelism flows through ``runtime/pool.py`` so the
  host's thread budget stays one knob and ``in_worker()`` can break nested
  blocking submits.  A stray ``ThreadPoolExecutor`` reintroduces exactly the
  oversubscription + nested-pool deadlock class PR 2 removed.
- ``lock-held-call``: no blocking call (pool submit/result, join, wait,
  sleep, file open) while holding a lock — a worker parked on a lock that a
  blocked submitter holds is the canonical pool deadlock.
- ``sqlite-scope``: sqlite connections/cursors only inside ``meta/store.py``
  whose RLock serializes the shared ``:memory:`` connection (the
  "Cursor needed to be reset" race fixed in PR 2 stays fixed).
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

# the one module allowed to construct raw thread primitives
_POOL_MODULE = "runtime/pool.py"

_THREAD_CTORS = {
    "threading.Thread",
    "Thread",
    "concurrent.futures.ThreadPoolExecutor",
    "futures.ThreadPoolExecutor",
    "ThreadPoolExecutor",
}


class RawThreadRule(Rule):
    id = "raw-thread"
    title = "raw threading.Thread / ThreadPoolExecutor outside runtime/pool.py"

    def check(self, module: Module) -> Iterable[Finding]:
        if module.relpath.endswith(_POOL_MODULE):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _THREAD_CTORS:
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    f"{name}(...) bypasses the shared worker pool "
                    "(runtime/pool.py); use get_pool()/pipeline stages, or "
                    "justify with an inline pragma / baseline entry",
                )


# method names that block the calling thread; attribute calls only, so
# ubiquitous non-blocking names (dict.get, …) stay out
_BLOCKING_METHODS = {"submit", "result", "join", "wait", "sleep"}
_BLOCKING_FUNCS = {"open"}

# receivers whose .join is string/path assembly, never a blocking wait
_JOIN_SAFE_PREFIXES = ("os.path", "posixpath", "ntpath", "pathlib")


def _is_blocking_join(call: ast.Call, receiver: str | None) -> bool:
    """``.join`` is only a blocking wait on thread-like receivers:
    ``str.join``/``os.path.join`` always take positional arguments while
    ``Thread.join`` takes none (timeouts are keyword in this codebase), so
    a positional-arg join is string/path assembly unless the receiver name
    says otherwise."""
    if receiver and any(
        receiver == p or receiver.startswith(p + ".") for p in _JOIN_SAFE_PREFIXES
    ):
        return False
    if not call.args:
        return True
    terminal = (receiver or "").rsplit(".", 1)[-1].lower()
    return any(hint in terminal for hint in ("thread", "proc", "worker", "pump"))


def _is_lock_expr(node: ast.expr) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1]
    return "lock" in terminal.lower()


def blocking_call_name(call: ast.Call) -> str | None:
    """The dotted name of a direct blocking call, or None.  Shared by the
    lexical rule and the transitive (call-graph) rule so both agree on what
    "blocking" means — submit/result/join/wait/sleep attribute calls (with
    the join string/path disambiguation) plus bare ``open``."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _BLOCKING_METHODS:
        if isinstance(func.value, ast.Constant):
            return None  # ", ".join(...) — a str method, not a thread
        receiver = dotted_name(func.value)
        if func.attr == "join" and not _is_blocking_join(call, receiver):
            return None
        return dotted_name(func) or func.attr
    if isinstance(func, ast.Name) and func.id in _BLOCKING_FUNCS:
        return func.id
    return None


def _iter_lock_bodies_from(nodes):
    """``(with_node, held_lock_name)`` for the ``with <lock>:`` blocks in
    ``nodes`` — the ONE definition of "a held lock" shared by the lexical
    and transitive rules (divergence here would make them disagree about
    what counts as a critical section)."""
    for node in nodes:
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_names = [
            dotted_name(item.context_expr)
            for item in node.items
            if _is_lock_expr(item.context_expr)
        ]
        if lock_names:
            yield node, lock_names[0]


def iter_lock_bodies(module: Module):
    """Every ``with <lock>:`` block in the module."""
    yield from _iter_lock_bodies_from(module.walk())


class LockHeldCallRule(Rule):
    id = "lock-held-call"
    title = "blocking call or pool.submit while holding a lock"

    def check(self, module: Module) -> Iterable[Finding]:
        for node, held in iter_lock_bodies(module):
            for inner in walk_stopping_at_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                called = blocking_call_name(inner)
                if called is None:
                    continue
                yield Finding(
                    self.id,
                    module.relpath,
                    inner.lineno,
                    f"{called}(...) can block while holding {held} — the "
                    "nested-pool deadlock class; move the blocking work "
                    "outside the critical section",
                )


class TransitiveLockHeldCallRule(Rule):
    """The lexical rule upgraded with call-graph reach: a helper that
    sleeps is just as much a deadlock under a held lock as an inline
    ``sleep`` — and exactly the thing a refactor extracts.  Flags calls in
    a ``with <lock>:`` body whose resolved callee reaches a direct blocking
    call within ``max_hops`` call-graph edges (hop 1 = the callee itself).
    Lexically-direct blocking calls stay the lexical rule's findings."""

    id = "transitive-lock-held-call"
    title = "blocking call reachable through helpers while holding a lock"

    def __init__(self, max_hops: int = 3):
        self.max_hops = max_hops

    def finalize(self, project) -> Iterable[Finding]:
        graph = project.callgraph()
        blocking_memo: dict[str, "tuple[str, int] | None"] = {}

        def direct_blocking(qname: str):
            hit = blocking_memo.get(qname, _UNSET)
            if hit is not _UNSET:
                return hit
            fn = graph.functions[qname]
            found = None
            for call in walk_stopping_at_functions(fn.node.body):
                if isinstance(call, ast.Call):
                    name = blocking_call_name(call)
                    if name is not None:
                        found = (name, call.lineno)
                        break
            blocking_memo[qname] = found
            return found

        for fn in graph.functions.values():
            edges_by_node = {id(e.node): e for e in graph.callees(fn.qname)}
            for with_node, held in iter_lock_bodies_in(fn):
                for inner in walk_stopping_at_functions(with_node.body):
                    if not isinstance(inner, ast.Call):
                        continue
                    edge = edges_by_node.get(id(inner))
                    if edge is None or edge.callee is None:
                        continue
                    chain = self._find_blocking_chain(
                        graph, edge.callee, direct_blocking
                    )
                    if chain is None:
                        continue
                    path = " -> ".join(
                        [edge.raw] + [c.rsplit("::", 1)[-1] for c in chain[0][1:]]
                        + [chain[1]]
                    )
                    yield Finding(
                        self.id,
                        fn.relpath,
                        inner.lineno,
                        f"{edge.raw}(...) reaches {chain[1]}(...) ({path}) "
                        f"within {len(chain[0])} call(s) while holding "
                        f"{held} — the nested-pool deadlock class, one "
                        "refactor away from lock-held-call",
                    )

    def _find_blocking_chain(self, graph, start: str, direct_blocking):
        """BFS over resolved edges: shortest (qnames, blocking_name) chain
        from ``start`` to a function with a direct blocking call, within
        ``max_hops`` functions; None if none."""
        frontier = [(start, [start])]
        seen = {start}
        for _ in range(self.max_hops):
            nxt = []
            for q, path in frontier:
                hit = direct_blocking(q)
                if hit is not None:
                    return path, hit[0]
                if len(path) >= self.max_hops:
                    continue
                for e in graph.callees(q):
                    if e.callee is not None and e.callee not in seen:
                        seen.add(e.callee)
                        nxt.append((e.callee, path + [e.callee]))
            frontier = nxt
            if not frontier:
                break
        return None


_UNSET = object()


def iter_lock_bodies_in(fn):
    """``(with_node, held)`` for with-lock blocks lexically inside ``fn``
    (not inside its nested defs — those bodies belong to the nested
    function's own analysis)."""
    yield from _iter_lock_bodies_from(walk_stopping_at_functions(fn.node.body))


_STORE_MODULE = "meta/store.py"
_SQLITE_MARKERS = {"sqlite3.connect", "sqlite3.Connection", "sqlite3.Cursor"}


class SqliteScopeRule(Rule):
    id = "sqlite-scope"
    title = "direct sqlite use outside the serialized meta/store.py path"

    def check(self, module: Module) -> Iterable[Finding]:
        if module.relpath.endswith(_STORE_MODULE):
            return
        for node in module.walk():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "sqlite3":
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            "import sqlite3 outside meta/store.py — all "
                            "sqlite access must go through the store's "
                            "RLock-serialized connection",
                        )
            elif isinstance(node, ast.ImportFrom) and node.module == "sqlite3":
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "from sqlite3 import … outside meta/store.py — all "
                    "sqlite access must go through the store's "
                    "RLock-serialized connection",
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in _SQLITE_MARKERS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "cursor"
                    and (dotted_name(node.func) or "").split(".")[-2:-1]
                    in (["conn"], ["connection"], ["db"], ["_conn"], ["_db"])
                ):
                    yield Finding(
                        self.id,
                        module.relpath,
                        node.lineno,
                        f"{name or 'cursor'}(...) outside meta/store.py — "
                        "the shared :memory: connection races without the "
                        "store's RLock (the 'Cursor needed to be reset' bug)",
                    )
