"""Cross-file convention rules (resolved in ``finalize``).

- ``undocumented-env``: every ``LAKESOUL_*`` env var the code reads must
  have a row in the README's environment-variable table.  Ops can only tune
  knobs they can find; PRs 1–2 each added knobs and the table is the one
  place reviewers look.  Wildcard rows (``LAKESOUL_PROXY_S3_*``) document a
  whole prefix.
- ``metric-name``: obs metric names must follow the registry's documented
  scheme — ``lakesoul_<layer>_<name>``, ``_total`` suffix for counters,
  ``_seconds`` for histograms — and one name must be registered under
  exactly one kind across the whole codebase (the registry raises at
  runtime on a kind clash, but only on the code path that hits it; the lint
  gate catches it before it ships).
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Project, Rule

_ENV_RE = re.compile(r"^LAKESOUL_[A-Z0-9_]+$")
_ENV_DOC_RE = re.compile(r"LAKESOUL_[A-Z0-9_]*\*?")
_METRIC_NAME_RE = re.compile(r"^lakesoul_[a-z][a-z0-9_]*$")

_METRIC_FACTORIES = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}


class UndocumentedEnvRule(Rule):
    id = "undocumented-env"
    title = "LAKESOUL_* env var read in code but missing from the README table"

    def finalize(self, project: Project) -> Iterable[Finding]:
        readme = project.readme_text()
        documented: set[str] = set()
        prefixes: list[str] = []
        for tok in _ENV_DOC_RE.findall(readme):
            if tok.endswith("*"):
                prefixes.append(tok[:-1])
            else:
                documented.add(tok)

        seen: set[str] = set()
        for mod in project.modules:
            for node in mod.walk():
                if not (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _ENV_RE.match(node.value)
                ):
                    continue
                var = node.value
                if var in seen:
                    continue
                # a var is documented if a wildcard row's prefix covers it;
                # the reverse direction is allowed ONLY for dynamic-prefix
                # constants ("LAKESOUL_PROXY_S3_" + key — they end in "_"),
                # otherwise any var that happens to be a prefix of a
                # documented row would silently pass
                if var in documented or any(
                    var.startswith(p) or (var.endswith("_") and p.startswith(var))
                    for p in prefixes
                ):
                    seen.add(var)
                    continue
                seen.add(var)
                yield Finding(
                    self.id,
                    mod.relpath,
                    node.lineno,
                    f"{var} is read here but has no row in the README "
                    "environment-variable table",
                )


class MetricNameRule(Rule):
    id = "metric-name"
    title = "obs metric naming / single-kind registration"

    def finalize(self, project: Project) -> Iterable[Finding]:
        # name -> {kind -> [(path, line)]}
        registrations: dict[str, dict[str, list[tuple[str, int]]]] = defaultdict(
            lambda: defaultdict(list)
        )
        for mod in project.modules:
            if mod.relpath.endswith("obs/metrics.py"):
                continue  # the registry's own plumbing, not a call site
            for node in mod.walk():
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                    continue
                kind = _METRIC_FACTORIES.get(node.func.attr)
                if kind is None or not node.args:
                    continue
                first = node.args[0]
                if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                    continue
                name = first.value
                registrations[name][kind].append((mod.relpath, node.lineno))
                if not _METRIC_NAME_RE.match(name):
                    yield Finding(
                        self.id,
                        mod.relpath,
                        node.lineno,
                        f"metric {name!r} breaks the lakesoul_<layer>_<name> "
                        "naming scheme (lowercase, lakesoul_ prefix)",
                    )
                elif kind == "counter" and not name.endswith("_total"):
                    yield Finding(
                        self.id,
                        mod.relpath,
                        node.lineno,
                        f"counter {name!r} must end in _total "
                        "(Prometheus counter convention)",
                    )
                elif kind == "histogram" and not name.endswith("_seconds"):
                    yield Finding(
                        self.id,
                        mod.relpath,
                        node.lineno,
                        f"histogram {name!r} must end in _seconds "
                        "(duration-histogram convention)",
                    )
        for name, kinds in sorted(registrations.items()):
            if len(kinds) > 1:
                sites = sorted(
                    (path, line) for locs in kinds.values() for path, line in locs
                )
                path, line = sites[0]
                yield Finding(
                    self.id,
                    path,
                    line,
                    f"metric {name!r} is registered under multiple kinds "
                    f"({', '.join(sorted(kinds))}) — the registry raises at "
                    "runtime on whichever call site loses the race",
                )
