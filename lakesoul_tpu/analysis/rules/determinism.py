"""``stage-nondeterminism``: wall-clock and unseeded randomness are banned
inside the ordered data path.

The runtime pipeline promises byte-identical output between serial and
pipelined execution (benchmarks/micro.py asserts it).  ``time.time()`` is
not monotonic (NTP steps break stage deadlines and latency math — use
``time.monotonic()`` / ``time.perf_counter()``) and the module-global
``random.*`` RNG draws depend on scheduling order across worker threads —
both produce runs that can't be reproduced from a seed, the failure mode
arxiv 2604.21275 ties most pipeline debugging pain to.  Seeded
``random.Random(seed)`` instances (fault injection) remain legal.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

# the ordered data path: modules whose code runs inside (or schedules)
# pipeline stages where determinism is part of the contract
ORDERED_STAGE_MODULES = (
    "runtime/pipeline.py",
    "runtime/pool.py",
    "runtime/faults.py",
    "io/reader.py",
    "io/streaming_merge.py",
    "io/merge.py",
    "io/page_cache.py",
    "data/jax_iter.py",
    # scan-plane producers: spool segments must be byte-identical no matter
    # which worker produces them, so their code paths stay deterministic
    "scanplane/worker.py",
    "scanplane/spool.py",
)

# random-module calls that draw from the GLOBAL rng; random.Random /
# random.SystemRandom construct an instance and stay allowed
_GLOBAL_RNG_BLOCKLIST_EXEMPT = {"Random", "SystemRandom", "seed"}


class StageNondeterminismRule(Rule):
    id = "stage-nondeterminism"
    title = "time.time()/global random.* inside ordered pipeline stages"

    def __init__(self, scope: tuple[str, ...] = ORDERED_STAGE_MODULES):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(module.relpath.endswith(m) for m in self.scope):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "time.time":
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "time.time() in an ordered pipeline stage — wall clock "
                    "is not monotonic; use time.monotonic() or "
                    "time.perf_counter()",
                )
            elif (
                name is not None
                and name.startswith("random.")
                and name.split(".", 1)[1] not in _GLOBAL_RNG_BLOCKLIST_EXEMPT
            ):
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    f"{name}(...) draws from the global RNG in an ordered "
                    "pipeline stage — scheduling order changes the stream; "
                    "use a seeded random.Random instance",
                )
