"""Durability rules — every cross-process publication is torn-state-free.

The fleet era made atomic publication the backbone of every cross-process
protocol: spool segments and session manifests (scanplane), ANN plane
records (annplane), obs fleet docs (obs/fleet), the CRC-sidecar spill
rung (fleet/transport), freshness oracle docs.  PR 18 consolidated the
four hand-rolled tmp→fsync→rename implementations onto ONE sanctioned
seam — :mod:`lakesoul_tpu.runtime.atomicio` — and these rules keep it
that way.  Three rules, all over the shared per-function filesystem-op
index (one pass, cached on the project):

- ``torn-publish``: a write-mode ``open`` (bare or ``fs.open(_, "wb")``)
  inside a publication module is a hand-rolled or in-place publish — a
  reader (or a crash) can observe the half-written file.  Renames whose
  producing write hides in a callee are flagged interprocedurally at the
  rename (1-hop over the callgraph).  Only ``runtime/atomicio.py`` may
  hold the raw ops.
- ``unfsynced-rename``: ``os.replace``/``rename``/``fs.mv`` of a file
  whose producing flow (same function + 1-hop callees) writes it but
  never fsyncs — the rename is atomic against readers, yet a host crash
  can replace good data with an empty inode (the classic ALICE finding).
- ``barrier-order``: publication barriers — CRC sidecars, ``LATEST``/
  ``PLANE`` pointers, manifest head docs — must be written AFTER the
  data they cover is durable, checked as intra-function op ordering.
  Barrier-ness is read off the call's argument identifiers (``crc_p``,
  ``LATEST``, ``POINTER``); nested call *names* in arguments are ignored
  so ``_crc_wrap(payload)`` wrapping data blobs does not misclassify.

Known limits, on purpose: flows are followed one resolved hop (the
publication helpers are all direct calls — deeper chains are the runtime
fscheck's job), and write-mode detection needs a constant mode string
(a variable mode is a wrapper's business; the wrapper itself is linted).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from lakesoul_tpu.analysis.callgraph import iter_calls_in_order
from lakesoul_tpu.analysis.engine import Finding, Project, Rule, dotted_name

# the publication modules the repo gate runs with; fixtures override.
# runtime/atomicio.py is the sanctioned seam: exempt from torn-publish,
# still checked by unfsynced-rename and barrier-order.
SCOPE = (
    "scanplane/",
    "annplane/",
    "fleet/",
    "freshness/",
    "obs/fleet",
    "vector/manifest",
    "runtime/atomicio",
)

SANCTIONED = ("runtime/atomicio.py",)

_RENAME_TERMINALS = {"replace", "rename", "mv", "move"}
_FSYNC_TERMINALS = {"fsync", "_fsync_best_effort", "fsync_best_effort"}
_PUBLISH_TERMINALS = {
    "publish_atomic", "publish_bytes_fs", "publish_stream", "stage_stream",
}

# exact-match barrier identifiers (pointer/head names are SHOUTED in the
# stores) + lowercase substrings for CRC/barrier-shaped variable names
_BARRIER_EXACT = {"LATEST", "PLANE", "POINTER", "HEAD"}
_BARRIER_SUBSTRINGS = ("crc", "barrier")


@dataclass(frozen=True)
class _FsOp:
    kind: str  # "open_w" | "rename" | "fsync" | "publish"
    line: int
    barrier: bool  # argument identifiers name a barrier artifact


@dataclass
class _FuncOps:
    qname: str
    relpath: str
    name: str
    ops: list = field(default_factory=list)  # [_FsOp] in lexical order


def _const_mode_writes(call: ast.Call) -> bool:
    """True when the call's mode argument is a constant string containing a
    write/append/create flag.  ``open(p)`` defaults to read; a variable
    mode is a wrapper's business, not a publication site."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value, str):
        return False
    return any(ch in mode.value for ch in "wxa")


def _arg_tokens(call: ast.Call) -> "set[str]":
    """Identifiers + string constants inside the call's ARGUMENTS, skipping
    the func position of nested calls — ``_crc_wrap(payload)`` as a data
    argument must not smuggle 'crc' into the data op's token set."""
    out: set[str] = set()
    stack: list[ast.AST] = list(call.args) + [kw.value for kw in call.keywords]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            stack.extend(node.args)
            stack.extend(kw.value for kw in node.keywords)
            # the func position (Name/Attribute chain) is dropped, but an
            # attribute call's RECEIVER is a value — keep it
            if isinstance(node.func, ast.Attribute):
                stack.append(node.func.value)
            continue
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
            stack.append(node.value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        else:
            stack.extend(ast.iter_child_nodes(node))
    return out


def _is_barrier(call: ast.Call) -> bool:
    for tok in _arg_tokens(call):
        if any(exact in tok for exact in _BARRIER_EXACT):
            return True
        low = tok.lower()
        if any(sub in low for sub in _BARRIER_SUBSTRINGS):
            return True
    return False


def _classify(call: ast.Call) -> "str | None":
    name = dotted_name(call.func) or ""
    terminal = name.rsplit(".", 1)[-1]
    if terminal == "open":
        return "open_w" if _const_mode_writes(call) else None
    if terminal in _FSYNC_TERMINALS:
        return "fsync"
    if terminal in _PUBLISH_TERMINALS or name.startswith("atomicio."):
        return "publish"
    if terminal in _RENAME_TERMINALS:
        # os.replace / os.rename / fs.mv / shutil.move — plain ``x.rename``
        # on non-fs receivers (pandas) is out of scope by module anyway
        return "rename"
    if terminal != "write" and (
        terminal.startswith("write_") or terminal.startswith("_write")
    ):
        # protocol-level writers (_write_blob, write_spill_probe, …):
        # publications for ordering purposes, not raw writes
        return "publish"
    return None


def _op_index(project: Project) -> "dict[str, _FuncOps]":
    """Per-function filesystem-op index over the WHOLE project (scope is a
    flag-time filter so cross-scope flows still resolve), built once and
    shared by all three rules."""
    cached = project._durability_index
    if cached is not None:
        return cached
    graph = project.callgraph()
    out: dict[str, _FuncOps] = {}
    for qname, fn in graph.functions.items():
        fo = _FuncOps(qname, fn.relpath, fn.name)
        for call in iter_calls_in_order(fn.node.body):
            kind = _classify(call)
            if kind is not None:
                fo.ops.append(_FsOp(kind, call.lineno, _is_barrier(call)))
        if fo.ops:
            out[qname] = fo
    project._durability_index = out
    return out


def _flow_ops(index: "dict[str, _FuncOps]", graph, qname: str) -> "list[_FsOp]":
    """A function's own ops plus its resolved 1-hop callees' ops — the
    producing flow a rename's durability is judged against."""
    own = index.get(qname)
    ops = list(own.ops) if own else []
    for edge in graph.callees(qname):
        if edge.callee is None or edge.callee == qname:
            continue
        callee = index.get(edge.callee)
        if callee is not None:
            ops.extend(callee.ops)
    return ops


def _in_scope(relpath: str, scope: tuple) -> bool:
    return any(s in relpath for s in scope)


class TornPublishRule(Rule):
    id = "torn-publish"
    title = "publication-path write bypasses the sanctioned atomic seam"

    def __init__(self, scope: tuple = SCOPE, sanctioned: tuple = SANCTIONED):
        self.scope = scope
        self.sanctioned = sanctioned

    def finalize(self, project: Project) -> Iterable[Finding]:
        index = _op_index(project)
        graph = project.callgraph()
        for qname, fo in sorted(index.items()):
            if not _in_scope(fo.relpath, self.scope):
                continue
            if any(fo.relpath.endswith(s) for s in self.sanctioned):
                continue
            for op in fo.ops:
                if op.kind == "open_w":
                    yield Finding(
                        self.id,
                        fo.relpath,
                        op.line,
                        f"{fo.name} opens a publication-path file in write "
                        "mode outside runtime/atomicio — a reader or a "
                        "crash can observe the half-written file; publish "
                        "via atomicio.publish_atomic/stage_stream "
                        "(publish_bytes_fs for fsspec stores)",
                    )
            own_has_open = any(o.kind == "open_w" for o in fo.ops)
            if own_has_open:
                continue  # the open above is the anchor; don't double-flag
            flow = _flow_ops(index, graph, qname)
            if any(o.kind == "publish" for o in fo.ops):
                continue
            if any(o.kind == "open_w" for o in flow):
                for op in fo.ops:
                    if op.kind == "rename":
                        yield Finding(
                            self.id,
                            fo.relpath,
                            op.line,
                            f"{fo.name} renames a file whose producing "
                            "write lives in a callee — a hand-rolled "
                            "publication split across functions; route the "
                            "whole flow through runtime/atomicio",
                        )


class UnfsyncedRenameRule(Rule):
    id = "unfsynced-rename"
    title = "rename publishes bytes the producing flow never fsynced"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        index = _op_index(project)
        graph = project.callgraph()
        for qname, fo in sorted(index.items()):
            if not _in_scope(fo.relpath, self.scope):
                continue
            renames = [o for o in fo.ops if o.kind == "rename"]
            if not renames:
                continue
            flow = _flow_ops(index, graph, qname)
            if not any(o.kind == "open_w" for o in flow):
                continue  # nothing written in this flow — a pure move
            if any(o.kind in ("fsync", "publish") for o in flow):
                continue  # the flow makes its bytes durable before renaming
            for op in renames:
                yield Finding(
                    self.id,
                    fo.relpath,
                    op.line,
                    f"{fo.name} renames a file its flow wrote but never "
                    "fsynced — the rename is atomic against readers, yet a "
                    "host crash can land the new name on an empty inode; "
                    "fsync before rename (atomicio does both)",
                )


class BarrierOrderRule(Rule):
    id = "barrier-order"
    title = "publication barrier written before the data it covers"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        index = _op_index(project)
        for qname, fo in sorted(index.items()):
            if not _in_scope(fo.relpath, self.scope):
                continue
            pubs = [
                o for o in fo.ops
                if o.kind in ("open_w", "rename", "publish")
            ]
            for i, op in enumerate(pubs):
                if not op.barrier:
                    continue
                if any(not later.barrier for later in pubs[i + 1:]):
                    yield Finding(
                        self.id,
                        fo.relpath,
                        op.line,
                        f"{fo.name} writes a publication barrier (CRC "
                        "sidecar / pointer / head doc) before the data it "
                        "covers — a crash between the two leaves a barrier "
                        "naming bytes that never landed; publish the data "
                        "first, the barrier last",
                    )
