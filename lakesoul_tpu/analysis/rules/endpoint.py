"""``hardcoded-endpoint``: connection endpoints come from config, not code.

The fleet plane made the process topology multi-host: gateways, workers,
autoscalers and trainers find each other through configuration (CLI args,
``LAKESOUL_*`` env vars, handle documents printed by the service role).  A
literal ``host:port`` — ``"grpc://10.0.0.5:8815"``, ``"localhost:9090"``
— baked into code is a deployment assumption that survives exactly one
machine: the moment a worker runs on another host, the literal silently
points at the wrong (or no) process, and no amount of fleet negotiation
can route around an address that never entered the config surface.

Flagged: a string literal (including f-string fragments that form one)
that names a concrete endpoint —

- a URI with an authority and a NONZERO port (``scheme://host:port``);
- a bare ``host:port`` where the host is an IPv4 address, a dotted
  hostname, or ``localhost``;
- any ``localhost`` / loopback-IP URI, with or without a port.

Allowed:

- port ``0`` (``"grpc://127.0.0.1:0"`` — "bind me anywhere", the
  ephemeral-port idiom every service entry uses for tests);
- docstrings (protocol documentation legitimately spells
  ``grpc://host:port``);
- literals that are the DEFAULT of an env lookup
  (``os.environ.get("LAKESOUL_X", "localhost:9090")``) — that IS config
  resolution: the operator can override it without a code change.

Everything else needs an inline pragma naming why the address is truly
invariant — endpoints should be loud in review.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule

# scheme://host:port (port captured; optional path suffix)
_URI_PORT_RE = re.compile(
    r"^[a-z][a-z0-9+.-]*://(?P<host>[^/:@\s]+):(?P<port>\d{1,5})(?:/\S*)?$"
)
# scheme://localhost-ish (no port needed — the host alone is the problem)
_URI_LOOPBACK_RE = re.compile(
    r"^[a-z][a-z0-9+.-]*://(?:localhost|127\.0\.0\.1|\[?::1\]?)(?:[:/]\S*)?$",
    re.IGNORECASE,
)
# bare host:port where the host is unambiguously a network endpoint:
# IPv4, a dotted hostname, or localhost (a lone word:digits like
# "attempt:3" is a label, not an address)
_BARE_HOSTPORT_RE = re.compile(
    r"^(?P<host>(?:\d{1,3}(?:\.\d{1,3}){3}"
    r"|[A-Za-z0-9-]+(?:\.[A-Za-z0-9-]+)+"
    r"|localhost)):(?P<port>\d{1,5})$",
    re.IGNORECASE,
)


def _endpoint_in(text: str) -> "str | None":
    """The offending endpoint spelling, or None if the text is clean."""
    m = _URI_PORT_RE.match(text)
    if m:
        # port 0 is "bind me anywhere" — sanctioned even on loopback
        return text if int(m.group("port")) != 0 else None
    if _URI_LOOPBACK_RE.match(text):
        return text
    m = _BARE_HOSTPORT_RE.match(text)
    if m and int(m.group("port")) != 0:
        return text
    return None


def _docstring_constants(tree: ast.AST) -> "set[int]":
    """ids of Constant nodes that are docstrings (module/class/function)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            continue
        body = getattr(node, "body", [])
        if (
            body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            out.add(id(body[0].value))
    return out


def _is_env_default(node: ast.AST, parents: dict) -> bool:
    """Is this literal an argument of an env lookup (``os.environ.get`` /
    ``os.getenv``)?  That literal is the config surface's DEFAULT — the
    sanctioned home for a fallback endpoint."""
    cur = parents.get(node)
    hops = 0
    while cur is not None and hops < 3:
        if isinstance(cur, ast.Call):
            f = cur.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            return name in ("get", "getenv")
        cur = parents.get(cur)
        hops += 1
    return False


class HardcodedEndpointRule(Rule):
    id = "hardcoded-endpoint"
    title = "literal network endpoint outside config/env resolution"

    def check(self, module: Module) -> Iterable[Finding]:
        docstrings = _docstring_constants(module.tree)
        parents = module.parents()
        for node in module.walk():
            if not (
                isinstance(node, ast.Constant) and isinstance(node.value, str)
            ):
                continue
            if id(node) in docstrings:
                continue
            endpoint = _endpoint_in(node.value)
            if endpoint is None:
                continue
            if _is_env_default(node, parents):
                continue
            yield Finding(
                self.id,
                module.relpath,
                node.lineno,
                f"hardcoded endpoint {endpoint!r}; resolve it through"
                " configuration (CLI arg, LAKESOUL_* env var, or a service"
                " handle) so the fleet can be re-homed without a code"
                " change",
            )
