"""``fleet-identity-label``: process-identity labels come from obs.fleet.

The fleet aggregator keys every merged series on the identity labels
(``role=``, ``service_id=``, ``worker=``).  A hand-rolled literal at a
metric call site — ``reg.gauge("...", role="scanworker")`` or an f-string
``service_id=f"w-{pid}"`` — mints a SECOND spelling of an identity the
process already has (:func:`lakesoul_tpu.obs.fleet.process_identity`), and
the aggregate silently splits into per-spelling series nobody sums.  The
sanctioned sources are the obs.fleet helpers (``identity_labels()``,
``identity().service_id``, a worker's own ``worker_id`` attribute):
VARIABLES carrying the one registered identity, which is exactly what this
rule can distinguish from an inline string.

Flagged: a string-literal or f-string value for an identity keyword in a
call to a metric factory (``counter``/``gauge``/``histogram``) or a stage
helper (``stage_merge``/``stage_observe``/``stage_histogram``).  Values
read from a variable, attribute, or call pass — they trace back to a
single assignment a reviewer can audit.  ``obs/fleet.py`` itself is
exempt: it is the implementation these labels must come from.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule

_IDENTITY_KEYS = ("role", "worker", "service_id")

_FACTORIES = (
    "counter", "gauge", "histogram",
    "stage_merge", "stage_observe", "stage_histogram",
)

_EXEMPT = ("lakesoul_tpu/obs/fleet.py",)


def _callee_name(func: ast.expr) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class FleetIdentityLabelRule(Rule):
    id = "fleet-identity-label"
    title = "hand-rolled process-identity label at a metric call site"

    def check(self, module: Module) -> Iterable[Finding]:
        if any(module.relpath.endswith(p) for p in _EXEMPT):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node.func)
            if name not in _FACTORIES:
                continue
            for kw in node.keywords:
                if kw.arg not in _IDENTITY_KEYS:
                    continue
                v = kw.value
                literal = (
                    isinstance(v, ast.Constant) and isinstance(v.value, str)
                ) or isinstance(v, ast.JoinedStr)
                if literal:
                    yield Finding(
                        self.id,
                        module.relpath,
                        node.lineno,
                        f"identity label {kw.arg}= is a hand-rolled string at"
                        f" a {name}() call site; use the obs.fleet identity"
                        " helpers (identity_labels() / process_identity())"
                        " so fleet aggregation sees ONE spelling",
                    )
