"""Isolation-portability rules — the metadata path must survive PG.

The seed store grew up on SQLite, whose write transactions are fully
serialized: any read-then-write inside ``transaction()`` is atomic for
free, and sqlite-only SQL just works.  The reference deployment is
PostgreSQL at READ COMMITTED, where none of that holds — a peer's commit
can land between your read and your dependent write, and a blind
``UPDATE ... WHERE pk=?`` silently overwrites a takeover.  PR 7 made the
lease protocol CAS-shaped by hand; these rules make the discipline
mechanical for the whole ``meta/`` path.  Four rules over the shared SQL
statement model (:mod:`lakesoul_tpu.analysis.sqlinfo`):

- ``cas-guard``: UPDATE/DELETE on the coordination tables (``lease``,
  ``partition_info``, ``data_commit_info``) must carry the full CAS
  predicate in the WHERE — not just the primary key — and lease CAS
  results must be consumed through ``.rowcount`` (an unexamined CAS is a
  blind write with extra steps).  ``DELETE FROM lease`` is always wrong:
  lease rows are tombstoned so fencing tokens stay monotonic per key.
- ``read-modify-write``: a value read from the store (``get_*``,
  ``commit_state``, …) flowing into a dependent blind store write
  (``set_global_config``/``update_table_properties``/
  ``update_table_schema``) — interprocedural, over the taint framework.
  Flows whose sink sits lexically inside a ``with store.transaction()``
  block are sanctioned: the seam (plus ``ROW_LOCK`` reads) makes them
  unsplittable.
- ``txn-boundary``: write statements must execute inside a transaction
  context (``with ...transaction()``, ``with conn:``, or routed through
  ``self._exec(conn, …)`` by a helper that received the txn's conn), and
  callers outside ``meta/store.py`` must not reach around the named seam
  via ``store._exec``/``store._txn``/``store._conn``.
- ``sqlite-ism``: sqlite-only SQL headed for the backend seam — ``INSERT
  OR REPLACE``, ``datetime('now')``/``julianday``/``strftime``,
  ``rowid``, ``AUTOINCREMENT``, ``PRAGMA`` outside the sqlite backend
  class, and qmark/OR-IGNORE statements bound past ``translate_sql`` via
  a raw ``execute`` — everything ``fake_psycopg2`` or real PG would
  reject or silently mis-run.

Known limits, on purpose: SQL strings assembled in variables before the
execute call are invisible (the store inlines every statement); the
seam-reach-around check keys on a ``store``-named receiver so unrelated
``_exec`` methods stay out of scope; and transaction context is lexical
(a callee that writes on a caller's conn is accepted only through the
``_exec(conn, …)`` convention) — deeper interleaving questions are the
runtime replayer's job (:mod:`lakesoul_tpu.analysis.txncheck`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Project,
    Rule,
    dotted_name,
)
from lakesoul_tpu.analysis.sqlinfo import Statement, parse_statement

# default repo scope: the PG-ready metadata path
SCOPE = ("meta/",)

# seam modules that may touch transaction internals (_exec/_txn/_conn)
SEAM = ("meta/store.py",)

# coordination tables and the CAS discipline each one carries
_LEASE_CAS_COLS = frozenset({"fencing_token", "holder_id", "expires_at_ms"})
_TABLE_KEYS = {
    "partition_info": frozenset({"table_id", "partition_desc", "version"}),
    "data_commit_info": frozenset({"table_id", "partition_desc", "commit_id"}),
}

_WRITE_VERBS = ("insert", "update", "delete")
_STMT_HEADS = (
    "SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "PRAGMA",
    "BEGIN", "COMMIT", "ROLLBACK", "ATTACH", "VACUUM", "ANALYZE",
)


@dataclass
class _SqlSite:
    """One SQL string in a module, with its execution context."""

    stmt: Statement
    line: int
    node: ast.AST  # the string expression
    call: "ast.Call | None"  # nearest enclosing call, if any
    exec_kind: str  # "seam" (_exec) | "direct" (execute*) | "none"
    in_txn: bool  # lexically inside an accepted transaction context
    conn_routed: bool  # _exec(conn, …) inside a conn-taking helper
    func: "ast.AST | None"  # enclosing function def
    class_name: "str | None"  # enclosing class name


def _string_text(node: ast.AST) -> "str | None":
    """The statement-ish text of a string expression.  JoinedStr formatted
    values become \\x00 placeholders (never identifier-shaped, so a dynamic
    table name reads as unresolvable rather than as a table)."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            else:
                parts.append("\x00")
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _string_text(node.left)
        right = _string_text(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def _terminal(func: ast.expr) -> str:
    return (dotted_name(func) or "").rsplit(".", 1)[-1]


def _is_txn_with(node: ast.With) -> bool:
    for item in node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call):
            if _terminal(ce.func) in ("transaction", "_txn"):
                return True
        elif isinstance(ce, ast.Name) and ce.id.startswith("conn"):
            return True  # `with conn:` — the DB-API transaction CM
    return False


_SCOPE_BOUNDARY = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)


def _context(node: ast.AST, parents: dict) -> tuple:
    """(nearest call, in_txn, enclosing function, enclosing class name) for
    a string node.  Transaction context is lexical and does not cross
    function boundaries — a With wrapping a nested def says nothing about
    when the def's body runs."""
    call = None
    in_txn = False
    func = None
    class_name = None
    crossed_func = False
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) and call is None and not crossed_func:
            call = cur
        elif isinstance(cur, ast.With) and not crossed_func:
            in_txn = in_txn or _is_txn_with(cur)
        elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if func is None:
                func = cur
            crossed_func = True
        elif isinstance(cur, ast.ClassDef):
            if class_name is None:
                class_name = cur.name
            crossed_func = True
        cur = parents.get(cur)
    return call, in_txn, func, class_name


def _module_sites(module: Module) -> "list[_SqlSite]":
    """Every statement-shaped SQL string in the module with its context.
    Cheap relative to the shared walk — the three per-module rules each
    call this on the handful of ``meta/`` files."""
    parents = module.parents()
    sites: list[_SqlSite] = []
    seen: set = set()
    for node in module.walk():
        if not isinstance(node, (ast.Constant, ast.JoinedStr)):
            continue
        if id(node) in seen:
            continue
        # implicit concatenation folds into one node; explicit `+` chains
        # are walked from their root so halves don't double-report
        parent = parents.get(node)
        if isinstance(parent, ast.JoinedStr) or (
            isinstance(parent, ast.BinOp) and isinstance(parent.op, ast.Add)
        ):
            continue
        text = _string_text(node)
        if text is None:
            continue
        head = text.lstrip().split(" ", 1)[0].upper() if text.strip() else ""
        if head.rstrip("(") not in _STMT_HEADS:
            continue
        stmt = parse_statement(text)
        if stmt is None:
            continue
        seen.add(id(node))
        call, in_txn, func, class_name = _context(node, parents)
        exec_kind = "none"
        conn_routed = False
        if call is not None:
            term = _terminal(call.func)
            if term == "_exec":
                exec_kind = "seam"
                has_conn_param = func is not None and any(
                    a.arg == "conn" for a in func.args.args
                )
                conn_routed = has_conn_param and bool(call.args) and (
                    isinstance(call.args[0], ast.Name)
                    and call.args[0].id == "conn"
                )
            elif term in ("execute", "executemany", "executescript"):
                exec_kind = "direct"
        line = call.lineno if call is not None else node.lineno
        sites.append(_SqlSite(
            stmt, line, node, call, exec_kind, in_txn, conn_routed,
            func, class_name,
        ))
    return sites


def _txn_ranges(project: Project) -> "dict[str, list[tuple[int, int]]]":
    """Per-module line ranges of transaction() / _txn Withs, built once and
    cached on the project — the read-modify-write sanction filter."""
    cached = project._isolation_index
    if cached is not None:
        return cached
    out: dict[str, list[tuple[int, int]]] = {}
    for module in project.modules:
        ranges = []
        for node in module.walk():
            if isinstance(node, ast.With) and _is_txn_with(node):
                end = getattr(node, "end_lineno", None) or node.lineno
                ranges.append((node.lineno, end))
        if ranges:
            out[module.relpath] = ranges
    project._isolation_index = out
    return out


def _in_scope(relpath: str, scope: tuple) -> bool:
    return any(s in relpath for s in scope)


def _consumes_rowcount(site: _SqlSite, module: Module) -> bool:
    """True when the execute call's result reaches a ``.rowcount`` read —
    directly (``...).rowcount``) or via the assigned name anywhere in the
    enclosing function."""
    call = site.call
    if call is None:
        return False
    parents = module.parents()
    parent = parents.get(call)
    if isinstance(parent, ast.Attribute) and parent.attr == "rowcount":
        return True
    target = None
    if (isinstance(parent, ast.Assign) and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)):
        target = parent.targets[0].id
    elif isinstance(parent, ast.AnnAssign) and isinstance(parent.target, ast.Name):
        target = parent.target.id
    if target is None:
        return False
    root = site.func if site.func is not None else module.tree
    for node in ast.walk(root):
        if (isinstance(node, ast.Attribute) and node.attr == "rowcount"
                and isinstance(node.value, ast.Name)
                and node.value.id == target):
            return True
    return False


class CasGuardRule(Rule):
    id = "cas-guard"
    title = "coordination-table write without a compare-and-set guard"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not _in_scope(module.relpath, self.scope):
            return
        for site in _module_sites(module):
            stmt = site.stmt
            if stmt.op not in ("update", "delete"):
                continue
            if stmt.table == "lease":
                if stmt.op == "delete":
                    yield Finding(
                        self.id, module.relpath, site.line,
                        "DELETE FROM lease — lease rows are tombstoned "
                        "(holder cleared), never deleted: deleting restarts "
                        "fencing tokens at 1 and a zombie ex-holder could "
                        "pass the commit guard with its stale token",
                    )
                    continue
                if ("lease_key" not in stmt.where_cols
                        or not (stmt.where_cols & _LEASE_CAS_COLS)):
                    yield Finding(
                        self.id, module.relpath, site.line,
                        "UPDATE lease without a CAS predicate — the WHERE "
                        "must re-check holder/token/expiry (not just "
                        "lease_key) so a racing takeover's commit makes "
                        "this update match zero rows under READ COMMITTED",
                    )
                    continue
                if not _consumes_rowcount(site, module):
                    yield Finding(
                        self.id, module.relpath, site.line,
                        "lease CAS result is never checked — read "
                        ".rowcount and treat 0 matched rows as 'lost the "
                        "race'; an unexamined CAS is a blind write with "
                        "extra steps",
                    )
                continue
            keys = _TABLE_KEYS.get(stmt.table or "")
            if keys is None:
                continue
            missing = keys - stmt.where_cols
            if missing:
                yield Finding(
                    self.id, module.relpath, site.line,
                    f"{stmt.op.upper()} {stmt.table} constrains "
                    f"{sorted(stmt.where_cols) or 'nothing'} but not "
                    f"{sorted(missing)} — version-chain rows are immutable "
                    "at coarser granularity; a write that spans versions "
                    "clobbers concurrent committers",
                )


# store reads whose results, flowing into a blind write, form an RMW race
_READ_METHODS = frozenset({
    "get_global_config", "get_desc_epoch", "get_lease",
    "get_latest_partition_info", "get_all_latest_partition_info",
    "get_partition_versions", "get_partition_info_at_version",
    "get_partition_descs", "get_partition_at_timestamp",
    "get_data_commit_info", "commit_state", "get_table_info_by_id",
    "get_table_info_by_name", "get_table_info_by_path",
    "list_uncommitted_commits",
})

# blind store writes: last-writer-wins on the whole value
_BLIND_WRITES = {
    "set_global_config": 1,
    "update_table_properties": 1,
    "update_table_schema": 1,
}

# every module is a potential entry: RMW flows start wherever store reads do
RMW_SCOPE = (".py",)


class ReadModifyWriteRule(Rule):
    id = "read-modify-write"
    title = "store read flows into a dependent blind store write"

    def __init__(self, scope: tuple = RMW_SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        from lakesoul_tpu.analysis.dataflow import TaintAnalysis, TaintConfig

        def is_store_read(call: ast.Call, name: str) -> bool:
            return (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _READ_METHODS)

        config = TaintConfig(
            source_self_attrs=frozenset(),
            sanitizer_prefixes=(),
            sink_methods=dict(_BLIND_WRITES),
            source_call_predicate=is_store_read,
            propagate_all_calls=True,
        )
        analysis = TaintAnalysis(project.callgraph(), config)
        ranges = _txn_ranges(project)
        seen: set = set()
        for hit in analysis.run(self.scope):
            if any(lo <= hit.line <= hi
                   for lo, hi in ranges.get(hit.relpath, ())):
                continue  # inside the transaction seam: unsplittable
            key = (hit.relpath, hit.line)
            if key in seen:
                continue
            seen.add(key)
            via = " -> ".join(hit.chain)
            yield Finding(
                self.id, hit.relpath, hit.line,
                f"value read from the store ({hit.source_desc}) flows into "
                f"blind write {hit.sink}(...) (via {via}) — under READ "
                "COMMITTED a peer's commit between read and write is "
                "silently overwritten; use a CAS helper "
                "(merge_table_properties / update_global_config / "
                "set_descs_verified) or do both inside one "
                "store.transaction() with a ROW_LOCK read",
            )


class TxnBoundaryRule(Rule):
    id = "txn-boundary"
    title = "store mutation outside the write-transaction seam"

    # the analysis package quotes SQL as data (rule messages, fixtures,
    # the replayer's statement model) — never executes it
    EXCLUDE = ("analysis/",)

    def __init__(self, scope: tuple = ("lakesoul_tpu/",), seam: tuple = SEAM):
        self.scope = scope
        self.seam = seam

    def check(self, module: Module) -> Iterable[Finding]:
        if not _in_scope(module.relpath, self.scope):
            return
        if _in_scope(module.relpath, self.EXCLUDE):
            return
        in_seam = any(module.relpath.endswith(s) for s in self.seam)
        if not in_seam:
            # reach-around: transaction internals on a store receiver
            for node in module.walk():
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                if node.func.attr not in ("_exec", "_txn", "_conn"):
                    continue
                receiver = (dotted_name(node.func.value) or "")
                if "store" not in receiver.rsplit(".", 1)[-1].lower():
                    continue
                yield Finding(
                    self.id, module.relpath, node.lineno,
                    f"store transaction internals reached around the named "
                    f"seam ({receiver}.{node.func.attr}) — callers use "
                    "store.transaction() or a CAS helper so subclass "
                    "overrides and txncheck instrumentation still apply",
                )
        for site in _module_sites(module):
            stmt = site.stmt
            if not stmt.is_write or stmt.table is None:
                continue
            if site.in_txn or site.conn_routed:
                continue
            yield Finding(
                self.id, module.relpath, site.line,
                f"{stmt.op.upper()} {stmt.table} executes outside any "
                "transaction context (autocommit) — multi-statement "
                "invariants straddle commit points under READ COMMITTED; "
                "wrap the statements in `with store.transaction() as "
                "conn:` or route through `self._exec(conn, ...)` from a "
                "helper that received the transaction's conn",
            )


_TIME_FUNCS = ("datetime(", "julianday(", "strftime(")


class SqliteIsmRule(Rule):
    id = "sqlite-ism"
    title = "sqlite-only SQL headed for the backend seam"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not _in_scope(module.relpath, self.scope):
            return
        for site in _module_sites(module):
            if site.class_name and site.class_name.startswith("Sqlite"):
                continue  # the sqlite backend speaks sqlite by definition
            reason = self._reason(site)
            if reason is not None:
                yield Finding(self.id, module.relpath, site.line, reason)

    @staticmethod
    def _reason(site: _SqlSite) -> "str | None":
        stmt = site.stmt
        low = stmt.text.lower()
        if stmt.or_replace:
            return ("INSERT OR REPLACE is sqlite-only and translate_sql "
                    "does not rewrite it — spell the upsert as ON CONFLICT "
                    "(...) DO UPDATE")
        for fn in _TIME_FUNCS:
            if fn in low:
                return (f"sqlite time function {fn}...) has no PG "
                        "equivalent — compute timestamps in Python "
                        "(now_millis()) and bind them as parameters")
        if "rowid" in low:
            return ("rowid is sqlite's implicit key and does not exist in "
                    "PG — name an explicit primary-key column")
        if "autoincrement" in low:
            return ("AUTOINCREMENT is sqlite-only — PG spells it "
                    "GENERATED ALWAYS AS IDENTITY; the shared schema must "
                    "avoid both (ids are assigned in Python)")
        if stmt.op == "pragma":
            return ("PRAGMA outside the sqlite backend class — backend "
                    "tuning belongs to SqliteMetadataStore; PG would "
                    "reject the statement")
        if site.exec_kind == "direct":
            if stmt.or_ignore:
                return ("INSERT OR IGNORE bound past translate_sql via a "
                        "raw execute — only self._exec() rewrites it to ON "
                        "CONFLICT DO NOTHING for the PG paramstyle")
            if stmt.qmark:
                return ("qmark placeholders executed directly — PG's "
                        "paramstyle is %s; route the statement through "
                        "self._exec() so translate_sql rebinds it")
        return None
