"""JAX/TPU trace-safety rules (the device-side rule pack).

The PR 3-4 rules guard the host/threaded half of the codebase; these five
guard the device half — the jit/pallas-traced code the north-star training
loop actually runs.  Their failure modes are *silent*: a host side effect
inside a traced function runs once at trace time and never again; a
``float64`` reaching a TPU boundary demotes without a word; a
data-dependent shape recompiles per batch; a malformed BlockSpec either
fails at Mosaic-compile time on real hardware (never on the CPU fallback
CI runs) or quietly reads the wrong tile.  Deep Lake (arxiv 2209.10785)
and arxiv 2604.21275 both identify host↔device transfer discipline and
static-shape violations as the dominant silent-throughput killers in
loader stacks — this pack makes them lint findings instead of benchmark
regressions.

Everything here keys off the **device index** built once per project:

- **jit entries** — functions decorated ``@jax.jit`` /
  ``@functools.partial(jax.jit, ...)`` (plus ``pjit``), functions passed
  to a ``jax.jit(...)`` call by name or through ``functools.partial``,
  and functions whose *parameter* some helper jits (the
  ``jax.jit(step_fn, ...)`` factory pattern) — each with its parsed
  ``static_argnames``/``static_argnums``;
- **pallas kernels** — first argument of every ``pl.pallas_call``;
- **traced functions** — the transitive closure over resolved call edges
  *and* function references (``lax.scan(body, ...)``: the callback is
  traced even when nobody "calls" it), starting from the entries,
  shard_map-wrapped functions and kernels.  References inside
  ``pure_callback``/``io_callback`` wrappers are excluded — those escape
  to the host by design.

The runtime counterpart is :mod:`lakesoul_tpu.analysis.tracecheck`
(``LAKESOUL_TRACECHECK=1``): these rules catch the lexical causes of
retraces, the detector catches whatever shape thrash survives them.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Project,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

# ------------------------------------------------------------ device index

_JIT_DOTTED = {"jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"}
_SHARD_MAP_DOTTED = {"shard_map", "jax.shard_map"}
_PARTIAL_DOTTED = {"functools.partial", "partial"}

# terminal attr names through which a function argument becomes traced code
_TRANSFORM_TERMINALS = {
    "jit", "pjit", "shard_map", "pallas_call",
    "scan", "fori_loop", "while_loop", "cond", "switch", "associative_scan",
    "vmap", "pmap", "grad", "value_and_grad", "remat", "checkpoint",
    "custom_vjp", "custom_jvp",
}
# lax.map only — a bare ``map(f, xs)`` is the Python builtin
_LAX_MAP_RECEIVERS = ("lax", "jax.lax")

# callbacks escape the trace to the host on purpose; functions passed to
# them are host code, not traced code
_CALLBACK_TERMINALS = {"pure_callback", "io_callback", "callback", "debug_callback"}


def _unwrap_partial(expr: ast.expr) -> tuple[ast.expr, "ast.Call | None"]:
    """``functools.partial(f, ...)`` → (f, the partial call); else (expr, None)."""
    if (
        isinstance(expr, ast.Call)
        and dotted_name(expr.func) in _PARTIAL_DOTTED
        and expr.args
    ):
        return expr.args[0], expr
    return expr, None


def _static_info(call: "ast.Call | None") -> tuple[frozenset, frozenset]:
    """(static_argnames, static_argnums) parsed from a jit/partial call."""
    names: set[str] = set()
    nums: set[int] = set()
    if call is None:
        return frozenset(), frozenset()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
                else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.add(v.value)
        elif kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
                else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.add(v.value)
    return frozenset(names), frozenset(nums)


def _decorator_trace_info(dec: ast.expr):
    """→ ("jit" | "shard_map", kwargs-carrying call | None), or None."""
    name = dotted_name(dec)
    if name in _JIT_DOTTED:
        return "jit", None
    if name in _SHARD_MAP_DOTTED:
        return "shard_map", None
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_DOTTED:
            return "jit", dec
        if fname in _SHARD_MAP_DOTTED:
            return "shard_map", dec
        if fname in _PARTIAL_DOTTED and dec.args:
            inner = dotted_name(dec.args[0])
            if inner in _JIT_DOTTED:
                return "jit", dec
            if inner in _SHARD_MAP_DOTTED:
                return "shard_map", dec
    return None


class DeviceIndex:
    """Jit entries, pallas kernels, and the traced-function closure —
    built ONCE per project (``device_index``) and shared by the pack."""

    def __init__(self) -> None:
        # qname → (static_argnames, static_argnums, decl line)
        self.jit_entries: dict[str, tuple[frozenset, frozenset, int]] = {}
        self.pallas_kernels: set[str] = set()
        # qname → human reason it is traced ("@jax.jit", "lax.scan callback",
        # "called from <fn>", ...)
        self.traced: dict[str, str] = {}

    @classmethod
    def build(cls, project: Project) -> "DeviceIndex":
        graph = project.callgraph()
        idx = cls()
        roots: list[tuple[str, str]] = []  # (qname, reason)

        # 1. decorators
        for q, fn in graph.functions.items():
            for dec in fn.node.decorator_list:
                info = _decorator_trace_info(dec)
                if info is None:
                    continue
                kind, call = info
                if kind == "jit":
                    names, nums = _static_info(call)
                    idx.jit_entries[q] = (names, nums, fn.node.lineno)
                    roots.append((q, "@jax.jit"))
                else:
                    roots.append((q, "@shard_map"))

        # 2. transform call sites: jit(f)/partial targets, scan/vmap/...
        # callbacks, pallas kernels; plus the jit-a-parameter factory shape
        param_jitters: dict[str, set[str]] = {}  # qname → param names it jits
        for caller_q, edges in graph.edges.items():
            caller = graph.functions.get(caller_q)
            relpath = caller_q.split("::", 1)[0]
            for e in edges:
                terminal = e.attr
                if terminal == "map" and e.receiver not in _LAX_MAP_RECEIVERS:
                    continue
                if terminal == "map" or terminal in _TRANSFORM_TERMINALS:
                    is_jit = e.raw in _JIT_DOTTED or terminal == "pjit"
                    is_kernel = terminal == "pallas_call"
                    arg_exprs = list(e.node.args) + [
                        kw.value for kw in e.node.keywords
                    ]
                    if is_kernel:
                        arg_exprs = arg_exprs[:1]  # only the kernel argument
                    for i, arg in enumerate(arg_exprs):
                        target, partial_call = _unwrap_partial(arg)
                        ref = dotted_name(target)
                        if ref is None:
                            continue
                        if is_jit and isinstance(target, ast.Name) and caller \
                                is not None:
                            # jax.jit(step_fn, ...): the jitted thing is a
                            # parameter of the caller OR of a lexically
                            # enclosing function (the jit often lives in a
                            # nested closure) — bindings at that function's
                            # call sites become entries
                            chain = caller.name.split(".")
                            owner = None
                            for depth in range(len(chain), 0, -1):
                                fq = f"{relpath}::{'.'.join(chain[:depth])}"
                                fi = graph.functions.get(fq)
                                if fi is not None and target.id in fi.params:
                                    owner = fq
                                    break
                            if owner is not None:
                                param_jitters.setdefault(owner, set()).add(
                                    target.id
                                )
                                continue
                        q = graph.resolve_reference(relpath, caller, ref)
                        if q is None:
                            continue
                        if is_kernel:
                            idx.pallas_kernels.add(q)
                            roots.append((q, "pallas kernel"))
                        elif is_jit and i == 0:
                            names, nums = _static_info(e.node)
                            idx.jit_entries.setdefault(
                                q, (names, nums, e.node.lineno)
                            )
                            roots.append((q, "jax.jit(...) target"))
                        elif not is_jit:
                            roots.append((q, f"{e.raw} callback"))

        # 3. propagate through the jit-a-parameter factories
        if param_jitters:
            for caller_q, edges in graph.edges.items():
                caller = graph.functions.get(caller_q)
                relpath = caller_q.split("::", 1)[0]
                for e in edges:
                    jitted_params = param_jitters.get(e.callee or "")
                    if not jitted_params:
                        continue
                    callee = graph.functions[e.callee]
                    params = callee.params
                    offset = 1 if callee.is_method and params and \
                        params[0] in ("self", "cls") else 0
                    bound: list[tuple[str, ast.expr]] = []
                    for i, a in enumerate(e.node.args):
                        j = i + offset
                        if j < len(params):
                            bound.append((params[j], a))
                    bound += [
                        (kw.arg, kw.value) for kw in e.node.keywords if kw.arg
                    ]
                    for pname, a in bound:
                        if pname not in jitted_params:
                            continue
                        target, _ = _unwrap_partial(a)
                        ref = dotted_name(target)
                        q = graph.resolve_reference(relpath, caller, ref) \
                            if ref else None
                        if q is not None:
                            idx.jit_entries.setdefault(q, (
                                frozenset(), frozenset(), e.node.lineno
                            ))
                            roots.append(
                                (q, f"jitted via {e.callee.rsplit('::', 1)[-1]}")
                            )

        # 4. traced closure: resolved callees + function references
        frontier = []
        for q, reason in roots:
            if q in graph.functions and q not in idx.traced:
                idx.traced[q] = reason
                frontier.append(q)
        while frontier:
            nxt: list[str] = []
            for q in frontier:
                fn = graph.functions[q]
                relpath = q.split("::", 1)[0]
                for e in graph.callees(q):
                    if e.callee is not None and e.callee not in idx.traced:
                        idx.traced[e.callee] = \
                            f"called from {q.rsplit('::', 1)[-1]}"
                        nxt.append(e.callee)
                # names referenced outside callback wrappers resolve too:
                # lax.scan / attention_fn defaults / closures passed around
                skip: set[int] = set()
                for node in walk_stopping_at_functions(fn.node.body):
                    if isinstance(node, ast.Call) and (
                        (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr in _CALLBACK_TERMINALS
                        )
                        or (
                            isinstance(node.func, ast.Name)
                            and node.func.id in _CALLBACK_TERMINALS
                        )
                    ):
                        for a in node.args:
                            skip.update(id(n) for n in ast.walk(a))
                for node in walk_stopping_at_functions(fn.node.body):
                    if id(node) in skip:
                        continue
                    ref = None
                    if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load
                    ):
                        ref = node.id
                    elif isinstance(node, ast.Attribute):
                        ref = dotted_name(node)
                    if ref is None:
                        continue
                    rq = graph.resolve_reference(relpath, fn, ref)
                    if rq is not None and rq not in idx.traced:
                        idx.traced[rq] = f"referenced from {q.rsplit('::', 1)[-1]}"
                        nxt.append(rq)
            frontier = nxt
        return idx


def device_index(project: Project) -> DeviceIndex:
    """The per-project device index, built once and shared by the pack
    (same contract as ``Project.callgraph()``)."""
    idx = getattr(project, "_device_index", None)
    if idx is None:
        idx = DeviceIndex.build(project)
        project._device_index = idx
    return idx


def _finding_fn_label(qname: str) -> str:
    return qname.rsplit("::", 1)[-1]


# -------------------------------------------------------- trace-impure-call

_IMPURE_CALLS = {
    "time.time": "wall clock is baked in as a constant at trace time",
    "time.monotonic": "wall clock is baked in as a constant at trace time",
    "time.perf_counter": "wall clock is baked in as a constant at trace time",
    "time.time_ns": "wall clock is baked in as a constant at trace time",
    "time.process_time": "wall clock is baked in as a constant at trace time",
    "datetime.now": "wall clock is baked in as a constant at trace time",
    "datetime.datetime.now": "wall clock is baked in as a constant at trace time",
    "datetime.utcnow": "wall clock is baked in as a constant at trace time",
    "os.urandom": "host entropy is drawn once at trace time",
    "uuid.uuid4": "host entropy is drawn once at trace time",
    "input": "host I/O runs at trace time only",
    "print": "runs at trace time only — use jax.debug.print for traced values",
    "open": "host I/O runs at trace time only",
}

# np/global RNG draws freeze one sample into the compiled graph; jax.random
# with explicit keys is the traced-code RNG
_NP_RANDOM_EXEMPT = {"default_rng", "Generator", "RandomState", "SeedSequence"}
_PY_RANDOM_EXEMPT = {"Random", "SystemRandom"}

_MUTATING_METHODS = {
    "append", "extend", "insert", "update", "setdefault", "pop", "popitem",
    "clear", "add", "remove", "discard", "write", "writelines",
}


def _locally_bound_names(fn_node) -> set[str]:
    """Params + every name the function itself binds (assignments, loop
    targets, withitems, walrus) — mutation of these is trace-local and
    legal; mutation of anything else escapes the trace."""
    a = fn_node.args
    bound = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for node in walk_stopping_at_functions(fn_node.body):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            bound.difference_update(node.names)  # explicitly NOT local
    return bound


class TraceImpureCallRule(Rule):
    id = "trace-impure-call"
    title = "Python side effect reachable inside jit/pallas-traced code"

    def finalize(self, project: Project) -> Iterable[Finding]:
        graph = project.callgraph()
        idx = device_index(project)
        for q, reason in sorted(idx.traced.items()):
            fn = graph.functions[q]
            label = _finding_fn_label(q)
            bound = None
            for node in walk_stopping_at_functions(fn.node.body):
                # mutation of a captured container: the list/dict outlives
                # the trace, so the mutation replays never.  Only calls
                # whose result is DISCARDED count — `d.update(e)` mutates,
                # `x, y = tx.update(...)` is a pure method that happens to
                # share the name
                if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                    mut = node.value
                    if (
                        isinstance(mut.func, ast.Attribute)
                        and mut.func.attr in _MUTATING_METHODS
                        and isinstance(mut.func.value, ast.Name)
                    ):
                        if bound is None:
                            bound = _locally_bound_names(fn.node)
                        recv = mut.func.value.id
                        if recv not in bound:
                            yield Finding(
                                self.id, fn.relpath, mut.lineno,
                                f"{recv}.{mut.func.attr}(...) inside {label} "
                                f"({reason}) mutates a captured container — "
                                "the side effect happens once at trace time "
                                "and never again on replay",
                            )
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                why = _IMPURE_CALLS.get(name or "")
                if why is None and name is not None:
                    terminal = name.rsplit(".", 1)[-1]
                    if (
                        name.startswith("random.")
                        and terminal not in _PY_RANDOM_EXEMPT
                    ):
                        why = (
                            "the global Python RNG draws once at trace time; "
                            "thread a jax.random key instead"
                        )
                    elif (
                        name.startswith(("np.random.", "numpy.random."))
                        and terminal not in _NP_RANDOM_EXEMPT
                    ):
                        why = (
                            "the numpy RNG draws once at trace time; "
                            "thread a jax.random key instead"
                        )
                if why is not None:
                    yield Finding(
                        self.id, fn.relpath, node.lineno,
                        f"{name}(...) inside {label} ({reason}) — {why}",
                    )


# --------------------------------------------------------- trace-host-sync

# runtime pipeline stages on the loader hot path: a device sync here stalls
# the decode/prefetch pipeline behind the accelerator
_LOADER_HOT_PATH = (
    "data/jax_iter.py",
    "runtime/pipeline.py",
    "io/reader.py",
    "io/streaming_merge.py",
)

_HOST_SYNC_RECEIVER_SINKS = frozenset(
    {"item", "tolist", "block_until_ready", "__array__"}
)


def _host_sync_config():
    from lakesoul_tpu.analysis.dataflow import TaintConfig

    return TaintConfig(
        source_self_attrs=frozenset(),
        sanitizers=frozenset({"len"}),
        sanitizer_prefixes=(),
        sink_functions={"float": 0, "int": 0, "bool": 0},
        sink_calls={
            "np.asarray": 0, "numpy.asarray": 0,
            "np.array": 0, "numpy.array": 0,
        },
        receiver_sinks=_HOST_SYNC_RECEIVER_SINKS,
        attr_sanitizers=frozenset({"shape", "dtype", "ndim", "size", "sharding"}),
        propagate_all_calls=True,
    )


class TraceHostSyncRule(Rule):
    id = "trace-host-sync"
    title = "host sync / device→host transfer inside traced code or a loader stage"

    def __init__(self, hot_path: tuple[str, ...] = _LOADER_HOT_PATH):
        self.hot_path = hot_path

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(module.relpath.endswith(m) for m in self.hot_path):
            return
        for node in module.walk():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                yield Finding(
                    self.id, module.relpath, node.lineno,
                    "block_until_ready() on the loader hot path stalls the "
                    "host pipeline behind the device — double-buffered "
                    "device_put already overlaps the transfer",
                )

    def finalize(self, project: Project) -> Iterable[Finding]:
        from lakesoul_tpu.analysis.dataflow import TaintAnalysis

        graph = project.callgraph()
        idx = device_index(project)
        analysis = TaintAnalysis(graph, _host_sync_config())
        seen: set[tuple] = set()
        for q in sorted(set(idx.jit_entries) | idx.pallas_kernels):
            fn = graph.functions.get(q)
            if fn is None:
                continue
            static_names, static_nums, _ = idx.jit_entries.get(
                q, (frozenset(), frozenset(), 0)
            )
            static = set(static_names) | {
                fn.params[i] for i in static_nums if i < len(fn.params)
            }
            tainted = frozenset(
                p for p in fn.params
                if p not in static and p not in ("self", "cls")
            )
            for hit in analysis.analyze_entry(q, tainted):
                key = (hit.relpath, hit.line, hit.sink)
                if key in seen:
                    continue
                seen.add(key)
                rendered = (
                    f"{hit.sink}()"
                    if hit.sink.rsplit(".", 1)[-1] in _HOST_SYNC_RECEIVER_SINKS
                    else f"{hit.sink}({hit.source_desc})"
                )
                yield Finding(
                    self.id, hit.relpath, hit.line,
                    f"{rendered} forces a device→host sync inside traced "
                    f"code (entry {_finding_fn_label(q)}) — a traced value "
                    "cannot be concretized; keep the op in jnp or hoist it "
                    "to the host wrapper",
                )


# --------------------------------------------------------- tpu-dtype-width

_WIDE_DTYPE_ATTRS = {"float64", "int64", "uint64", "complex128"}
_WIDE_DTYPE_STRINGS = {"float64", "int64", "uint64", "complex128"}
_DTYPE_RECEIVERS = ("np", "numpy", "jnp", "jax.numpy")

# the device-path modules whose host code feeds jit boundaries
DEVICE_MODULE_SCOPE = (
    "vector/kernels.py", "vector/kmeans.py", "vector/rabitq.py",
    "vector/index.py", "vector/builder.py", "vector/serving.py",
    "parallel/ring_attention.py", "parallel/ulysses.py",
    "parallel/pipeline.py", "parallel/moe.py", "parallel/mesh.py",
    "models/bert.py", "models/mlp.py", "models/resnet.py",
    "models/train.py", "models/checkpoint.py",
    "data/jax_iter.py",
)


def _is_wide_dtype_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute) and node.attr in _WIDE_DTYPE_ATTRS:
        recv = dotted_name(node.value)
        return recv in _DTYPE_RECEIVERS
    if isinstance(node, ast.Constant) and node.value in _WIDE_DTYPE_STRINGS:
        return True
    return False


def _call_has_wide_dtype(call: ast.Call, name: "str | None") -> bool:
    terminal = (name or "").rsplit(".", 1)[-1]
    if terminal in _WIDE_DTYPE_ATTRS and (name or "").rsplit(".", 1)[0] in \
            _DTYPE_RECEIVERS:
        return True  # np.float64(x) constructor
    for kw in call.keywords:
        if kw.arg == "dtype" and _is_wide_dtype_expr(kw.value):
            return True
    # positional dtype conventions: astype(t), np.asarray(x, t),
    # np.zeros/ones/empty/full/arange(..., t)
    if terminal == "astype" and call.args:
        return _is_wide_dtype_expr(call.args[0])
    if terminal in {"asarray", "array", "zeros", "ones", "empty", "arange",
                    "full"} and len(call.args) >= 2:
        return _is_wide_dtype_expr(call.args[-1])
    return False


_DEVICE_BOUNDARY_SINKS = {
    "jax.device_put": 0, "device_put": 0,
    "jnp.asarray": 0, "jnp.array": 0,
    "jax.numpy.asarray": 0, "jax.numpy.array": 0,
}


class TpuDtypeWidthRule(Rule):
    id = "tpu-dtype-width"
    title = "64-bit dtype flowing into a jit/device boundary (TPU demotes silently)"

    def __init__(self, scope: tuple[str, ...] = DEVICE_MODULE_SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        from lakesoul_tpu.analysis.dataflow import TaintAnalysis, TaintConfig

        graph = project.callgraph()
        idx = device_index(project)

        # (a) direct: a 64-bit dtype named inside traced code is always a
        # demotion (or an x64-flag landmine) on TPU — traced code is device
        # code wherever it lives, so this half ignores the module scope
        for q in sorted(idx.traced):
            fn = graph.functions[q]
            for node in walk_stopping_at_functions(fn.node.body):
                if isinstance(node, ast.Attribute) and _is_wide_dtype_expr(node):
                    yield Finding(
                        self.id, fn.relpath, node.lineno,
                        f"{dotted_name(node)} inside traced "
                        f"{_finding_fn_label(q)} — TPU has no 64-bit lanes; "
                        "the value silently demotes (or flips on "
                        "jax_enable_x64); pick the 32-bit dtype explicitly",
                    )

        # (b) host flow: a 64-bit-typed value built on the host and handed
        # across a device boundary (device_put / jnp.asarray / a jit entry)
        entry_names = frozenset(
            _finding_fn_label(q).rsplit(".", 1)[-1] for q in idx.jit_entries
        )
        config = TaintConfig(
            source_self_attrs=frozenset(),
            sanitizers=frozenset(),
            sanitizer_prefixes=(),
            sink_calls=dict(_DEVICE_BOUNDARY_SINKS),
            sink_all_args_names=entry_names,
            attr_sanitizers=frozenset({"shape", "ndim"}),
            source_call_predicate=_call_has_wide_dtype,
        )
        analysis = TaintAnalysis(graph, config)
        seen: set[tuple] = set()
        for hit in analysis.run(self.scope):
            key = (hit.relpath, hit.line, hit.sink)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                self.id, hit.relpath, hit.line,
                f"64-bit value ({hit.source_desc}) reaches the device "
                f"boundary at {hit.sink}(...) — TPU silently demotes to "
                "32 bits; convert with an explicit 32-bit dtype on the host",
            )

        # (c) promoting literals: a Python int too wide for int32 at a
        # device boundary overflows after the silent demotion
        for mod in project.modules:
            if not any(mod.relpath.endswith(s) for s in self.scope):
                continue
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name not in _DEVICE_BOUNDARY_SINKS:
                    continue
                for arg in node.args[:1]:
                    if (
                        isinstance(arg, ast.Constant)
                        and isinstance(arg.value, int)
                        and not isinstance(arg.value, bool)
                        and abs(arg.value) > 2**31 - 1
                    ):
                        yield Finding(
                            self.id, mod.relpath, node.lineno,
                            f"integer literal {arg.value} at {name}(...) "
                            "does not fit int32 — TPU demotes 64-bit ints "
                            "and the value wraps",
                        )


# ----------------------------------------------------- jit-static-arg-shape

def _is_const_int_expr(node: ast.expr) -> bool:
    """Literal bound: a constant, a signed constant (``-1``), or arithmetic
    over constants (``2 * K`` is NOT — K is a name) — a fixed slice offset
    compiles exactly once and must not be called data-dependent."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_const_int_expr(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_const_int_expr(node.left) and _is_const_int_expr(node.right)
    return False


_DYNAMIC_SHAPE_CALLS = {
    "nonzero": "returns a data-dependent number of indices",
    "flatnonzero": "returns a data-dependent number of indices",
    "argwhere": "returns a data-dependent number of rows",
    "unique": "returns a data-dependent number of elements",
}


class JitStaticArgShapeRule(Rule):
    id = "jit-static-arg-shape"
    title = "data-dependent shape under jit / static_argnames mismatch"

    def finalize(self, project: Project) -> Iterable[Finding]:
        graph = project.callgraph()
        idx = device_index(project)

        # (a) static_argnames/static_argnums must name real parameters —
        # a typo silently traces the arg dynamic and retraces per value
        for q, (names, nums, line) in sorted(idx.jit_entries.items()):
            fn = graph.functions.get(q)
            if fn is None:
                continue
            params = fn.params
            for n in sorted(names):
                if n not in params:
                    yield Finding(
                        self.id, fn.relpath, line,
                        f"static_argnames names {n!r} but "
                        f"{_finding_fn_label(q)} has no such parameter — "
                        "the intended static arg traces dynamic and "
                        "retraces per value",
                    )
            n_pos = len(params)
            for n in sorted(nums):
                if n >= n_pos:
                    yield Finding(
                        self.id, fn.relpath, line,
                        f"static_argnums includes {n} but "
                        f"{_finding_fn_label(q)} takes only {n_pos} "
                        "parameters",
                    )

        # (b) data-dependent shapes inside traced code
        for q in sorted(idx.traced):
            fn = graph.functions[q]
            for node in walk_stopping_at_functions(fn.node.body):
                if isinstance(node, ast.Subscript) and isinstance(
                    node.slice, (ast.Compare, ast.BoolOp)
                ):
                    yield Finding(
                        self.id, fn.relpath, node.lineno,
                        f"boolean-mask indexing inside traced "
                        f"{_finding_fn_label(q)} — the result shape depends "
                        "on the data; use jnp.where(mask, x, fill) or a "
                        "fixed-size gather",
                    )
                elif isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    terminal = name.rsplit(".", 1)[-1]
                    why = _DYNAMIC_SHAPE_CALLS.get(terminal)
                    if why is not None and name.startswith(("jnp.", "jax.numpy.")):
                        if any(kw.arg == "size" for kw in node.keywords):
                            continue  # size= pins the output shape
                        yield Finding(
                            self.id, fn.relpath, node.lineno,
                            f"{name}(...) inside traced "
                            f"{_finding_fn_label(q)} {why} — not traceable "
                            "without size=; pass size= or restructure",
                        )
                    elif terminal == "where" and name.startswith(
                        ("jnp.", "jax.numpy.")
                    ) and len(node.args) == 1:
                        yield Finding(
                            self.id, fn.relpath, node.lineno,
                            f"single-argument jnp.where inside traced "
                            f"{_finding_fn_label(q)} returns data-dependent "
                            "indices — use the 3-argument form",
                        )

        # (c) data-dependent slice handed straight to a jit entry: every
        # distinct length is a fresh compilation (the pow2-bucket discipline
        # exists to prevent exactly this)
        for caller_q, edges in graph.edges.items():
            if caller_q in idx.traced:
                continue  # inside a trace, slices of traced values differ
            caller_rel = caller_q.split("::", 1)[0]
            for e in edges:
                if e.callee not in idx.jit_entries:
                    continue
                for arg in list(e.node.args) + [
                    kw.value for kw in e.node.keywords
                ]:
                    if not (
                        isinstance(arg, ast.Subscript)
                        and isinstance(arg.slice, ast.Slice)
                    ):
                        continue
                    bounds = (arg.slice.lower, arg.slice.upper)
                    if any(
                        b is not None and not _is_const_int_expr(b)
                        for b in bounds
                    ):
                        yield Finding(
                            self.id, caller_rel, e.line,
                            f"data-dependent slice passed to jit entry "
                            f"{e.raw}(...) — every distinct length compiles "
                            "fresh; pad to a bucketed size before the call",
                        )


# --------------------------------------------------------- pallas-blockspec

_VMEM_BUDGET_BYTES = 16 * 1024 * 1024  # per-core VMEM; blocks must fit

# pallas primitives that write their first ref argument — as much "the
# kernel writes this ref" as a subscript store is
_REF_STORE_CALLS = {
    "store", "swap", "atomic_add", "atomic_max", "atomic_min", "atomic_and",
    "atomic_or", "atomic_xor", "atomic_xchg", "atomic_cas",
}


def _literal_tuple(node: "ast.expr | None") -> "list | None":
    """Tuple/List of int constants → python list; else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return out
    return None


class _BlockSpecInfo:
    def __init__(self, call: ast.Call):
        self.call = call
        self.shape_node = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "block_shape":
                self.shape_node = kw.value
        self.index_map = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Lambda):
            self.index_map = call.args[1]
        for kw in call.keywords:
            if kw.arg == "index_map" and isinstance(kw.value, ast.Lambda):
                self.index_map = kw.value

    @property
    def shape_rank(self) -> "int | None":
        if isinstance(self.shape_node, (ast.Tuple, ast.List)):
            return len(self.shape_node.elts)
        return None

    @property
    def literal_shape(self) -> "list | None":
        return _literal_tuple(self.shape_node)


def _iter_specs(node: "ast.expr | None") -> Iterator[ast.Call]:
    if node is None:
        return
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _iter_specs(e)
    elif isinstance(node, ast.Call) and isinstance(node.func, (ast.Attribute, ast.Name)):
        terminal = dotted_name(node.func) or ""
        if terminal.rsplit(".", 1)[-1] == "BlockSpec":
            yield node


class PallasBlockSpecRule(Rule):
    id = "pallas-blockspec"
    title = "pallas_call BlockSpec/grid/kernel-signature inconsistency"

    def check(self, module: Module) -> Iterable[Finding]:
        # module-level function defs, for kernel signature resolution
        top_defs = {
            s.name: s for s in module.tree.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name.rsplit(".", 1)[-1] != "pallas_call":
                continue
            yield from self._check_call(module, node, top_defs)

    def _check_call(self, module: Module, call: ast.Call, top_defs):
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        if "grid_spec" in kwargs:
            return  # PrefetchScalarGridSpec etc. — different contract
        grid = kwargs.get("grid")
        grid_rank = None
        if isinstance(grid, (ast.Tuple, ast.List)):
            grid_rank = len(grid.elts)
        elif grid is not None and (
            isinstance(grid, ast.Constant) or _is_const_int_expr(grid)
            or isinstance(grid, ast.BinOp)
        ):
            grid_rank = 1  # a scalar expression is rank 1 by construction
        # anything else (a name holding a tuple, a call) stays unknown:
        # literal-first, never guessed

        # grid element `A // B` with literal remainder drops rows silently
        if isinstance(grid, (ast.Tuple, ast.List)):
            for e in grid.elts:
                if (
                    isinstance(e, ast.BinOp)
                    and isinstance(e.op, ast.FloorDiv)
                    and isinstance(e.left, ast.Constant)
                    and isinstance(e.right, ast.Constant)
                    and isinstance(e.left.value, int)
                    and isinstance(e.right.value, int)
                    and e.right.value != 0
                    and e.left.value % e.right.value != 0
                ):
                    yield Finding(
                        self.id, module.relpath, e.lineno,
                        f"grid dimension {e.left.value} // {e.right.value} "
                        f"drops {e.left.value % e.right.value} trailing "
                        "rows — pad the operand or use a ceil-div grid",
                    )

        in_specs = list(_iter_specs(kwargs.get("in_specs")))
        out_specs_node = kwargs.get("out_specs")
        out_specs = list(_iter_specs(out_specs_node))
        # out_shape is pallas_call's second positional parameter, so accept
        # both spellings; only a literal shape (a ShapeDtypeStruct call or a
        # tuple of them) pins the output count — a name holding one stays
        # unknown and skips the arity checks
        out_shape = kwargs.get("out_shape")
        if out_shape is None and len(call.args) >= 2:
            out_shape = call.args[1]
        n_out = None
        if isinstance(out_shape, (ast.Tuple, ast.List)):
            n_out = len(out_shape.elts)
        elif isinstance(out_shape, ast.Call):
            n_out = 1

        for spec_call in in_specs + out_specs:
            info = _BlockSpecInfo(spec_call)
            if info.index_map is not None and grid_rank is not None:
                arity = len(info.index_map.args.args)
                if arity != grid_rank:
                    yield Finding(
                        self.id, module.relpath, spec_call.lineno,
                        f"BlockSpec index_map takes {arity} argument(s) but "
                        f"the grid has rank {grid_rank} — one index per "
                        "grid dimension",
                    )
            if info.index_map is not None and info.shape_rank is not None:
                body = info.index_map.body
                ret_rank = len(body.elts) if isinstance(body, ast.Tuple) else 1
                if ret_rank != info.shape_rank:
                    yield Finding(
                        self.id, module.relpath, spec_call.lineno,
                        f"BlockSpec index_map returns {ret_rank} block "
                        f"coordinate(s) for a rank-{info.shape_rank} block "
                        "shape — one coordinate per block dimension",
                    )
            shape = info.literal_shape
            if shape:
                size = 4  # dtype unknown statically; assume 4-byte lanes
                for d in shape:
                    size *= max(d, 1)
                if size > _VMEM_BUDGET_BYTES:
                    yield Finding(
                        self.id, module.relpath, spec_call.lineno,
                        f"BlockSpec block {tuple(shape)} needs ~{size // (1 << 20)}"
                        " MiB of VMEM (≈16 MiB per core available) — tile "
                        "smaller",
                    )

        # kernel signature vs specs, and out-ref writes
        kernel_expr = call.args[0] if call.args else None
        target, partial_call = _unwrap_partial(kernel_expr) if kernel_expr \
            is not None else (None, None)
        kname = target.id if isinstance(target, ast.Name) else None
        kernel = top_defs.get(kname) if kname else None
        if (
            kernel is None
            or kernel.args.vararg is not None
            or not in_specs
            or n_out is None
        ):
            return
        n_pos = len(kernel.args.posonlyargs) + len(kernel.args.args)
        bound_pos = len(partial_call.args) - 1 if partial_call is not None else 0
        n_scratch = 0
        scratch = kwargs.get("scratch_shapes")
        if isinstance(scratch, (ast.Tuple, ast.List)):
            n_scratch = len(scratch.elts)
        elif scratch is not None:
            return  # scratch count unknowable: skip the arity check
        expected = len(in_specs) + n_out + n_scratch
        got = n_pos - bound_pos
        if got != expected:
            yield Finding(
                self.id, module.relpath, call.lineno,
                f"kernel {kname} takes {got} ref argument(s) but pallas_call "
                f"passes {len(in_specs)} in_spec(s) + {n_out} output(s)"
                + (f" + {n_scratch} scratch" if n_scratch else "")
                + f" = {expected} — refs and specs must line up 1:1",
            )
            return
        # the output refs sit between the inputs and the scratch refs
        # (pallas ref order: in, out, scratch): a kernel that never stores
        # into one returns garbage for that block
        all_params = [
            p.arg for p in (kernel.args.posonlyargs + kernel.args.args)
        ]
        out_params = all_params[n_pos - n_out - n_scratch: n_pos - n_scratch]
        stored: set[str] = set()
        for node in walk_stopping_at_functions(kernel.body):
            tgt_list = []
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgt_list = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
            for t in tgt_list:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Subscript) and isinstance(
                        sub.value, ast.Name
                    ):
                        stored.add(sub.value.id)
            # the store/atomic primitives write their first ref argument
            if (
                isinstance(node, ast.Call)
                and (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                in _REF_STORE_CALLS
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                stored.add(node.args[0].id)
        for p in out_params:
            if p not in stored:
                yield Finding(
                    self.id, module.relpath, kernel.lineno,
                    f"kernel {kname} never writes output ref {p!r} — the "
                    "output block is returned uninitialized",
                )
