"""Zero-copy buffer-lifetime rules for the PR-8 collate machinery.

The scan path's speed comes from *borrowing*: ``_np_column_views`` hands
out numpy views over Arrow batch buffers, and the opt-in
``LAKESOUL_COLLATE_REUSE`` ring hands out output-buffer sets that are
**overwritten in place** once the ring wraps.  Both are only sound inside
a window discipline — a view travels with the batch that owns its bytes,
and a ring slot is dead the moment the ring wraps back to it.  Nothing
type-checks that discipline, and a violation is not a crash but silently
corrupt training data.  Two rules pin it:

- ``view-escapes-release``: the result of ``_np_column_views(batch)`` or
  ``<ring>.next_slot()`` must stay inside the borrowing function's window:
  passing it as a call argument is the sanctioned hand-off
  (``window.collate(slot)``), and storing a *view* together with its
  owning batch in one tuple is the rebatcher's keep-alive idiom
  (``self._pending.append((b, views))``).  Everything else escapes the
  release point: storing a bare view/slot on ``self`` or into a
  container, returning it, or closing over it in a nested function — the
  borrower then outlives the slot and reads bytes a later window already
  overwrote.
- ``ring-aliasing``: every ``_BufferRing(...)`` construction must sit
  under a guard that either excludes ``cache='device'`` or consults the
  tensor plane's MEASURED aliasing probe
  (``delivery_copies(...)``/``device_put_copies(...)``,
  tensorplane/dlpack.py).  The device-resident epoch KEEPS every
  delivered batch, and an aliasing ``device_put`` borrows the host
  buffer — a ring under either condition would overwrite live data in
  place.  The probe is the sanctioned hand-off: when every column's put
  is a real copy, slot reuse cannot touch delivered (or cached) data, so
  a probe-guarded ring is sound on any backend.  The guard lives in one
  ``if`` today; this rule keeps any future ring construction honest.

The runtime half (``analysis/racecheck.py``) closes what the lexical
rules cannot see: its ring canary checks, at each slot hand-out, that no
borrower still holds the previous window's buffers, and poisons the slot
so a stale read is loud garbage instead of plausible data.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    enclosing_function_bodies,
    walk_stopping_at_functions,
)

# the zero-copy loader module the rules default-scope to; fixtures override
SCOPE = ("data/jax_iter.py",)

_VIEW_FACTORY = "_np_column_views"
_SLOT_METHOD = "next_slot"
_RING_CTOR = "_BufferRing"

# container methods a borrowed value must not be handed into
_STORE_METHODS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "put", "put_nowait",
}


def _tracked_call(value: ast.expr) -> "str | tuple[str, str | None] | None":
    """Classify an RHS: ``("view", source_name)`` for ``_np_column_views(x)``,
    ``("slot", None)`` for ``<ring>.next_slot()``, else None.  IfExp arms
    are checked too (``views = _np_column_views(b) if cap else None``)."""
    if isinstance(value, ast.IfExp):
        return _tracked_call(value.body) or _tracked_call(value.orelse)
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    terminal = (name or "").rsplit(".", 1)[-1]
    if terminal == _VIEW_FACTORY:
        src = value.args[0].id if (
            value.args and isinstance(value.args[0], ast.Name)
        ) else None
        return ("view", src)
    if isinstance(value.func, ast.Attribute) and value.func.attr == _SLOT_METHOD:
        return ("slot", None)
    return None


class ViewEscapesReleaseRule(Rule):
    id = "view-escapes-release"
    title = "borrowed view / ring slot escapes its release point"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(s in module.relpath for s in self.scope):
            return
        for _, body in enclosing_function_bodies(module.tree):
            nodes = list(walk_stopping_at_functions(body))
            views: dict[str, str | None] = {}  # name -> owning-batch name
            slots: set[str] = set()
            for node in nodes:
                if isinstance(node, ast.Assign):
                    kind = _tracked_call(node.value)
                    if kind is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            if kind[0] == "view":
                                views[t.id] = kind[1]
                            else:
                                slots.add(t.id)
            if not views and not slots:
                continue
            yield from self._scan_escapes(module, nodes, views, slots)

    # ------------------------------------------------------------- escapes
    def _borrowed(self, expr: ast.expr, views, slots) -> "tuple[str, str] | None":
        """``(kind, name)`` when ``expr`` hands a borrowed value onward
        WITHOUT its keep-alive: a bare tracked name, or a tuple/list that
        contains a tracked view but NOT the batch that owns its bytes
        (slots have no keep-alive — any containerized escape is a bug)."""
        if isinstance(expr, ast.Name):
            if expr.id in slots:
                return ("ring slot", expr.id)
            if expr.id in views:
                return ("view", expr.id)
            return None
        if isinstance(expr, (ast.Tuple, ast.List)):
            names = {e.id for e in expr.elts if isinstance(e, ast.Name)}
            for n in names & slots:
                return ("ring slot", n)
            for n in names & set(views):
                src = views[n]
                if src is None or src not in names:
                    return ("view", n)  # travelling without its batch
            return None
        return None

    def _scan_escapes(self, module, nodes, views, slots) -> Iterable[Finding]:
        def finding(line: int, kind: str, name: str, how: str) -> Finding:
            return Finding(
                self.id,
                module.relpath,
                line,
                f"{kind} {name!r} {how} — it escapes the release point: the "
                "borrower can outlive the window and read bytes a later "
                "window already overwrote (views must travel with their "
                "owning batch; ring slots must not outlive one collate)",
            )

        for node in nodes:
            if isinstance(node, ast.Assign):
                if _tracked_call(node.value) is not None:
                    continue  # the tracking assignment itself
                hit = self._borrowed(node.value, views, slots)
                if hit is not None:
                    kind, name = hit
                    target = node.targets[0]
                    if isinstance(target, ast.Name):
                        continue  # local rebind stays inside the window
                    yield finding(node.lineno, kind, name, "is stored")
            elif isinstance(node, ast.Return) and node.value is not None:
                hit = self._borrowed(node.value, views, slots)
                if hit is not None:
                    kind, name = hit
                    yield finding(node.lineno, kind, name, "is returned")
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr not in _STORE_METHODS:
                    continue
                for arg in node.args:
                    hit = self._borrowed(arg, views, slots)
                    if hit is not None:
                        kind, name = hit
                        yield finding(
                            node.lineno, kind, name,
                            f"is stored via .{node.func.attr}(...)",
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                captured = {
                    n.id for n in ast.walk(node)
                    if isinstance(n, ast.Name)
                } & (set(views) | slots)
                for name in sorted(captured):
                    kind = "ring slot" if name in slots else "view"
                    yield finding(
                        node.lineno, kind, name, "is closed over"
                    )


class RingAliasingRule(Rule):
    id = "ring-aliasing"
    title = "_BufferRing built without an aliasing guard"

    # guard calls that measure aliasing for real (tensorplane/dlpack.py):
    # a ring under `if delivery_copies(...)` only arms when every column's
    # device_put is a genuine copy, which is strictly safer than the
    # lexical cache!='device' exclusion
    _PROBE_GUARDS = frozenset({"delivery_copies", "device_put_copies"})

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(s in module.relpath for s in self.scope):
            return
        parents = module.parents()
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if (name or "").rsplit(".", 1)[-1] != _RING_CTOR:
                continue
            if self._aliasing_guarded(node, parents):
                continue
            yield Finding(
                self.id,
                module.relpath,
                node.lineno,
                "_BufferRing(...) constructed without an aliasing guard — "
                "either the cache='device' exclusion or the measured "
                "delivery_copies(...) probe: the device-resident epoch "
                "keeps every delivered batch and an aliasing device_put "
                "borrows host buffers, so an unguarded reuse ring would "
                "overwrite live data in place",
            )

    @classmethod
    def _aliasing_guarded(cls, call: ast.Call, parents) -> bool:
        prev: ast.AST = call
        node: ast.AST = call
        while node in parents:
            prev, node = node, parents[node]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            test = None
            if isinstance(node, (ast.If, ast.IfExp)):
                test = node.test
            if test is None:
                continue
            if any(
                isinstance(sub, ast.Constant) and sub.value == "device"
                for sub in ast.walk(test)
            ):
                return True
            # probe guard: only sanctioned when the probe's TRUTH selects
            # the ring — the ctor must sit in the if-BODY and the probe
            # call must not be negated; `if not delivery_copies(...):` (or
            # building the ring in the else branch) is the inverted-guard
            # bug this rule exists to catch, not a guard
            if cls._in_if_body(node, prev) and cls._unnegated_probe(test):
                return True
        return False

    @staticmethod
    def _in_if_body(branch: ast.AST, child: ast.AST) -> bool:
        if isinstance(branch, ast.If):
            return any(child is stmt for stmt in branch.body)
        if isinstance(branch, ast.IfExp):
            return child is branch.body
        return False

    @classmethod
    def _unnegated_probe(cls, test: ast.expr) -> bool:
        negated: set = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.UnaryOp) and isinstance(sub.op, ast.Not):
                negated.update(
                    n for n in ast.walk(sub.operand) if isinstance(n, ast.Call)
                )
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) and sub not in negated:
                name = dotted_name(sub.func)
                if name is not None and \
                        name.rsplit(".", 1)[-1] in cls._PROBE_GUARDS:
                    return True
        return False
