"""``unstoppable-loop``: a ``while True`` poll loop in a long-running
service layer must consult a stop event or deadline every iteration.

PR 12's freshness follower made the shutdown contract explicit: a
follower, worker or service poll loop that sleeps blind (``while True:
...; time.sleep(poll)``) can only be stopped by killing the process — a
fleet drain then leaks a whole poll interval per role, and a test that
forgets the kill hangs the suite.  The settled discipline (compaction
service, scan-plane worker, freshness follower): the idle wait rides the
stop event itself (``stop.wait(poll_interval)``) or the loop condition
consults it, so shutdown latency is bounded by ONE tick.

Scope: ``streaming/``, ``compaction/``, ``scanplane/``, ``freshness/`` —
the layers whose loops outlive a request.  A loop is flagged when it is
``while True:`` (or ``while 1:``), its body contains a blocking sleep
(``time.sleep`` / bare ``sleep``) — the poll-loop signature — and the
body (nested defs excluded) consults nothing that can end it:

- no ``.wait(...)`` / ``.is_set()`` call (event consult),
- no ``if``/``while`` test mentioning a stop/cancel/shutdown/deadline/
  stop-event-shaped identifier,
- no conditional ``raise`` (an attempt-budget loop that raises on
  exhaustion — the scan-plane client's reconnect loop — terminates under
  persistent failure and stays legal).

Data-drain loops without a sleep (``while True: rows = cur.fetchmany();
if not rows: break``) terminate with their input and are not poll loops.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

SCOPE = ("streaming/", "compaction/", "scanplane/", "freshness/")

_STOP_WORDS = ("stop", "cancel", "shutdown", "deadline", "closing", "done")
_CONSULT_ATTRS = ("wait", "is_set")


def _is_while_true(node: ast.While) -> bool:
    test = node.test
    return isinstance(test, ast.Constant) and test.value in (True, 1)


def _mentions_stop_word(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            low = name.lower()
            if any(w in low for w in _STOP_WORDS):
                return True
    return False


class UnstoppableLoopRule(Rule):
    id = "unstoppable-loop"
    title = "while-True poll loop never consults a stop event/deadline"

    def __init__(self, scope: tuple[str, ...] = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(s in module.relpath for s in self.scope):
            return
        for node in module.walk():
            if not (isinstance(node, ast.While) and _is_while_true(node)):
                continue
            sleeps = False
            consults = False
            for sub in walk_stopping_at_functions(node.body):
                if isinstance(sub, ast.Call):
                    dn = dotted_name(sub.func)
                    if dn in ("time.sleep", "sleep"):
                        sleeps = True
                    if (
                        isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _CONSULT_ATTRS
                    ):
                        consults = True
                elif isinstance(sub, (ast.If, ast.While)) and _mentions_stop_word(
                    sub.test
                ):
                    consults = True
                elif isinstance(sub, ast.Raise):
                    # a raise = attempt budget or hard failure: the loop
                    # ends under persistent failure (the scan-plane
                    # client's reconnect loop shape)
                    consults = True
            if sleeps and not consults:
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "while-True poll loop sleeps blind: consult a stop"
                    " event/deadline each iteration (idiom:"
                    " stop_event.wait(poll_interval) as the idle wait, or"
                    " a while-not-stop loop condition) so shutdown is"
                    " bounded by one tick",
                )
