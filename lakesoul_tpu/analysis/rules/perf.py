"""``hot-path-materialize``: intermediate table materializations are banned
in the scan/loader hot-path modules.

PR 8 closed the scan-path efficiency gap by deleting exactly these: the
rebatcher's ``pa.concat_tables`` per window (rebuilt a table of everything
buffered for every pop), the collate's per-column ``combine_chunks`` (a full
copy per window), and the general class of "make a big table so the next
line can slice it".  The zero-copy discipline that replaced them — chunk
slice descriptors, ``Table.from_batches`` over zero-copy slices, direct
view→buffer memcpys — only survives if new code can't quietly reintroduce a
materialization two PRs later.

Flagged calls, anywhere in the hot-path modules (``data/jax_iter.py``,
``io/reader.py``, ``io/streaming_merge.py``):

- ``concat_tables(...)`` (any qualification) — chunk-list concat is cheap,
  but every historical regression started as "just concat the pending
  tables"; the survivors are pragma'd with their zero-copy justification.
- ``.combine_chunks()`` — a full buffer copy of the receiver.
- ``.to_pandas()`` — a full copy *and* a pandas dependency on the hot path.

Sites that are allowed to materialize (a bounded remainder copy that unpins
decoded parents, a zero-copy chunk-list append) carry an inline
``# lakelint: ignore[hot-path-materialize] <reason>`` pragma, so every
exception is justified in place.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

SCOPE = ("data/jax_iter.py", "io/reader.py", "io/streaming_merge.py")

_METHODS = ("combine_chunks", "to_pandas")


class HotPathMaterializeRule(Rule):
    id = "hot-path-materialize"
    title = "intermediate table materialization in the scan/loader hot path"

    def __init__(self, scope: tuple[str, ...] = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(module.relpath.endswith(s) for s in self.scope):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = None
            name = dotted_name(node.func)
            if name is not None and name.split(".")[-1] == "concat_tables":
                callee = "concat_tables()"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS
            ):
                callee = f".{node.func.attr}()"
            if callee is None:
                continue
            yield Finding(
                self.id,
                module.relpath,
                node.lineno,
                f"{callee} materializes an intermediate table in the"
                " scan/loader hot path — use zero-copy chunk slices"
                " (Table.from_batches over slices, window descriptors,"
                " view→buffer copies) or move the copy off the hot path;"
                " a justified exception needs an inline pragma",
            )
