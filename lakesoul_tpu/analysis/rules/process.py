"""``raw-process``: ad-hoc process management and raw socket servers are
banned outside the process-topology layers.

PR 11 made multi-process a first-class deployment shape: the scan plane
(``scanplane/``) spawns and supervises worker processes, leases serialize
their work, and spool publication makes their crashes recoverable.  That
machinery only holds if process creation stays INSIDE the layers built for
it — a stray ``subprocess.Popen`` in a data-path module is a child nobody
reaps, SIGKILLs, or fences; a hand-rolled ``multiprocessing.Pool`` brings
back the fork-safety and nested-pool hazards ``runtime/pool.py`` exists to
contain; an ad-hoc ``ThreadingHTTPServer`` is a serving surface with no
admission control, no RBAC, and no metrics.

Allowed homes:

- ``scanplane/`` — the process-topology layer itself (worker children,
  supervised spawning);
- ``fleet/`` — the fleet plane (the autoscaler spawns and supervises
  scanplane worker children under its lease);
- ``runtime/`` — the execution runtime (owns parallelism policy);
- the existing serving entries: ``obs/exporter.py`` (the /metrics HTTP
  endpoint) and ``service/storage_proxy.py`` (the storage-proxy HTTP
  server).

Everything else needs an inline pragma naming why (e.g. the native
build's one-shot compiler invocation, the git-diff helper shelling out to
git) — process creation should be loud in review.

Three shapes are flagged:

- ``subprocess`` process creation (``Popen``/``run``/``call``/
  ``check_call``/``check_output``, dotted or from-imported) plus
  ``os.fork``/``os.system``/``os.spawn*``/``os.exec*``;
- any use of ``multiprocessing`` (its Process/Pool/shared memory all
  bypass the topology layer's supervision), flagged at the import;
- raw socket *servers*: ``socketserver.*Server`` / ``*HTTPServer``
  construction, ``socket.create_server``, and ``socket.socket`` whose
  enclosing function also calls ``.listen(...)`` (a bare client socket —
  connect-and-talk — stays legal); serving sockets belong behind the
  Flight gateway or the sanctioned HTTP entries.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

# module-path fragments where process/socket primitives are legitimate
_ALLOWED = (
    "/scanplane/",
    "/fleet/",
    "/runtime/",
    "obs/exporter.py",
    "service/storage_proxy.py",
)

_SUBPROCESS_CALLS = {
    "subprocess.Popen", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}
# bare names that from-imports commonly bind; only flagged when the module
# imports them FROM subprocess (tracked below)
_SUBPROCESS_NAMES = {"Popen", "run", "call", "check_call", "check_output"}

_OS_PROCESS_CALLS = {"os.fork", "os.forkpty", "os.system"}
_OS_PROCESS_PREFIXES = ("os.spawn", "os.exec", "os.posix_spawn")

_SERVER_CALLS = {"socket.create_server"}
_SERVER_SUFFIXES = ("HTTPServer", "TCPServer", "UDPServer", "UnixStreamServer")


def _is_server_ctor(name: str) -> bool:
    if name in _SERVER_CALLS:
        return True
    last = name.rsplit(".", 1)[-1]
    # class-shaped names ending in a server suffix: HTTPServer,
    # ThreadingHTTPServer, socketserver.TCPServer, ...
    return bool(last) and last[0].isupper() and any(
        last.endswith(s) for s in _SERVER_SUFFIXES
    )


def _function_listens(module: Module, node: ast.AST) -> bool:
    """Whether the function lexically enclosing ``node`` calls
    ``.listen(...)`` anywhere — the serving half of a raw socket; a
    client socket (connect-and-talk) never listens."""
    parents = module.parents()
    fn = node
    while fn is not None and not isinstance(
        fn, (ast.FunctionDef, ast.AsyncFunctionDef)
    ):
        fn = parents.get(fn)
    scope = fn if fn is not None else module.tree
    for sub in ast.walk(scope):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "listen"
        ):
            return True
    return False


class RawProcessRule(Rule):
    id = "raw-process"
    title = (
        "ad-hoc subprocess/multiprocessing/socket server outside the"
        " process-topology layers"
    )

    def __init__(self, allowed: tuple[str, ...] = _ALLOWED):
        self.allowed = allowed

    def check(self, module: Module) -> Iterable[Finding]:
        rel = module.relpath
        if any(a in rel for a in self.allowed):
            return
        from_subprocess: set[str] = set()
        for node in module.walk():
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                yield from self._check_import(module, node, from_subprocess)
            elif isinstance(node, ast.Call):
                yield from self._check_call(module, node, from_subprocess)

    def _check_import(self, module, node, from_subprocess) -> Iterable[Finding]:
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".", 1)[0]
                if root == "multiprocessing":
                    yield Finding(
                        self.id, module.relpath, node.lineno,
                        "multiprocessing bypasses the scan-plane/runtime "
                        "process topology (supervised spawning, leases, "
                        "fork safety); spawn real service entries instead",
                    )
        else:  # ImportFrom
            mod = node.module or ""
            root = mod.split(".", 1)[0]
            if root == "multiprocessing":
                yield Finding(
                    self.id, module.relpath, node.lineno,
                    "multiprocessing bypasses the scan-plane/runtime "
                    "process topology (supervised spawning, leases, fork "
                    "safety); spawn real service entries instead",
                )
            elif root == "subprocess":
                for alias in node.names:
                    if alias.name in _SUBPROCESS_NAMES:
                        from_subprocess.add(alias.asname or alias.name)

    def _check_call(self, module, node, from_subprocess) -> Iterable[Finding]:
        name = dotted_name(node.func)
        if name is None:
            return
        if name.startswith("multiprocessing."):
            yield Finding(
                self.id, module.relpath, node.lineno,
                f"{name}(...) bypasses the scan-plane/runtime process "
                "topology (supervised spawning, leases, fork safety); "
                "spawn real service entries instead",
            )
        elif name in _SUBPROCESS_CALLS or name in from_subprocess:
            yield Finding(
                self.id, module.relpath, node.lineno,
                f"{name}(...) creates an unsupervised child process; "
                "process spawning lives in scanplane//runtime/ (leased, "
                "reaped, chaos-tested) — or justify with a pragma",
            )
        elif name in _OS_PROCESS_CALLS or any(
            name.startswith(p) for p in _OS_PROCESS_PREFIXES
        ):
            yield Finding(
                self.id, module.relpath, node.lineno,
                f"{name}(...) forks/execs outside the process-topology "
                "layers; route through a supervised service entry",
            )
        elif _is_server_ctor(name) or (
            name == "socket.socket" and _function_listens(module, node)
        ):
            yield Finding(
                self.id, module.relpath, node.lineno,
                f"{name}(...) opens a raw serving socket with no admission "
                "control/RBAC/metrics; serve through the Flight gateway or "
                "the sanctioned HTTP entries (obs/exporter.py, "
                "service/storage_proxy.py)",
            )
