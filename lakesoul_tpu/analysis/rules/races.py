"""Shared-state race rules — the Eraser lockset discipline, statically.

PRs 6–8 made the stack aggressively concurrent: heartbeat and pump
threads, pool tasks, admission-gated handler threads.  Nothing before
this module machine-checked the one invariant that keeps all of that
coherent: **a field touched from two thread roots holds one consistent
lock**.  Two rules enforce it, both over the shared thread-root index
(:mod:`~lakesoul_tpu.analysis.threadroots`) and one per-class access
index built once per run:

- ``shared-state-race`` (Eraser's lockset algorithm, lexically): for each
  class, every method's ``self.<field>`` writes (rebinds, ``+=``,
  subscript stores, and container-mutator calls like ``.append``) are
  collected with the set of locks lexically held at the access.  A field
  written from ≥ 2 distinct thread roots whose write locksets intersect
  to ∅ is a race: two threads can interleave mid-update and the field's
  value silently corrupts — the reproducibility killer class (arxiv
  2604.21275) the runtime racecheck hunts dynamically.
- ``racy-check-then-act``: an ``if``/``while`` whose test reads a shared
  mutable container field and whose body mutates it, with no lock held —
  the TOCTOU shape (``if len(self.q) < cap: self.q.append(...)``) that is
  racy even when every individual operation is GIL-atomic.

What counts as "a lock held": ``with self.<attr>:`` where ``<attr>`` was
assigned a ``Lock``/``RLock``/``Condition``/``Semaphore`` anywhere in the
class (a ``Condition(self._mu)`` aliases to ``_mu`` — the wrapped lock IS
the condition's lock, so ``with self._cv:`` and ``with self._mu:`` agree),
``with <module-level lock>:``, or any ``with`` expression whose terminal
name looks lock-shaped (``*lock*``/``*guard*``/``*mutex*``) — the same
heuristic family as ``lock-held-call``.

Known limits, on purpose (low false positives over completeness):
``__init__`` writes are the init phase (Eraser's Virgin→Exclusive states —
construction happens-before publication); nested-function bodies belong to
their own node, so a closure's writes are the runtime detector's job; and
only *resolved* call edges propagate roots, so dynamically dispatched
paths under-report rather than spray.  Fields whose single-writer
invariant is load-bearing carry an inline pragma naming it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Project, Rule, dotted_name
from lakesoul_tpu.analysis.threadroots import ThreadRootIndex, thread_roots

# the package scope the repo gate runs with; fixtures override
SCOPE = ("lakesoul_tpu/",)

# terminal callable names whose result is a lock-ish synchronizer
_LOCK_CTOR_TERMINALS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}

# method calls that mutate their receiver container in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "remove",
    "clear", "add", "discard", "update", "setdefault", "sort", "reverse",
}

_LOCKISH_NAME_HINTS = ("lock", "guard", "mutex")


@dataclass(frozen=True)
class _Access:
    method: str  # method qname
    terminal: str  # method name as written ("submit")
    attr: str
    kind: str  # "write" | "mutate" | "read"
    line: int
    locks: frozenset
    roots: frozenset


@dataclass(frozen=True)
class _Check:
    """One if/while whose test reads ``attr`` and whose body mutates it."""

    method: str
    terminal: str
    attr: str
    line: int
    locks: frozenset


@dataclass
class _ClassAccesses:
    qname: str
    relpath: str
    name: str
    lock_attrs: set
    container_attrs: set  # attrs the class binds to builtin containers
    accesses: list  # [_Access]
    checks: list  # [_Check]


def _lockish_terminal(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _LOCKISH_NAME_HINTS)


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_ctor_call(value: ast.expr) -> "tuple[bool, str | None]":
    """``(is lock ctor, aliased self attr)`` for an assignment's RHS.
    ``threading.Condition(self._mu)`` aliases to ``_mu`` — the condition
    *wraps* that lock, it does not introduce a second one."""
    if not isinstance(value, ast.Call):
        return False, None
    name = dotted_name(value.func)
    if name is None:
        return False, None
    terminal = name.rsplit(".", 1)[-1]
    if terminal not in _LOCK_CTOR_TERMINALS and not (
        terminal.lower().endswith("lock") and terminal[:1].isupper()
    ):
        return False, None
    alias = None
    if terminal == "Condition" and value.args:
        alias = _self_attr(value.args[0])
    return True, alias


_CONTAINER_CTOR_TERMINALS = {
    "list", "dict", "set", "deque", "OrderedDict", "defaultdict", "Counter",
}


def _is_container_ctor(value: ast.expr) -> bool:
    """RHS shapes that make an attribute a builtin mutable container — the
    precondition for reading ``.add``/``.update``/… as a *container*
    mutation rather than a domain method on a thread-safe object
    (``self.metrics.add(...)`` must not count)."""
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        return (name or "").rsplit(".", 1)[-1] in _CONTAINER_CTOR_TERMINALS
    return False


def _module_locks(module) -> set:
    """Module-level names bound to lock constructors (``_POOL_LOCK = …``)."""
    out = set()
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign):
            is_lock, _ = _lock_ctor_call(stmt.value)
            if is_lock:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


class _MethodWalker:
    """Collect field accesses (+ check-then-act shapes) in one method body
    with the lexically-held lock tokens at each point.  Nested function
    bodies are skipped — their code runs outside this lock context."""

    def __init__(self, cls: _ClassAccesses, aliases: dict, mod_locks: set,
                 fn, roots: frozenset):
        self.cls = cls
        self.aliases = aliases
        self.mod_locks = mod_locks
        self.fn = fn
        self.roots = roots
        self.terminal = fn.name.rsplit(".", 1)[-1]

    # ----------------------------------------------------------- lock tokens
    def _canonical(self, attr: str) -> str:
        seen = set()
        while attr in self.aliases and attr not in seen:
            seen.add(attr)
            attr = self.aliases[attr]
        return attr

    def _lock_token(self, expr: ast.expr) -> str | None:
        attr = _self_attr(expr)
        if attr is not None:
            if attr in self.cls.lock_attrs:
                return f"self.{self._canonical(attr)}"
            if _lockish_terminal(attr):
                return f"self.{attr}"
            return None
        name = dotted_name(expr)
        if name is None:
            return None
        terminal = name.rsplit(".", 1)[-1]
        if terminal in self.mod_locks or _lockish_terminal(terminal):
            return name
        return None

    # --------------------------------------------------------------- walking
    def walk(self) -> None:
        for stmt in self.fn.node.body:
            self._visit(stmt, frozenset())

    def _record(self, attr: str, kind: str, line: int, held: frozenset) -> None:
        if attr.startswith("__") or attr in self.cls.lock_attrs:
            return
        self.cls.accesses.append(_Access(
            self.fn.qname, self.terminal, attr, kind, line, held, self.roots,
        ))

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            tokens = set(held)
            for item in node.items:
                self._visit(item.context_expr, held)
                tok = self._lock_token(item.context_expr)
                if tok is not None:
                    tokens.add(tok)
            new = frozenset(tokens)
            for stmt in node.body:
                self._visit(stmt, new)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._visit(node.test, held)
            read = self._attrs_read(node.test)
            mutated = self._attrs_mutated_in(node.body)
            for attr in read & mutated:
                if not held and not attr.startswith("__"):
                    self.cls.checks.append(_Check(
                        self.fn.qname, self.terminal, attr, node.lineno, held,
                    ))
            for stmt in node.body:
                self._visit(stmt, held)
            for stmt in getattr(node, "orelse", []):
                self._visit(stmt, held)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                kind = (
                    "write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                self._record(attr, kind, node.lineno, held)
            self._visit(node.value, held)
            return
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._record(attr, "mutate", node.lineno, held)
            for child in ast.iter_child_nodes(node):
                self._visit(child, held)
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr is not None and attr in self.cls.container_attrs:
                    self._record(attr, "mutate", node.lineno, held)
            for child in ast.iter_child_nodes(node):
                self._visit(child, held)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    # ----------------------------------------------- check-then-act helpers
    def _attrs_read(self, test: ast.expr) -> set:
        out = set()
        for sub in ast.walk(test):
            attr = _self_attr(sub)
            if attr is not None:
                out.add(attr)
        return out

    def _attrs_mutated_in(self, body: list) -> set:
        out = set()
        stack: list[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested defs run elsewhere
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                self._lock_token(item.context_expr) is not None
                for item in node.items
            ):
                continue  # the act happens under a lock — not the TOCTOU
                # shape; non-lock context managers (open(), suppress())
                # don't shield their bodies
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _MUTATORS:
                    attr = _self_attr(node.func.value)
                    if attr is not None and attr in self.cls.container_attrs:
                        out.add(attr)
            if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr is not None:
                    out.add(attr)
            if isinstance(node, ast.Attribute) and isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node)
                if attr is not None:
                    out.add(attr)
            stack.extend(ast.iter_child_nodes(node))
        return out


def _class_index(project: Project, scope: tuple) -> "dict[str, _ClassAccesses]":
    """Per-class access index, built once per (project, scope) and shared by
    both rules (the walk over every method is the expensive half)."""
    cache = project._race_index
    if cache is None:
        cache = project._race_index = {}
    hit = cache.get(scope)
    if hit is not None:
        return hit

    graph = project.callgraph()
    idx: ThreadRootIndex = thread_roots(project)
    mod_locks_by_rel = {
        m.relpath: _module_locks(m) for m in project.modules
    }
    out: dict[str, _ClassAccesses] = {}
    for cq, cls in graph.classes.items():
        if not any(s in cls.relpath for s in scope):
            continue
        # lock attributes + condition aliases, over every method (usually
        # __init__, but lazily-created locks count too)
        lock_attrs: set = set()
        aliases: dict = {}
        container_attrs: set = set()
        for mq in cls.methods.values():
            fn = graph.functions[mq]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                is_lock, alias = _lock_ctor_call(node.value)
                is_container = _is_container_ctor(node.value)
                if not is_lock and not is_container:
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if is_lock:
                        lock_attrs.add(attr)
                        if alias is not None:
                            aliases[attr] = alias
                    else:
                        container_attrs.add(attr)
        acc = _ClassAccesses(
            cq, cls.relpath, cls.name, lock_attrs, container_attrs, [], []
        )
        for mname, mq in cls.methods.items():
            if mname == "__init__":
                continue  # init phase: construction happens-before publication
            fn = graph.functions[mq]
            _MethodWalker(
                acc, aliases, mod_locks_by_rel.get(cls.relpath, set()),
                fn, idx.roots_of(mq),
            ).walk()
        out[cq] = acc
    cache[scope] = out
    return out


def _render_roots(roots: Iterable[str]) -> str:
    return ", ".join(sorted(ThreadRootIndex.render(r) for r in roots))


class SharedStateRaceRule(Rule):
    id = "shared-state-race"
    title = "field written from ≥2 thread roots with no common lock"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        for cls in _class_index(project, self.scope).values():
            by_field: dict[str, list[_Access]] = {}
            for a in cls.accesses:
                by_field.setdefault(a.attr, []).append(a)
            for attr, accs in sorted(by_field.items()):
                writes = [a for a in accs if a.kind in ("write", "mutate")]
                if not writes:
                    continue
                write_roots = frozenset().union(*(a.roots for a in writes))
                if len(write_roots) < 2:
                    continue
                lockset = writes[0].locks
                for a in writes[1:]:
                    lockset &= a.locks
                if lockset:
                    continue
                anchor = min(
                    (a for a in writes if not a.locks),
                    key=lambda a: a.line,
                    default=min(writes, key=lambda a: a.line),
                )
                methods = ", ".join(sorted({a.terminal for a in writes}))
                yield Finding(
                    self.id,
                    cls.relpath,
                    anchor.line,
                    f"field self.{attr} of {cls.name} is written from "
                    f"{len(write_roots)} thread roots "
                    f"({_render_roots(write_roots)}) via {methods} with no "
                    "common lock — interleaved updates silently corrupt it; "
                    "hold one lock at every write or make the field "
                    "single-writer (pragma naming the invariant)",
                )


class RacyCheckThenActRule(Rule):
    id = "racy-check-then-act"
    title = "read-test-then-mutate on a shared container outside any lock"

    def __init__(self, scope: tuple = SCOPE):
        self.scope = scope

    def finalize(self, project: Project) -> Iterable[Finding]:
        for cls in _class_index(project, self.scope).values():
            # a field is a shared mutable container when it is container-
            # mutated at all and its accesses span ≥2 roots
            shared: set[str] = set()
            by_field: dict[str, list[_Access]] = {}
            for a in cls.accesses:
                by_field.setdefault(a.attr, []).append(a)
            for attr, accs in by_field.items():
                if not any(a.kind == "mutate" for a in accs):
                    continue
                roots = frozenset().union(*(a.roots for a in accs))
                if len(roots) >= 2:
                    shared.add(attr)
            seen: set[tuple] = set()
            for c in cls.checks:
                if c.attr not in shared:
                    continue
                key = (c.method, c.attr, c.line)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    self.id,
                    cls.relpath,
                    c.line,
                    f"{c.terminal} tests self.{c.attr} and then mutates it "
                    "with no lock held — a concurrent mutation can land "
                    "between the check and the act (TOCTOU); hold the "
                    "class lock across both",
                )
