"""``replay-host-roundtrip``: device-resident replay must stay device-resident.

The tensor plane's whole value is that epoch ≥ 2 never touches the host:
pinned shards replay from HBM, permutations run on device, the streamed
tail is the ONLY host traffic a spilled cache pays.  One stray
``np.asarray(batch["x"])`` in the serving path silently reintroduces a
device→host→device round trip per batch — no crash, no wrong bytes, just
the subsystem's reason to exist gone.  Nothing type-checks that; this rule
does.

Flagged calls, anywhere under ``tensorplane/``:

- ``asarray(...)`` (``np.asarray``, ``numpy.asarray``, a bare import) —
  the canonical device→host materialization.  ``jnp.asarray`` /
  ``jax.numpy.asarray`` stay legal: they move TOWARD the device;
- ``.tolist()`` — a host materialization *and* a Python-object explosion;
- ``.to_pandas()`` — a host copy and a pandas dependency in the device
  plane.

Sanctioned host readbacks exist — the smoke register reads device results
back to *verify* them against host twins — and each carries an inline
``# lakelint: ignore[replay-host-roundtrip] <reason>`` pragma naming that
purpose, so every exception is justified in place.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

SCOPE = ("lakesoul_tpu/tensorplane/",)

_METHODS = ("tolist", "to_pandas")


class ReplayHostRoundtripRule(Rule):
    id = "replay-host-roundtrip"
    title = "host materialization of device-resident replay data"

    def __init__(self, scope: tuple[str, ...] = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(s in module.relpath for s in self.scope):
            return
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = None
            name = dotted_name(node.func)
            if name is not None and name.split(".")[-1] == "asarray" \
                    and name.split(".")[0] not in ("jnp", "jax"):
                callee = f"{name}()"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS
            ):
                callee = f".{node.func.attr}()"
            if callee is None:
                continue
            yield Finding(
                self.id,
                module.relpath,
                node.lineno,
                f"{callee} materializes device-resident data on the host"
                " inside the tensor plane — replay shards must stay on"
                " device (permute with jax.random, account with .nbytes,"
                " compare with device-side ops); a justified verification"
                " readback needs an inline pragma naming its purpose",
            )
