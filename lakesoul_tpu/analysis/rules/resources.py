"""``unclosed-reader``: pyarrow readers / IPC streams / memory maps must be
closed or context-managed.

A leaked ``pa.memory_map`` pins a file descriptor and the whole mapping
until GC gets around to it; at loader rates (thousands of scan units per
epoch) that is an fd-exhaustion outage, and on Windows an unclosed map
blocks compaction's file replacement.  The LSF reader leaked exactly this
way until this rule flagged it (``LsfFile`` now closes — see io/lsf.py).

Heuristics, in order:

1. constructor used as a ``with`` context manager → fine;
2. chained use-and-drop (``Ctor(...).attr``) or bare expression → flagged;
3. assigned to a local name → the enclosing function must ``close()`` it,
   ``with`` it, wrap it in ``contextlib.closing``, return/yield it
   (ownership transferred), or pass it onward as a call argument;
4. stored on ``self`` → the class must define ``close``/``__exit__``/
   ``__del__`` (someone has to end the object's lifetime deliberately).
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

_CLOSABLE_CTORS = {
    "pa.memory_map",
    "pyarrow.memory_map",
    "pa.OSFile",
    "pyarrow.OSFile",
    "pa.ipc.open_stream",
    "pyarrow.ipc.open_stream",
    "ipc.open_stream",
    "pa.ipc.open_file",
    "pyarrow.ipc.open_file",
    "ipc.open_file",
    "pa.ipc.new_stream",
    "pyarrow.ipc.new_stream",
    "ipc.new_stream",
    "pa.ipc.new_file",
    "pyarrow.ipc.new_file",
    "ipc.new_file",
    "pq.ParquetFile",
    "pyarrow.parquet.ParquetFile",
    "ParquetFile",
    # project-native closable readers
    "LsfFile",
}


def _nearest(parents, node, kinds):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def _name_released(scope: ast.AST, name: str) -> bool:
    """True when ``name`` is closed, context-managed, escapes by return/yield,
    or is handed to another call inside ``scope``."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "close"
                and dotted_name(func.value) == name
            ):
                return True
            if dotted_name(func) in ("contextlib.closing", "closing") and any(
                dotted_name(a) == name for a in node.args
            ):
                return True
            if any(dotted_name(a) == name for a in node.args):
                return True  # ownership handed onward
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if dotted_name(item.context_expr) == name:
                    return True
        elif isinstance(node, ast.Return) and node.value is not None:
            if dotted_name(node.value) == name:
                return True
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
            if dotted_name(node.value) == name:
                return True
        elif isinstance(node, ast.Assign):
            # re-homed onto self.<attr>: the attribute rule takes over
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and dotted_name(node.value) == name
                ):
                    return True
    return False


def _class_can_close(cls: ast.ClassDef) -> bool:
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt.name in (
            "close",
            "__exit__",
            "__del__",
        ):
            return True
    return False


class UnclosedReaderRule(Rule):
    id = "unclosed-reader"
    title = "pyarrow reader / IPC stream / memory map never closed"

    def check(self, module: Module) -> Iterable[Finding]:
        parents = module.parents()
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _CLOSABLE_CTORS:
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            msg = (
                f"{name}(...) holds an fd/mapping — close it, use a `with` "
                "block, or transfer ownership explicitly"
            )
            if isinstance(parent, ast.Attribute):
                # Ctor(...).x — used once and dropped; nothing can close it.
                # Exception: footer-only metadata reads that the ctor itself
                # documents as self-closing would be context-managed instead.
                yield Finding(self.id, module.relpath, node.lineno, msg)
                continue
            if isinstance(parent, ast.Expr):
                yield Finding(self.id, module.relpath, node.lineno, msg)
                continue
            if isinstance(parent, ast.Assign):
                tgt = parent.targets[0]
                scope = _nearest(
                    parents, node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) or module.tree
                if isinstance(tgt, ast.Name):
                    if not _name_released(scope, tgt.id):
                        yield Finding(self.id, module.relpath, node.lineno, msg)
                    elif _stored_on_self_without_close(
                        scope, tgt.id, parents, node
                    ):
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            f"{name}(...) is stored on self but the class "
                            "defines no close()/__exit__/__del__ — the "
                            "mapping lives until GC",
                        )
                elif (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    cls = _nearest(parents, node, (ast.ClassDef,))
                    if cls is not None and not _class_can_close(cls):
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            f"{name}(...) is stored on self but "
                            f"{cls.name} defines no close()/__exit__/"
                            "__del__ — the mapping lives until GC",
                        )


def _stored_on_self_without_close(scope, name, parents, node) -> bool:
    """Local name later stashed on ``self`` — walk up to the class and apply
    the attribute criterion."""
    stored = False
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and dotted_name(n.value) == name
                ):
                    stored = True
    if not stored:
        return False
    cls = _nearest(parents, node, (ast.ClassDef,))
    return cls is not None and not _class_can_close(cls)
