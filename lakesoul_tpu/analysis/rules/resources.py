"""``unclosed-reader``: pyarrow readers / IPC streams / memory maps must be
closed or context-managed.

A leaked ``pa.memory_map`` pins a file descriptor and the whole mapping
until GC gets around to it; at loader rates (thousands of scan units per
epoch) that is an fd-exhaustion outage, and on Windows an unclosed map
blocks compaction's file replacement.  The LSF reader leaked exactly this
way until this rule flagged it (``LsfFile`` now closes — see io/lsf.py).

Heuristics, in order:

1. constructor used as a ``with`` context manager → fine;
2. chained use-and-drop (``Ctor(...).attr``) or bare expression → flagged;
3. assigned to a local name → the enclosing function must ``close()`` it,
   ``with`` it, wrap it in ``contextlib.closing``, return/yield it
   (ownership transferred), or pass it onward as a call argument;
4. stored on ``self`` → the class must define ``close``/``__exit__``/
   ``__del__`` (someone has to end the object's lifetime deliberately).
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

_CLOSABLE_CTORS = {
    "pa.memory_map",
    "pyarrow.memory_map",
    "pa.OSFile",
    "pyarrow.OSFile",
    "pa.ipc.open_stream",
    "pyarrow.ipc.open_stream",
    "ipc.open_stream",
    "pa.ipc.open_file",
    "pyarrow.ipc.open_file",
    "ipc.open_file",
    "pa.ipc.new_stream",
    "pyarrow.ipc.new_stream",
    "ipc.new_stream",
    "pa.ipc.new_file",
    "pyarrow.ipc.new_file",
    "ipc.new_file",
    "pq.ParquetFile",
    "pyarrow.parquet.ParquetFile",
    "ParquetFile",
    # project-native closable readers
    "LsfFile",
}


def _nearest(parents, node, kinds):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def _name_released(scope: ast.AST, name: str) -> bool:
    """True when ``name`` is closed, context-managed, escapes by return/yield,
    or is handed to another call inside ``scope``."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "close"
                and dotted_name(func.value) == name
            ):
                return True
            if dotted_name(func) in ("contextlib.closing", "closing") and any(
                dotted_name(a) == name for a in node.args
            ):
                return True
            if any(dotted_name(a) == name for a in node.args):
                return True  # ownership handed onward
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if dotted_name(item.context_expr) == name:
                    return True
        elif isinstance(node, ast.Return) and node.value is not None:
            if dotted_name(node.value) == name:
                return True
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
            if dotted_name(node.value) == name:
                return True
        elif isinstance(node, ast.Assign):
            # re-homed onto self.<attr>: the attribute rule takes over
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and dotted_name(node.value) == name
                ):
                    return True
    return False


def _class_can_close(cls: ast.ClassDef) -> bool:
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt.name in (
            "close",
            "__exit__",
            "__del__",
        ):
            return True
    return False


class UnclosedReaderRule(Rule):
    id = "unclosed-reader"
    title = "pyarrow reader / IPC stream / memory map never closed"

    def check(self, module: Module) -> Iterable[Finding]:
        parents = module.parents()
        for node in module.walk():
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _CLOSABLE_CTORS:
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            msg = (
                f"{name}(...) holds an fd/mapping — close it, use a `with` "
                "block, or transfer ownership explicitly"
            )
            if isinstance(parent, ast.Attribute):
                # Ctor(...).x — used once and dropped; nothing can close it.
                # Exception: footer-only metadata reads that the ctor itself
                # documents as self-closing would be context-managed instead.
                yield Finding(self.id, module.relpath, node.lineno, msg)
                continue
            if isinstance(parent, ast.Expr):
                yield Finding(self.id, module.relpath, node.lineno, msg)
                continue
            if isinstance(parent, ast.Assign):
                tgt = parent.targets[0]
                scope = _nearest(
                    parents, node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) or module.tree
                if isinstance(tgt, ast.Name):
                    if not _name_released(scope, tgt.id):
                        yield Finding(self.id, module.relpath, node.lineno, msg)
                    elif _stored_on_self_without_close(
                        scope, tgt.id, parents, node
                    ):
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            f"{name}(...) is stored on self but the class "
                            "defines no close()/__exit__/__del__ — the "
                            "mapping lives until GC",
                        )
                elif (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    cls = _nearest(parents, node, (ast.ClassDef,))
                    if cls is not None and not _class_can_close(cls):
                        yield Finding(
                            self.id,
                            module.relpath,
                            node.lineno,
                            f"{name}(...) is stored on self but "
                            f"{cls.name} defines no close()/__exit__/"
                            "__del__ — the mapping lives until GC",
                        )


def _stored_on_self_without_close(scope, name, parents, node) -> bool:
    """Local name later stashed on ``self`` — walk up to the class and apply
    the attribute criterion."""
    stored = False
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and dotted_name(n.value) == name
                ):
                    stored = True
    if not stored:
        return False
    cls = _nearest(parents, node, (ast.ClassDef,))
    return cls is not None and not _class_can_close(cls)


# -------------------------------------------------------- interprocedural


class InterproceduralUnclosedReaderRule(Rule):
    """Ownership *escape* analysis across call boundaries.  The lexical
    rule treats "passed onward as a call argument" and "returned to the
    caller" as ownership transfers and stops — reasonable per-function,
    but wrong in two refactor shapes this rule closes:

    1. a reader handed to a project helper that neither closes, stores,
       returns, nor forwards it (the helper just *drops* it — nobody ever
       owns the fd);
    2. a project function whose contract is "returns an open reader"
       (``LsfFormat._open``) called by a caller that drops the result.

    Unresolvable callees keep the lexical rule's benefit of the doubt."""

    id = "interprocedural-unclosed-reader"
    title = "reader ownership dropped across a call boundary"

    _MAX_FORWARD = 3  # helper → helper → helper forwarding depth

    def finalize(self, project) -> Iterable[Finding]:
        graph = project.callgraph()
        returns_closable = self._returns_closable_set(graph)
        for fn in graph.functions.values():
            yield from self._check_function(fn, graph, returns_closable)

    # ----------------------------------------------------------- summaries

    def _returns_closable_set(self, graph) -> set[str]:
        """Functions whose return value is an open closable (directly, via
        a local name, or by forwarding another returns-closable call)."""
        out: set[str] = set()
        for _ in range(4):  # fixpoint over forwarding chains
            grew = False
            for qname, fn in graph.functions.items():
                if qname in out:
                    continue
                edges_by_node = {id(e.node): e for e in graph.callees(qname)}
                ctor_names = self._closable_local_names(fn)
                for node in walk_stopping_at_functions(fn.node.body):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    v = node.value
                    if isinstance(v, ast.Call):
                        if dotted_name(v.func) in _CLOSABLE_CTORS:
                            out.add(qname)
                            grew = True
                            break
                        edge = edges_by_node.get(id(v))
                        if edge is not None and edge.callee in out:
                            out.add(qname)
                            grew = True
                            break
                    elif isinstance(v, ast.Name) and v.id in ctor_names:
                        out.add(qname)
                        grew = True
                        break
            if not grew:
                break
        return out

    @staticmethod
    def _closable_local_names(fn) -> set[str]:
        names: set[str] = set()
        for node in walk_stopping_at_functions(fn.node.body):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if dotted_name(node.value.func) in _CLOSABLE_CTORS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
        return names

    def _param_released(self, graph, qname: str, param: str, depth: int) -> bool:
        """Does the callee give ``param`` an owner?  close/with/return/
        yield/self-store count; forwarding to a *resolved* callee recurses;
        forwarding to an unresolved callee gets the benefit of the doubt."""
        fn = graph.functions.get(qname)
        if fn is None or depth > self._MAX_FORWARD:
            return True  # can't see it — don't guess
        edges_by_node = {id(e.node): e for e in graph.callees(qname)}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "close"
                    and dotted_name(func.value) == param
                ):
                    return True
                if dotted_name(func) in ("contextlib.closing", "closing") and any(
                    dotted_name(a) == param for a in node.args
                ):
                    return True
                forwarded = [
                    i for i, a in enumerate(node.args)
                    if dotted_name(a) == param
                ]
                if forwarded:
                    edge = edges_by_node.get(id(node))
                    if edge is None or edge.callee is None:
                        return True  # unresolved — lexical rule's benefit
                    callee = graph.functions[edge.callee]
                    params = callee.params
                    off = 1 if callee.is_method and params[:1] in (
                        ["self"], ["cls"]
                    ) else 0
                    for i in forwarded:
                        if i + off < len(params) and self._param_released(
                            graph, edge.callee, params[i + off], depth + 1
                        ):
                            return True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if dotted_name(item.context_expr) == param:
                        return True
            elif isinstance(node, ast.Return) and node.value is not None:
                if dotted_name(node.value) == param:
                    return True
                if isinstance(node.value, (ast.Tuple, ast.List)) and any(
                    dotted_name(e) == param for e in node.value.elts
                ):
                    return True
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
                if dotted_name(node.value) == param:
                    return True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and dotted_name(node.value) == param
                    ):
                        return True
        return False

    # ------------------------------------------------------------- checking

    def _check_function(self, fn, graph, returns_closable: set[str]):
        edges_by_node = {id(e.node): e for e in graph.callees(fn.qname)}
        # parent map local to this function body
        parents: dict = {}
        for node in ast.walk(fn.node):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        # findings anchor to THIS function's lexical body only — nested
        # defs are their own call-graph nodes and get their own visit
        for node in walk_stopping_at_functions(fn.node.body):
            if not isinstance(node, ast.Call):
                continue
            is_ctor = dotted_name(node.func) in _CLOSABLE_CTORS
            edge = edges_by_node.get(id(node))
            is_factory = (
                edge is not None
                and edge.callee in returns_closable
                and edge.callee != fn.qname
            )
            if not (is_ctor or is_factory):
                continue
            what = dotted_name(node.func) or (
                node.func.attr if isinstance(node.func, ast.Attribute) else "call"
            )
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            if is_factory and isinstance(parent, (ast.Expr, ast.Attribute)):
                # factory result dropped on the floor (lexical rule only
                # knows ctors; the factory's "returns an open reader"
                # contract comes from the call graph)
                yield Finding(
                    self.id,
                    fn.relpath,
                    node.lineno,
                    f"{what}(...) returns an open reader that is dropped — "
                    "close it, `with` it, or pass ownership on",
                )
                continue
            if not isinstance(parent, ast.Assign):
                continue
            tgt = parent.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            name = tgt.id
            scope = fn.node
            released = self._name_released_interproc(
                scope, name, graph, fn, edges_by_node
            )
            if released is False:
                yield Finding(
                    self.id,
                    fn.relpath,
                    node.lineno,
                    f"{what}(...) is handed to a helper that drops it — no "
                    "function in the chain closes, stores, or returns the "
                    "reader, so the fd lives until GC",
                )
            elif released is None and is_factory:
                yield Finding(
                    self.id,
                    fn.relpath,
                    node.lineno,
                    f"{what}(...) returns an open reader that is never "
                    "closed, context-managed, or passed on in this scope",
                )

    def _name_released_interproc(self, scope, name, graph, fn, edges_by_node):
        """True = released; False = provably dropped across a call
        boundary; None = never released at all (no call transfer either)."""
        transferred_calls: list = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and dotted_name(func.value) == name
                ):
                    if func.attr == "close":
                        return True
                    continue  # method use is not a transfer
                if dotted_name(func) in ("contextlib.closing", "closing") and any(
                    dotted_name(a) == name for a in node.args
                ):
                    return True
                if any(dotted_name(a) == name for a in node.args):
                    transferred_calls.append(node)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if dotted_name(item.context_expr) == name:
                        return True
            elif isinstance(node, ast.Return) and node.value is not None:
                if dotted_name(node.value) == name:
                    return True
                if isinstance(node.value, (ast.Tuple, ast.List)) and any(
                    dotted_name(e) == name for e in node.value.elts
                ):
                    return True
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value is not None:
                if dotted_name(node.value) == name:
                    return True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and dotted_name(node.value) == name
                    ):
                        return True
        if not transferred_calls:
            return None
        for call in transferred_calls:
            edge = edges_by_node.get(id(call))
            if edge is None or edge.callee is None:
                return True  # unresolved callee — benefit of the doubt
            callee = graph.functions[edge.callee]
            params = callee.params
            off = 1 if callee.is_method and params[:1] in (["self"], ["cls"]) \
                else 0
            for i, a in enumerate(call.args):
                if dotted_name(a) == name and i + off < len(params):
                    if self._param_released(
                        graph, edge.callee, params[i + off], 1
                    ):
                        return True
        return False
