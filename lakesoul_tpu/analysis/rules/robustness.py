"""``ad-hoc-retry``: hand-rolled retry loops are banned outside the
resilience layer.

The repo used to carry four independent retry dialects (meta commit's
unseeded ``random.uniform`` sleeps, compaction's bare 3-attempt loop, the
proxy upstreams' ``for _ in range(retries + 1)``, the page cache's
hardcoded backoff constant).  Each invented its own backoff, its own idea
of which errors are worth retrying, and none of them counted attempts or
exhaustion anywhere observable.  ``runtime/resilience.py`` is now the one
place a retry loop may live: every other call site configures a
:class:`~lakesoul_tpu.runtime.resilience.RetryPolicy` (seeded jitter,
deadlines, ``lakesoul_retry_*`` counters) instead of writing a loop.

Two shapes are flagged, both only inside ``for ... in range(...)`` loops
(the canonical bounded-attempts shape; ``while`` condition polls and
event waits stay legal):

- a ``try`` whose ``except`` handler swallows the error (no top-level
  ``raise``/``return``/``break``) so the loop can go around again — the
  retry loop itself, anchored at the ``for`` line;
- ``time.sleep(...)`` inside such a loop that also contains a ``try`` —
  sleep-based backoff, anchored at the sleep call.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import (
    Finding,
    Module,
    Rule,
    dotted_name,
    walk_stopping_at_functions,
)

# the one module allowed to iterate attempts and sleep between them
_RESILIENCE_MODULE = "runtime/resilience.py"


def _is_range_for(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.For)
        and isinstance(node.iter, ast.Call)
        and dotted_name(node.iter.func) in ("range",)
    )


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """A handler that neither re-raises nor exits the loop at its top level
    lets the ``for`` go around again — the defining move of a retry loop.
    (A conditional ``raise`` buried in an ``if`` still swallows on the
    other branch, which is exactly the not-retryable/retryable split the
    policy's ``classify`` should own.)"""
    return not any(
        isinstance(stmt, (ast.Raise, ast.Return, ast.Break))
        for stmt in handler.body
    )


class AdHocRetryRule(Rule):
    id = "ad-hoc-retry"
    title = "hand-rolled retry loop / sleep backoff outside runtime/resilience.py"

    def __init__(self, scope_exempt: tuple[str, ...] = (_RESILIENCE_MODULE,)):
        self.scope_exempt = scope_exempt

    def check(self, module: Module) -> Iterable[Finding]:
        if any(module.relpath.endswith(m) for m in self.scope_exempt):
            return
        for node in module.walk():
            if not _is_range_for(node):
                continue
            # lexical loop body only; a nested def's body runs elsewhere
            body_nodes = list(walk_stopping_at_functions(node.body))
            tries = [n for n in body_nodes if isinstance(n, ast.Try)]
            swallowing = [
                t for t in tries if any(_handler_swallows(h) for h in t.handlers)
            ]
            if swallowing:
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "for-range loop swallows exceptions to try again — an "
                    "ad-hoc retry loop; route through "
                    "runtime/resilience.RetryPolicy (seeded backoff, "
                    "deadlines, retry counters)",
                )
            if not swallowing:
                # a re-raising handler (or no handler) means the loop is not
                # retrying; a sleep there is a poll cadence, not backoff
                continue
            for n in body_nodes:
                if (
                    isinstance(n, ast.Call)
                    and dotted_name(n.func) in ("time.sleep", "sleep")
                ):
                    yield Finding(
                        self.id,
                        module.relpath,
                        n.lineno,
                        "sleep-based backoff inside a retry loop — use "
                        "RetryPolicy's backoff schedule instead of "
                        "hand-rolled sleeps",
                    )
