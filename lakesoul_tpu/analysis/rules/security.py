"""Interprocedural security rules for the service gateways.

Both rules exist because the per-function rules cannot see the one
refactor that actually happens in practice: a handler's gate or sanitizer
moving into (or being forgotten by) a helper.

- ``rbac-gate-reachability``: every Flight/FlightSQL handler
  (``do_get``/``do_put``/``do_action``/``do_exchange``) must pass an RBAC
  check (``_check``/``_check_statement``/``_check_warehouse_wide``) on
  every path that transitively reaches a catalog/meta mutation.  The
  analysis is a branch-aware "checked" flag walked over each function with
  bottom-up summaries over the call graph: a helper that always checks
  *establishes* the gate for its caller; a helper that mutates without
  checking propagates the violation up to the handler that can be blamed.
- ``taint-path-segments``: request-derived strings in the storage proxy
  and its upstreams must pass the path sanitizer before reaching any
  filesystem/object-store call — tracked across helper functions via
  :mod:`lakesoul_tpu.analysis.dataflow`.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.callgraph import CallGraph, FuncInfo, iter_calls_in_order
from lakesoul_tpu.analysis.engine import Finding, Project, Rule, dotted_name

__all__ = ["RbacGateReachabilityRule", "TaintPathSegmentsRule"]

# gateway modules whose handlers carry the RBAC obligation
_GATEWAY_SCOPE = ("service/flight.py", "service/flight_sql.py")

_HANDLER_NAMES = frozenset({"do_get", "do_put", "do_action", "do_exchange"})

_CHECK_NAMES = frozenset(
    {"_check", "_check_statement", "_check_warehouse_wide"}
)

# attribute calls that mutate catalog/meta state (meta/client.py commit
# APIs + catalog.py write paths + the staged-writer publish calls).  Within
# the gateway modules these names are unambiguous regardless of receiver —
# the resolver cannot type `self.catalog`, but nothing else there is called
# `commit_data_files`.
_MUTATION_ATTRS = frozenset({
    "create_table", "drop_table", "create_namespace", "drop_namespace",
    "commit_data", "commit_data_files", "update_table_schema",
    "write_arrow", "upsert", "delete_partitions", "delete_where",
    "update_where", "compact", "rollback", "add_columns",
    "canonicalize_partition_descs", "meta_cleanup",
    "checkpoint", "checkpoint_replace",
})


class _Unguarded:
    """One mutation reachable with no check yet on the path."""

    __slots__ = ("relpath", "line", "raw", "chain")

    def __init__(self, relpath: str, line: int, raw: str, chain: tuple[str, ...]):
        self.relpath = relpath
        self.line = line
        self.raw = raw
        self.chain = chain


class _Summary:
    __slots__ = ("establishes", "unguarded")

    def __init__(self, establishes: bool, unguarded: list):
        self.establishes = establishes  # every normal exit passed a check
        self.unguarded = unguarded  # list[_Unguarded] assuming unchecked entry


class RbacGateReachabilityRule(Rule):
    id = "rbac-gate-reachability"
    title = "Flight handler reaches a catalog/meta mutation without RBAC"

    def __init__(
        self,
        scope: tuple[str, ...] = _GATEWAY_SCOPE,
        *,
        handlers: frozenset = _HANDLER_NAMES,
        check_names: frozenset = _CHECK_NAMES,
        mutation_attrs: frozenset = _MUTATION_ATTRS,
    ):
        self.scope = scope
        self.handlers = handlers
        self.check_names = check_names
        self.mutation_attrs = mutation_attrs
        self._memo: dict[str, _Summary] = {}
        self._visiting: set[str] = set()

    def finalize(self, project: Project) -> Iterable[Finding]:
        graph: CallGraph = project.callgraph()
        self._memo.clear()
        self._visiting.clear()
        seen: dict[tuple, Finding] = {}
        for fn in graph.functions_in(self.scope):
            if not fn.is_method or fn.name.rsplit(".", 1)[-1] not in self.handlers:
                continue
            handler = fn.name.rsplit(".", 1)[-1]
            for mut in self._summary(fn, graph).unguarded:
                via = " -> ".join((handler,) + mut.chain)
                finding = Finding(
                    self.id,
                    mut.relpath,
                    mut.line,
                    f"{mut.raw}(...) is reachable from {handler} (via {via}) "
                    "on a path with no RBAC check — every gateway path that "
                    "mutates catalog/meta state must pass _check/"
                    "_check_statement/_check_warehouse_wide first",
                )
                key = (mut.relpath, mut.line, mut.raw, handler)
                seen.setdefault(key, finding)
        return list(seen.values())

    # ----------------------------------------------------------- summaries

    def _summary(self, fn: FuncInfo, graph: CallGraph) -> _Summary:
        hit = self._memo.get(fn.qname)
        if hit is not None:
            return hit
        if fn.qname in self._visiting:
            # recursion: assume the cycle neither checks nor mutates — the
            # acyclic entry into the cycle still gets analyzed
            return _Summary(False, [])
        self._visiting.add(fn.qname)
        try:
            edges_by_node = {id(e.node): e for e in graph.callees(fn.qname)}
            unguarded: list[_Unguarded] = []
            checked_out, _ = self._walk(
                fn.node.body, False, fn, graph, edges_by_node, unguarded
            )
            summary = _Summary(checked_out, unguarded)
            self._memo[fn.qname] = summary
            return summary
        finally:
            self._visiting.discard(fn.qname)

    def _walk(self, body: list, checked: bool, fn: FuncInfo, graph: CallGraph,
              edges_by_node: dict, unguarded: list) -> tuple[bool, bool]:
        """→ (checked at block end, block always terminates)."""
        for stmt in body:
            checked, terminated = self._walk_stmt(
                stmt, checked, fn, graph, edges_by_node, unguarded
            )
            if terminated:
                return checked, True
        return checked, False

    def _walk_stmt(self, stmt, checked: bool, fn: FuncInfo, graph: CallGraph,
                   edges_by_node: dict, unguarded: list) -> tuple[bool, bool]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return checked, False  # nested bodies run outside this flow
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                checked = self._eval_calls(
                    [stmt.value], checked, fn, graph, edges_by_node, unguarded
                )
            return checked, True
        if isinstance(stmt, ast.If):
            checked = self._eval_calls(
                [stmt.test], checked, fn, graph, edges_by_node, unguarded
            )
            t_checked, t_term = self._walk(
                stmt.body, checked, fn, graph, edges_by_node, unguarded
            )
            # an absent else is a fall-through branch with the entry state
            # (walking [] returns (checked, False)), so the join below is
            # uniform: checked-after = every LIVE branch checked
            e_checked, e_term = self._walk(
                stmt.orelse, checked, fn, graph, edges_by_node, unguarded
            )
            if t_term and e_term:
                return True, True  # both branches leave; after is unreachable
            live = [c for c, term in ((t_checked, t_term), (e_checked, e_term))
                    if not term]
            return all(live), False
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            head = [stmt.iter] if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                else [stmt.test]
            checked = self._eval_calls(
                head, checked, fn, graph, edges_by_node, unguarded
            )
            # the body may run zero times: mutations inside are evaluated
            # with the entry state, but nothing it establishes survives
            self._walk(stmt.body, checked, fn, graph, edges_by_node, unguarded)
            self._walk(stmt.orelse, checked, fn, graph, edges_by_node, unguarded)
            return checked, False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            checked = self._eval_calls(
                [i.context_expr for i in stmt.items], checked, fn, graph,
                edges_by_node, unguarded,
            )
            return self._walk(
                stmt.body, checked, fn, graph, edges_by_node, unguarded
            )
        if isinstance(stmt, ast.Try):
            b_checked, _ = self._walk(
                stmt.body, checked, fn, graph, edges_by_node, unguarded
            )
            handler_states = []
            for handler in stmt.handlers:
                h_checked, h_term = self._walk(
                    handler.body, checked, fn, graph, edges_by_node, unguarded
                )
                if not h_term:
                    handler_states.append(h_checked)
            o_checked, _ = self._walk(
                stmt.orelse, b_checked, fn, graph, edges_by_node, unguarded
            )
            out = o_checked if stmt.orelse else b_checked
            # conservative join: the check must have happened on the try
            # path AND every live handler path
            joined = out and all(handler_states)
            return self._walk(
                stmt.finalbody, joined, fn, graph, edges_by_node, unguarded
            ) if stmt.finalbody else (joined, False)
        # plain statement: evaluate its calls in order
        exprs = [n for n in ast.iter_child_nodes(stmt) if isinstance(n, ast.expr)]
        checked = self._eval_calls(
            exprs, checked, fn, graph, edges_by_node, unguarded
        )
        return checked, False

    def _eval_calls(self, exprs: list, checked: bool, fn: FuncInfo,
                    graph: CallGraph, edges_by_node: dict,
                    unguarded: list) -> bool:
        wrapper = [ast.Expr(value=e) for e in exprs if e is not None]
        for call in iter_calls_in_order(wrapper):
            name = dotted_name(call.func)
            terminal = (name or "").rsplit(".", 1)[-1] or (
                call.func.attr if isinstance(call.func, ast.Attribute) else ""
            )
            if terminal in self.check_names:
                checked = True
                continue
            edge = edges_by_node.get(id(call))
            callee_q = edge.callee if edge is not None else None
            if not checked and isinstance(call.func, ast.Attribute) \
                    and call.func.attr in self.mutation_attrs:
                unguarded.append(_Unguarded(
                    fn.relpath, call.lineno, name or call.func.attr, ()
                ))
                continue
            if callee_q is not None:
                callee = graph.functions[callee_q]
                sub = self._summary(callee, graph)
                if not checked:
                    for mut in sub.unguarded:
                        unguarded.append(_Unguarded(
                            mut.relpath, mut.line, mut.raw,
                            (callee.name.rsplit(".", 1)[-1],) + mut.chain,
                        ))
                if sub.establishes:
                    checked = True
        return checked


# --------------------------------------------------------------------- taint


class TaintPathSegmentsRule(Rule):
    id = "taint-path-segments"
    title = "request-derived path reaches the store without the sanitizer"

    _PROXY_SCOPE = (
        "service/storage_proxy.py",
        "service/s3_upstream.py",
        "service/azure.py",
    )

    def __init__(self, scope: tuple[str, ...] = _PROXY_SCOPE, *,
                 extra_sanitizers: frozenset = frozenset()):
        self.scope = scope
        self.extra_sanitizers = extra_sanitizers

    def finalize(self, project: Project) -> Iterable[Finding]:
        from lakesoul_tpu.analysis.dataflow import TaintAnalysis, TaintConfig

        config = TaintConfig(
            source_self_attrs=frozenset({"path", "headers", "rfile"}),
            sanitizers=frozenset({
                "sanitize_path_segments",
                "_upload_id_shape_ok",
                "_safe_upload_id",
                "parse_range",
            }) | self.extra_sanitizers,
            sink_functions={"filesystem_for": 0, "ensure_dir": 0, "open": 0},
            sink_methods={
                "open": 0, "rm": 0, "ls": 0, "find": 0, "size": 0,
                "exists": 0, "cat_file": 0, "pipe_file": 0, "makedirs": 0,
                "mkdir": 0, "request": 1,
            },
            sink_keywords=frozenset({"key"}),
        )
        analysis = TaintAnalysis(project.callgraph(), config)
        for hit in analysis.run(self.scope):
            via = " -> ".join(hit.chain)
            yield Finding(
                self.id,
                hit.relpath,
                hit.line,
                f"request-derived value {hit.source_desc!r} reaches "
                f"{hit.sink}(...) (via {via}) without passing the path "
                "sanitizer — an empty/'.'/'..'/encoded segment would escape "
                "the RBAC-checked table directory",
            )
