"""``wall-clock-lease``: ``time.time()`` arithmetic is banned in TTL /
deadline / lease math across the coordination-bearing layers.

PR 7's lease table makes clocks load-bearing: a compaction service that
computes "is my lease still valid" or "has this deadline passed" from
``time.time()`` is one NTP step away from either abandoning a healthy
lease or trusting a dead one.  The discipline the topology layer settled
on:

- **Local** validity windows, renewal cadences, and shutdown/drain
  deadlines use ``time.monotonic()`` — immune to wall-clock jumps.
- **Cross-process** lease expiry lives in the store on ITS shared
  timebase (``meta.entity.now_millis``); no in-process wall-clock
  comparison ever decides correctness — the fencing token does.
- Wire formats whose spec *is* epoch seconds (JWT ``exp``, RFC 7519)
  keep the wall clock behind a justified pragma.

Scope: ``service/``, ``compaction/``, ``meta/`` — the layers that hold
leases, serve tokens, or sweep by age.  A ``time.time()`` call is flagged
when the statement it sits in also mentions a TTL/deadline/lease-shaped
identifier (``ttl``, ``deadline``, ``lease``, ``expire``/``expiry``,
``timeout``) — the co-occurrence that marks duration math, while plain
epoch *timestamps* (``now_millis``-style stamping) stay legal.  For
compound statements (``while``/``if``/``for``) only the controlling
expression is considered, not the body.
"""

from __future__ import annotations

import ast
from typing import Iterable

from lakesoul_tpu.analysis.engine import Finding, Module, Rule, dotted_name

SCOPE = ("service/", "compaction/", "meta/", "scanplane/", "freshness/")

_KEYWORDS = ("ttl", "deadline", "lease", "expire", "expiry", "timeout")


def _controlling_expr(stmt: ast.stmt) -> ast.AST:
    """The part of a compound statement whose identifiers count: the test
    of a While/If, the iterable of a For — never the body (nested
    statements get their own check)."""
    if isinstance(stmt, (ast.While, ast.If)):
        return stmt.test
    if isinstance(stmt, ast.For):
        return stmt.iter
    return stmt


def _mentions_keyword(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            low = name.lower()
            if any(k in low for k in _KEYWORDS):
                return True
    return False


class WallClockLeaseRule(Rule):
    id = "wall-clock-lease"
    title = "time.time() in TTL/deadline/lease arithmetic (use time.monotonic())"

    def __init__(self, scope: tuple[str, ...] = SCOPE):
        self.scope = scope

    def check(self, module: Module) -> Iterable[Finding]:
        if not any(s in module.relpath for s in self.scope):
            return
        parents = module.parents()
        for node in module.walk():
            if not (
                isinstance(node, ast.Call)
                and dotted_name(node.func) == "time.time"
            ):
                continue
            stmt: ast.AST = node
            while stmt in parents and not isinstance(stmt, ast.stmt):
                stmt = parents[stmt]
            if not isinstance(stmt, ast.stmt):
                continue
            if _mentions_keyword(_controlling_expr(stmt)):
                yield Finding(
                    self.id,
                    module.relpath,
                    node.lineno,
                    "time.time() used in TTL/deadline/lease math — wall-clock"
                    " jumps (NTP) corrupt it; use time.monotonic() for local"
                    " windows (cross-process lease expiry belongs in the"
                    " store via meta.entity.now_millis)",
                )
