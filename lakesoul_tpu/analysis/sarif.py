"""SARIF 2.1.0 rendering for lakelint findings.

SARIF is what code-scanning UIs (GitHub code scanning, VS Code SARIF
viewer, Azure DevOps) ingest, so `lakesoul-lint --format sarif` makes the
project-native rules first-class citizens next to any generic scanner in
the same pipeline.  Only the shape those consumers actually read is
emitted: tool.driver with the rule catalog, and one result per finding
with ruleId, message.text and a physicalLocation (artifactLocation.uri is
repo-relative with posix separators, matching ``Finding.path``).
"""

from __future__ import annotations

from typing import Iterable

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def to_sarif(findings: Iterable, rules: Iterable) -> dict:
    """``(findings, rules) -> SARIF 2.1.0 log`` as a plain dict (the CLI
    json-dumps it).  ``rules`` is the full catalog that ran, not just the
    ids that fired — consumers use it to render titles and to know a rule
    ran clean."""
    rule_list = [
        {
            "id": r.id,
            "shortDescription": {"text": r.title or r.id},
        }
        for r in rules
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line},
                    }
                }
            ],
        }
        for f in findings
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "lakesoul-lint",
                        "informationUri": (
                            "https://github.com/lakesoul-io/LakeSoul"
                        ),
                        "rules": rule_list,
                    }
                },
                "results": results,
            }
        ],
    }
