"""Minimal SQL statement model for the metadata-store dialect.

Shared by the isolation rule pack (``rules/isolation.py``), which reads
statements out of the AST, and the runtime interleaving replayer
(``txncheck.py``), which records them at the ``meta/store.py`` execution
boundary.  This is NOT a SQL parser — it is a regex-level classifier for
the one dialect the store emits: single-table INSERT/UPDATE/DELETE/SELECT
with ``?`` placeholders, ``IN (...)`` lists, ``ON CONFLICT`` upserts and
the ``/*row-lock*/`` / ``FOR UPDATE`` row-lock markers.  Known limits, on
purpose: joins, subqueries and OR-trees are not modeled — columns named
anywhere after the first WHERE count as constrained (the loosening
direction: more where-columns means FEWER isolation findings, never
false ones), and values it cannot bind stay unknown rather than guessed.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

__all__ = ["Statement", "parse_statement", "bind_values"]

_WS_RE = re.compile(r"\s+")
_OP_RE = re.compile(r"^\s*([A-Za-z]+)")
_UPDATE_RE = re.compile(r"^\s*UPDATE\s+([A-Za-z_]\w*)", re.I)
_DELETE_RE = re.compile(r"^\s*DELETE\s+FROM\s+([A-Za-z_]\w*)", re.I)
_INSERT_RE = re.compile(
    r"^\s*INSERT(?:\s+OR\s+(IGNORE|REPLACE))?\s+INTO\s+([A-Za-z_]\w*)\s*(?:\(([^)]*)\))?",
    re.I,
)
_FROM_RE = re.compile(r"\bFROM\s+([A-Za-z_]\w*)", re.I)
_WHERE_SPLIT_RE = re.compile(r"\bWHERE\b", re.I)
_SET_SPLIT_RE = re.compile(r"\bSET\b", re.I)
_CONFLICT_RE = re.compile(r"\bON\s+CONFLICT\s*\(([^)]*)\)", re.I)
_DO_UPDATE_RE = re.compile(r"\bDO\s+UPDATE\b", re.I)
# a column under comparison, or heading an IN list
_WHERE_COL_RE = re.compile(r"([A-Za-z_]\w*)\s*(?:=|<=|>=|<>|!=|<|>|\s+IN\b)", re.I)
# one ordered scan: comparisons and IN lists, so ? slots bind in textual order
_WHERE_TERM_RE = re.compile(
    r"([A-Za-z_]\w*)\s*(=|<=|>=|<>|!=|<|>)\s*(\?|'[^']*'|-?\d+|NULL)"
    r"|([A-Za-z_]\w*)\s+IN\s*\(([^)]*)\)",
    re.I,
)
_ROW_LOCK_RE = re.compile(r"/\*row-lock\*/|\bFOR\s+UPDATE\b", re.I)


@dataclass(frozen=True)
class Statement:
    """One classified SQL statement (pre-``translate_sql`` spelling)."""

    op: str  # "select" | "insert" | "update" | "delete" | "pragma" | "other"
    table: "str | None"
    where_cols: frozenset  # every column constrained after the first WHERE
    set_cols: frozenset  # UPDATE SET targets / INSERT column list
    relative_cols: frozenset  # SET cols whose RHS references themselves (x=x+1)
    or_ignore: bool = False
    or_replace: bool = False
    upsert: bool = False  # ON CONFLICT ... DO UPDATE
    conflict_cols: frozenset = frozenset()
    row_locked: bool = False
    qmark: bool = False
    text: str = ""
    # ordered binding slots: ("where"|"set"|"insert", col, "?"|literal)
    _slots: tuple = field(default=(), repr=False)

    @property
    def is_write(self) -> bool:
        return self.op in ("insert", "update", "delete")


def _split_top_level(text: str) -> "list[str]":
    """Split on commas at paren depth 0 (SET lists, VALUES lists)."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


def parse_statement(sql: str) -> "Statement | None":
    """Classify one statement; None when the text is not statement-shaped
    (prefix fragments like ``"INSERT OR IGNORE"`` used by translate_sql)."""
    text = _WS_RE.sub(" ", sql).strip()
    m = _OP_RE.match(text)
    if not m:
        return None
    verb = m.group(1).upper()
    qmark = "?" in text
    row_locked = bool(_ROW_LOCK_RE.search(text))
    slots: list = []

    if verb == "PRAGMA":
        return Statement("pragma", None, frozenset(), frozenset(), frozenset(),
                         qmark=qmark, text=text)

    where_part = ""
    where_split = _WHERE_SPLIT_RE.split(text, maxsplit=1)
    if len(where_split) == 2:
        where_part = where_split[1]
    where_cols = frozenset(c.lower() for c in _WHERE_COL_RE.findall(where_part))
    for m2 in _WHERE_TERM_RE.finditer(where_part):
        if m2.group(1):  # comparison — equality binds, others only consume ?
            kind = "where" if m2.group(2) == "=" else "where-skip"
            slots.append((kind, m2.group(1).lower(), m2.group(3)))
        else:  # IN list — each item binds into the column's value set
            for item in _split_top_level(m2.group(5)):
                slots.append(("where", m2.group(4).lower(), item.strip()))

    if verb == "UPDATE":
        mt = _UPDATE_RE.match(text)
        # "UPDATE SET x: ..." in an error message is prose, not SQL — a
        # table position holding a keyword means this never parsed
        if mt and mt.group(1).lower() in ("set", "where", "from"):
            mt = None
        head = where_split[0]
        set_split = _SET_SPLIT_RE.split(head, maxsplit=1)
        set_cols, relative = set(), set()
        set_slots: list = []
        if len(set_split) == 2:
            for item in _split_top_level(set_split[1]):
                if "=" not in item:
                    continue
                col, rhs = item.split("=", 1)
                col = col.strip().lower()
                rhs = rhs.strip()
                set_cols.add(col)
                if re.search(rf"\b{re.escape(col)}\b", rhs, re.I):
                    relative.add(col)
                set_slots.append(("set", col, rhs))
        return Statement(
            "update", mt.group(1).lower() if mt else None,
            where_cols, frozenset(set_cols), frozenset(relative),
            row_locked=row_locked, qmark=qmark, text=text,
            _slots=tuple(set_slots + slots),
        )

    if verb == "DELETE":
        mt = _DELETE_RE.match(text)
        return Statement(
            "delete", mt.group(1).lower() if mt else None,
            where_cols, frozenset(), frozenset(),
            row_locked=row_locked, qmark=qmark, text=text, _slots=tuple(slots),
        )

    if verb == "INSERT":
        mt = _INSERT_RE.match(text)
        if not mt or not mt.group(2):
            return None  # not statement-shaped (no INTO <table>)
        modifier = (mt.group(1) or "").upper()
        cols = tuple(
            c.strip().lower() for c in (mt.group(3) or "").split(",") if c.strip()
        )
        insert_slots: list = []
        mv = re.search(r"\bVALUES\s*\(", text, re.I)
        if mv and cols:
            depth, i, start = 1, mv.end(), mv.end()
            while i < len(text) and depth:
                depth += {"(": 1, ")": -1}.get(text[i], 0)
                i += 1
            values = _split_top_level(text[start:i - 1])
            if len(values) == len(cols):
                insert_slots = [
                    ("insert", c, v.strip()) for c, v in zip(cols, values)
                ]
        conflict = _CONFLICT_RE.search(text)
        return Statement(
            "insert", mt.group(2).lower(),
            where_cols, frozenset(cols), frozenset(),
            or_ignore=modifier == "IGNORE", or_replace=modifier == "REPLACE",
            upsert=bool(_DO_UPDATE_RE.search(text)),
            conflict_cols=frozenset(
                c.strip().lower() for c in conflict.group(1).split(",")
            ) if conflict else frozenset(),
            row_locked=row_locked, qmark=qmark, text=text,
            _slots=tuple(insert_slots + slots),
        )

    if verb == "SELECT":
        mt = _FROM_RE.search(text)
        return Statement(
            "select", mt.group(1).lower() if mt else None,
            where_cols, frozenset(), frozenset(),
            row_locked=row_locked, qmark=qmark, text=text, _slots=tuple(slots),
        )

    return Statement("other", None, frozenset(), frozenset(), frozenset(),
                     qmark=qmark, text=text)


def bind_values(stmt: Statement, params: tuple) -> "dict[str, dict]":
    """Resolve the statement's per-column values against its parameters.

    Returns ``{"where": {col: {values...}}, "write": {col: {values...}}}``
    where ``write`` covers SET/INSERT columns.  ``?`` slots consume params
    in statement order (SET before WHERE, matching the store's argument
    convention); quoted/numeric literals bind directly; expressions bind
    nothing (the column stays constrained-but-unknown)."""
    out: dict = {"where": {}, "write": {}}
    params = tuple(params or ())
    idx = 0
    for kind, col, val in stmt._slots:
        bound = None
        if val == "?":
            if idx < len(params):
                bound = params[idx]
            idx += 1
        elif re.fullmatch(r"'[^']*'", val):
            bound = val[1:-1]
        elif re.fullmatch(r"-?\d+", val):
            bound = int(val)
        elif val.upper() == "NULL":
            bound = None
        else:
            idx += val.count("?")  # expression: unknown value, keep alignment
            continue
        if kind == "where-skip":
            continue  # non-equality comparison: slot consumed, no key bound
        bucket = "where" if kind == "where" else "write"
        out[bucket].setdefault(col, set()).add(bound)
    return out
