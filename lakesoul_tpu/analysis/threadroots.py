"""Thread-root inference over the project call graph.

The lockset rules (``rules/races.py``) need to answer "which *threads* can
be executing this method?" — a question the call graph alone cannot: a
``Thread(target=...)`` or ``pool.submit(fn)`` is a *reference*, not a call
edge, yet it is exactly where a second thread of control enters the
program.  This module enumerates the codebase's **thread roots** — every
place the runtime hands a function to another thread — and tags each
function with the set of roots that can reach it:

- ``thread:<entry>`` — ``threading.Thread(target=f)`` (the pipeline pumps,
  the lease heartbeat, the ANN batching worker, server accept loops);
- ``pool:<entry>`` — ``<anything>.submit(f, ...)`` where ``f`` resolves to
  a project function (the shared worker pool's tasks);
- ``pipeline:<entry>`` — functions registered as pipeline stages
  (``.map(f)`` / ``.map_parallel(f)`` / ``.flat_map_parallel(f)``) or as a
  generator source (``.source(f(...))``): stage fns run on pool workers,
  and the source generator's body runs on whichever thread iterates it
  (the prefetch pump);
- ``handler:<entry>`` — ``do_*`` methods (Flight ``do_get``/``do_put``/
  ``do_action``/``do_exchange``, ``http.server`` ``do_GET``/…): the server
  substrate invokes them on its own request threads, so no static edge
  exists.  Classes deriving from ``*HTTPRequestHandler`` get ONE collapsed
  ``handler`` root per class — ``http.server`` constructs a fresh handler
  instance per request, so two verb methods of the same class never share
  instance state across threads (a Flight server instance, by contrast, is
  shared across concurrent RPCs, so each of its verbs is a distinct root);
- ``main`` — reachable from module level or from an uncalled public
  surface (API methods invoked by code outside the package: tests,
  training loops, the console).

Reachability is a BFS over *resolved* call edges from each entry, so a
field write three helpers deep below a pump function still carries the
pump's root.  Calls the resolver cannot pin (dynamic receivers) simply
don't propagate roots — the rules stay conservative (fewer findings), the
known trade of the whole interprocedural layer.

Built once per :class:`~lakesoul_tpu.analysis.engine.Project` and cached
(:func:`thread_roots`), same contract as the call graph and device index.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from lakesoul_tpu.analysis.engine import Project, dotted_name

__all__ = ["MAIN_ROOT", "ThreadRootIndex", "thread_roots"]

MAIN_ROOT = "main"

_THREAD_CTOR_TERMINALS = {"Thread"}
_STAGE_METHODS = {"map", "map_parallel", "flat_map_parallel"}
_HANDLER_RE = re.compile(r"^do_[A-Za-z]\w*$")
# per-request-instance server substrates: one handler object per request,
# so the class's verb methods never race each other on instance state
_PER_REQUEST_BASES = ("HTTPRequestHandler",)


@dataclass
class ThreadRootIndex:
    """``roots``: function qname → frozenset of root labels (``main`` and/or
    ``<kind>:<entry qname>``).  ``entries``: the discovered background
    entries as ``(kind, entry qname)``."""

    entries: set = field(default_factory=set)
    roots: dict = field(default_factory=dict)

    def roots_of(self, qname: str) -> frozenset:
        """Root labels for ``qname``; a function nothing reaches is treated
        as main-callable (public surface the package doesn't call itself)."""
        return self.roots.get(qname) or frozenset((MAIN_ROOT,))

    @staticmethod
    def render(label: str) -> str:
        """``pool:lakesoul_tpu/runtime/pipeline.py::PipelineIterator._run_item``
        → ``pool:PipelineIterator._run_item`` (messages stay readable AND
        stable — no line numbers)."""
        kind, _, entry = label.partition(":")
        if not entry:
            return label
        return f"{kind}:{entry.rsplit('::', 1)[-1]}"


def _resolve_ref(graph, relpath: str, caller, node: "ast.expr | None"):
    """A function *reference* (Thread target, submit arg, stage fn) resolved
    to a project function qname, or None."""
    if node is None:
        return None
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        if caller is not None and caller.class_qname:
            return graph.resolve_method(caller.class_qname, node.attr)
        return None
    name = dotted_name(node)
    if name is None:
        return None
    return graph.resolve_reference(relpath, caller, name)


def _collect_entries(graph) -> set:
    entries: set = set()
    for caller_q, edges in graph.edges.items():
        relpath = caller_q.split("::", 1)[0]
        caller = graph.functions.get(caller_q)  # None for <module>
        for e in edges:
            call = e.node
            if e.attr in _THREAD_CTOR_TERMINALS:
                target = next(
                    (kw.value for kw in call.keywords if kw.arg == "target"),
                    None,
                )
                q = _resolve_ref(graph, relpath, caller, target)
                if q is not None:
                    entries.add(("thread", q))
            elif e.attr == "submit" and call.args:
                q = _resolve_ref(graph, relpath, caller, call.args[0])
                if q is not None:
                    entries.add(("pool", q))
            elif e.attr in _STAGE_METHODS and call.args:
                q = _resolve_ref(graph, relpath, caller, call.args[0])
                if q is not None:
                    entries.add(("pipeline", q))
            elif e.attr == "source" and call.args and isinstance(call.args[0], ast.Call):
                # .source(f(...)): the generator f builds runs on whichever
                # thread iterates the pipeline — the prefetch pump
                q = _resolve_ref(graph, relpath, caller, call.args[0].func)
                if q is not None:
                    entries.add(("pipeline", q))
    for fn in graph.functions.values():
        terminal = fn.name.rsplit(".", 1)[-1]
        if fn.is_method and _HANDLER_RE.match(terminal):
            entries.add(("handler", fn.qname))
    return entries


def _per_request_class(graph, class_qname: str) -> bool:
    for cq in graph.class_mro(class_qname):
        info = graph.classes.get(cq)
        if info is None:
            continue
        for base in info.base_names:
            if base.rsplit(".", 1)[-1].endswith(_PER_REQUEST_BASES):
                return True
    return False


def build(project: Project) -> ThreadRootIndex:
    graph = project.callgraph()
    idx = ThreadRootIndex()
    idx.entries = _collect_entries(graph)

    roots: dict[str, set[str]] = {}

    def mark_reachable(entry_q: str, label: str) -> None:
        seen = {entry_q}
        stack = [entry_q]
        while stack:
            q = stack.pop()
            roots.setdefault(q, set()).add(label)
            for e in graph.callees(q):
                if e.callee is not None and e.callee not in seen:
                    seen.add(e.callee)
                    stack.append(e.callee)

    for kind, entry_q in idx.entries:
        label = f"{kind}:{entry_q}"
        if kind == "handler":
            fn = graph.functions.get(entry_q)
            if fn is not None and fn.class_qname and _per_request_class(
                graph, fn.class_qname
            ):
                # fresh handler object per request: every verb of the class
                # is the same single thread of control over instance state
                label = f"handler:{fn.class_qname}"
        mark_reachable(entry_q, label)

    # ``main`` reachability: module-level code plus every function the
    # package itself never calls (the public API surface — tests, training
    # loops, and the console enter there), propagated along resolved edges.
    incoming: set[str] = set()
    for edges in graph.edges.values():
        for e in edges:
            if e.callee is not None:
                incoming.add(e.callee)
    entry_qnames = {q for _, q in idx.entries}
    seeds = [q for q in graph.edges if q.endswith("::<module>")]
    seeds += [
        q for q in graph.functions
        if q not in incoming and q not in entry_qnames
    ]
    seen = set(seeds)
    stack = list(seeds)
    while stack:
        q = stack.pop()
        roots.setdefault(q, set()).add(MAIN_ROOT)
        for e in graph.callees(q):
            if e.callee is not None and e.callee not in seen:
                seen.add(e.callee)
                stack.append(e.callee)

    idx.roots = {q: frozenset(r) for q, r in roots.items()}
    return idx


def thread_roots(project: Project) -> ThreadRootIndex:
    """The project's thread-root index, built once and cached (the same
    build-once contract as ``Project.callgraph()``)."""
    if project._thread_roots is None:
        project._thread_roots = build(project)
    return project._thread_roots
