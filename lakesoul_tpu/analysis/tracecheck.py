"""Runtime retrace detector (opt-in: ``LAKESOUL_TRACECHECK=1``).

The static device rules (``trace-*``, ``jit-static-arg-shape``) catch the
*lexical* causes of recompilation; this is their runtime half, in the
:mod:`~lakesoul_tpu.analysis.lockgraph` mold: instrument the jit entry
points themselves and count how many distinct abstract signatures — and
therefore XLA compilations — each function accumulates.  A loader that
feeds un-rebatched tails, a search path that forgets its pow2 bucketing, or
a host wrapper that bakes a data-dependent length into a static arg shows
up here as a per-function signature explosion long before it shows up as a
benchmark regression (compile time is the dominant silent-throughput
killer: a single BERT-step retrace costs more than an epoch of steps).

Mechanics:

- :func:`enable` patches ``jax.jit`` so every jit wrapper built *after*
  enabling returns a counting proxy, and retro-instruments the
  already-imported hot modules (``vector/kernels``, ``vector/kmeans``,
  ``vector/rabitq``) whose jitted functions were created at import time.
- Each top-level call computes the **abstract signature** — per-leaf
  ``(shape, dtype)`` for array arguments, ``repr`` for static ones — and
  records it per function.  Calls made *during another trace* (args are
  tracers; jit-of-jit is inlined, no separate top-level compilation) are
  not counted.
- A function whose distinct-signature count exceeds its **budget**
  (:data:`DEFAULT_BUDGET`, overridable per function via
  :func:`set_budget`) records a :class:`Violation` carrying the full
  signature history, so the failure message shows exactly which
  shapes/dtypes thrashed the cache.

Violations are *recorded*, not raised — instrumentation must never change
program behavior; the conftest fixture fails the test at teardown, exactly
like the lockgraph detector.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

__all__ = [
    "DEFAULT_BUDGET",
    "Violation",
    "enable",
    "disable",
    "enabled",
    "env_requested",
    "reset",
    "set_budget",
    "signature_counts",
    "violations",
    "watch",
]

_ENV = "LAKESOUL_TRACECHECK"

DEFAULT_BUDGET = 8

# module-level jitted functions created at import time: patching jax.jit
# after the fact cannot see them, so enable() rewraps them in place
_HOT_MODULES = (
    "lakesoul_tpu.vector.kernels",
    "lakesoul_tpu.vector.kmeans",
    "lakesoul_tpu.vector.rabitq",
)


@dataclass
class Violation:
    kind: str  # "retrace-budget"
    function: str
    count: int
    budget: int
    signatures: tuple[str, ...] = field(default_factory=tuple)

    def render(self) -> str:
        out = [
            f"[{self.kind}] {self.function} compiled {self.count} distinct "
            f"signatures (budget {self.budget}) — every new abstract "
            "signature is a fresh XLA compilation; bucket/pad the thrashing "
            "dimension or mark it static on purpose"
        ]
        for s in self.signatures:
            out.append(f"  {s}")
        return "\n".join(out)


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = False
        # function label → ordered list of distinct signature strings
        self.signatures: dict[str, list[str]] = {}
        self.budgets: dict[str, int] = {}
        self.violations: list[Violation] = []
        self.reported: set[str] = set()
        # instrumented module attributes to restore on disable:
        # (module, attr name, original object)
        self.patched_attrs: list[tuple] = []
        self.real_jit = None


_STATE = _State()


def _is_tracer(x) -> bool:
    import jax

    return isinstance(x, jax.core.Tracer)


def _leaf_sig(x) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(map(str, shape))}]"
    return repr(x)


def _abstract_signature(args, kwargs) -> str:
    """Per-leaf (shape, dtype) over the call's pytree — the cache key a jit
    wrapper derives, minus donation/layout detail.  Static (non-array)
    leaves contribute their repr: a changed static arg IS a retrace."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    return "(" + ", ".join(_leaf_sig(x) for x in leaves) + ")"


def _record(label: str, sig: str) -> None:
    with _STATE.lock:
        if not _STATE.enabled:
            return
        seen = _STATE.signatures.setdefault(label, [])
        if sig in seen:
            return
        seen.append(sig)
        budget = _STATE.budgets.get(label, DEFAULT_BUDGET)
        if len(seen) > budget and label not in _STATE.reported:
            _STATE.reported.add(label)
            _STATE.violations.append(
                Violation(
                    "retrace-budget", label, len(seen), budget, tuple(seen)
                )
            )
        elif len(seen) > budget:
            # keep the violation's history current past the first overrun
            for v in _STATE.violations:
                if v.function == label:
                    v.count = len(seen)
                    v.signatures = tuple(seen)


class _TraceCheckedFn:
    """Counting proxy around one jit wrapper.  ``__getattr__`` falls through
    so AOT surfaces (``lower``, ``eval_shape``, ``clear_cache``) keep
    working on the instrumented object."""

    def __init__(self, inner, label: str):
        self._inner = inner
        self._label = label

    def __call__(self, *args, **kwargs):
        # cheap fast path first: proxies built while armed outlive
        # disable() (closures, module globals outside the hot modules), so
        # the per-call flatten + signature build must not be paid forever
        # after recording stops
        if _STATE.enabled:
            # tracer args ⇒ this call happens inside an enclosing trace and
            # is inlined there — no top-level compilation of its own
            import jax

            if not any(
                _is_tracer(x) for x in jax.tree_util.tree_leaves((args, kwargs))
            ):
                _record(self._label, _abstract_signature(args, kwargs))
        return self._inner(*args, **kwargs)

    def __getattr__(self, item):
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(item)
        return getattr(inner, item)

    def __repr__(self):
        return f"<tracechecked {self._label}>"


def _label_for(fun) -> str:
    mod = getattr(fun, "__module__", None) or "<unknown>"
    name = getattr(fun, "__qualname__", None) or getattr(
        fun, "__name__", repr(fun)
    )
    return f"{mod}.{name}"


def _checked_jit(real_jit):
    def jit(fun=None, **kwargs):
        if fun is None:
            # decorator-with-kwargs form: jax.jit(static_argnames=...)(f)
            return lambda f: jit(f, **kwargs)
        wrapped = real_jit(fun, **kwargs)
        # functools.partial(f, ...) carries no name; label via its target
        target = getattr(fun, "func", fun)
        return _TraceCheckedFn(wrapped, _label_for(target))

    jit._tracecheck_orig = real_jit
    return jit


def _looks_jitted(obj) -> bool:
    # duck-typing over jaxlib's PjitFunction: the compiled-call surface is
    # stable across versions even when the class name is not
    return (
        callable(obj)
        and not isinstance(obj, type)
        and hasattr(obj, "lower")
        and (hasattr(obj, "clear_cache") or hasattr(obj, "_cache_size"))
    )


def _instrument_hot_modules() -> None:
    import sys

    for modname in _HOT_MODULES:
        mod = sys.modules.get(modname)
        if mod is None:
            continue  # not imported: the jax.jit patch will catch it
        for attr, obj in list(vars(mod).items()):
            if isinstance(obj, _TraceCheckedFn) or not _looks_jitted(obj):
                continue
            label = f"{modname}.{attr}"
            setattr(mod, attr, _TraceCheckedFn(obj, label))
            _STATE.patched_attrs.append((mod, attr, obj))


# ------------------------------------------------------------------ control


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def set_budget(function_label: str, budget: int) -> None:
    """Declare a per-function compilation budget (label as rendered in
    violations: ``module.qualname``).  Applies to future recordings."""
    with _STATE.lock:
        _STATE.budgets[function_label] = budget


def signature_counts() -> dict[str, int]:
    with _STATE.lock:
        return {k: len(v) for k, v in _STATE.signatures.items()}


def violations() -> list[Violation]:
    with _STATE.lock:
        return list(_STATE.violations)


def reset() -> None:
    """Drop recorded signatures/violations (instrumentation stays)."""
    with _STATE.lock:
        _STATE.signatures.clear()
        _STATE.violations.clear()
        _STATE.reported.clear()


def enable() -> None:
    """Patch ``jax.jit`` + retro-instrument hot modules.  Idempotent."""
    if _STATE.enabled:
        return
    import jax

    if not hasattr(jax.jit, "_tracecheck_orig"):
        _STATE.real_jit = jax.jit
        jax.jit = _checked_jit(jax.jit)
    _instrument_hot_modules()
    _STATE.enabled = True


def disable() -> None:
    """Restore ``jax.jit`` and the instrumented module attributes.  Proxies
    already handed out keep delegating; recording stops."""
    if not _STATE.enabled:
        return
    import jax

    orig = getattr(jax.jit, "_tracecheck_orig", None)
    if orig is not None:
        jax.jit = orig
    _STATE.real_jit = None
    for mod, attr, obj in _STATE.patched_attrs:
        setattr(mod, attr, obj)
    _STATE.patched_attrs.clear()
    _STATE.enabled = False


class Watch:
    """Handle yielded by :func:`watch`: violations recorded since entry."""

    def __init__(self, mark: int):
        self._mark = mark

    @property
    def violations(self) -> list[Violation]:
        return violations()[self._mark :]


class watch:
    """``with watch() as w:`` — enable for the block, inspect
    ``w.violations`` after (state is NOT reset on exit so nested watches
    compose; call :func:`reset` between independent scenarios)."""

    def __enter__(self) -> Watch:
        self._was_enabled = _STATE.enabled
        enable()
        return Watch(len(violations()))

    def __exit__(self, *exc):
        if not self._was_enabled:
            disable()
        return False
