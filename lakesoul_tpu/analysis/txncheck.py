"""Runtime transaction-interleaving replay (opt-in: ``LAKESOUL_TXNCHECK=1``).

The static isolation rules (rules/isolation.py) prove store writes are
CAS-*shaped*; this half proves the committed protocols actually survive a
READ COMMITTED backend.  :func:`enable` interposes the metadata store's
two seams — ``SqlMetadataStore._exec`` (every statement) and each class's
``transaction`` contextmanager (the txn boundary PR 19 named) — and
records, per committed transaction, the parsed statement trace with
bound parameter values (:mod:`lakesoul_tpu.analysis.sqlinfo`).  Aborted
transactions record nothing; autocommit writes outside any transaction
become their own single-statement transactions.

:func:`replay` then asks, for every committed transaction T1 that read a
row and later wrote it WITHOUT holding a row lock (``ROW_LOCK``) on the
read: *if a concurrent peer's committed write to the same row had landed
between T1's read and T1's write — which READ COMMITTED permits — would
T1 have silently overwritten it?*  T1's write survives the interleaving
only when it is CAS-shaped (its WHERE re-checks a column the peer
wrote, so the peer's commit makes it match zero rows), self-relative
(``SET x = x + 1`` re-reads inside the statement), or value-idempotent
(both wrote the same values).  Everything else is a lost update, and is
recorded with both transactions' statement traces and the offending
interleaving spelled out.  Peers are transactions on the same store from
a DIFFERENT thread — same-thread transactions are program-ordered and
cannot interleave.  A second pass checks fencing-token monotonicity: the
sequence of token values written per (store, lease_key) must never
decrease across the whole committed history (PR 7's invariant — a
regressing token re-arms a zombie's commit guard).

Violations are *recorded* (never raised — the store must not change
behavior under instrumentation); the conftest fixture calls
:func:`replay` at teardown for ``test_metadata``/``test_lease``/
``test_topology`` and fails the test on any finding, exactly like
lockgraph/fscheck.

Known limits, on purpose: the replay is symbolic (column/value-level over
recorded statements, not a re-execution), DELETE is never treated as the
clobbering write (delete-after-read flows carry range predicates the
model would misjudge), and writes whose values the binder cannot resolve
are assumed idempotent — unknowns must not manufacture alarms.
"""

from __future__ import annotations

import contextlib
import os
import threading
import traceback
from dataclasses import dataclass, field

from lakesoul_tpu.analysis.lockgraph import real_lock
from lakesoul_tpu.analysis.sqlinfo import Statement, bind_values, parse_statement

__all__ = [
    "Txn",
    "TxnStmt",
    "Violation",
    "enable",
    "disable",
    "reset",
    "violations",
    "enabled",
    "env_requested",
    "transactions",
    "replay",
    "watch",
]

_ENV = "LAKESOUL_TXNCHECK"

# per-table row identity: the columns whose bound values decide whether two
# statements can touch the same row(s); a key column a statement leaves
# unconstrained means "all rows" for that column
_KEY_COLS = {
    "lease": ("lease_key",),
    "global_config": ("key",),
    "partition_info": ("table_id", "partition_desc", "version"),
    "data_commit_info": ("table_id", "partition_desc", "commit_id"),
    "table_info": ("table_id",),
    "table_name_id": ("table_name",),
    "table_path_id": ("table_path",),
    "namespace": ("namespace",),
    "discard_compressed_file_info": ("file_path",),
}


@dataclass(frozen=True)
class TxnStmt:
    """One recorded statement: parsed shape + bound values + origin."""

    stmt: Statement
    binds: dict  # {"where": {col: {vals}}, "write": {col: {vals}}}
    stack: str

    def key_vals(self, col: str) -> "set | None":
        """Bound values identifying this statement's rows on ``col`` —
        WHERE bindings for select/update/delete, inserted values for
        insert; None = unconstrained (all rows)."""
        if self.stmt.op == "insert":
            return self.binds["write"].get(col)
        return self.binds["where"].get(col)

    def written_cols(self) -> frozenset:
        """Columns whose stored value this statement overwrites.  Upsert
        conflict targets and insert key columns identify the row rather
        than changing it."""
        if self.stmt.op == "update":
            return self.stmt.set_cols
        if self.stmt.op == "insert":
            keys = frozenset(_KEY_COLS.get(self.stmt.table or "", ()))
            return self.stmt.set_cols - self.stmt.conflict_cols - keys
        return frozenset()


@dataclass
class Txn:
    """One committed transaction in commit order."""

    store_id: int
    thread_id: int
    thread_name: str
    seq: int = 0  # commit order, assigned at commit
    autocommit: bool = False
    stmts: "list[TxnStmt]" = field(default_factory=list)

    def describe(self) -> str:
        ops = ", ".join(
            f"{s.stmt.op.upper()} {s.stmt.table or '?'}" for s in self.stmts
        )
        return (f"txn #{self.seq} (thread {self.thread_name}"
                f"{', autocommit' if self.autocommit else ''}): {ops}")


@dataclass
class Violation:
    kind: str  # "lost-update" | "fencing-regression"
    message: str
    stacks: "tuple[str, ...]" = ()

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}"]
        for s in self.stacks:
            out.append(s.rstrip())
        return "\n".join(out)


class _State:
    def __init__(self):
        self.lock = real_lock()
        self.enabled = False
        self.txns: list[Txn] = []
        self.seq = 0
        self.violations: list[Violation] = []
        self.reported: set = set()
        self.patched: list = []  # (cls, attr, original) for disable()


_STATE = _State()
_TLS = threading.local()


def _stack_summary() -> str:
    frames = [
        fr
        for fr in traceback.extract_stack()
        if "lakesoul_tpu/analysis/txncheck" not in fr.filename.replace("\\", "/")
    ]
    return "\n".join(
        f"  {fr.filename}:{fr.lineno} in {fr.name}" for fr in frames[-8:]
    )


_PARSE_CACHE: dict = {}


def _parse_cached(sql: str) -> "Statement | None":
    stmt = _PARSE_CACHE.get(sql, False)
    if stmt is False:
        stmt = parse_statement(sql)
        _PARSE_CACHE[sql] = stmt
    return stmt


def _commit(txn: Txn) -> None:
    with _STATE.lock:
        _STATE.seq += 1
        txn.seq = _STATE.seq
        _STATE.txns.append(txn)


def _record_stmt(store, sql: str, params) -> None:
    stmt = _parse_cached(sql)
    if stmt is None or stmt.op in ("pragma", "other"):
        return
    try:
        bound = bind_values(stmt, tuple(params or ()))
    except Exception:
        bound = {"where": {}, "write": {}}
    entry = TxnStmt(stmt, bound, _stack_summary())
    stack = getattr(_TLS, "txns", None)
    if stack:
        for open_txn in reversed(stack):
            if open_txn.store_id == id(store):
                open_txn.stmts.append(entry)
                return
    if stmt.op == "select":
        return  # autocommit reads cannot anchor a read-then-write
    _commit(Txn(
        id(store), threading.get_ident(), threading.current_thread().name,
        autocommit=True, stmts=[entry],
    ))


# ------------------------------------------------------------ interposition


def _traced_exec(orig):
    def _exec(self, conn, sql, params=()):
        if _STATE.enabled:
            try:
                _record_stmt(self, sql, params)
            except Exception:
                pass
        return orig(self, conn, sql, params)

    _exec._txncheck_orig = orig
    return _exec


def _traced_transaction(orig):
    @contextlib.contextmanager
    def _cm(self):
        if not _STATE.enabled:
            with orig(self) as conn:
                yield conn
            return
        txn = Txn(id(self), threading.get_ident(),
                  threading.current_thread().name)
        stack = getattr(_TLS, "txns", None)
        if stack is None:
            stack = _TLS.txns = []
        stack.append(txn)
        try:
            with orig(self) as conn:
                yield conn
        except BaseException:
            stack.remove(txn)  # aborted: its statements never happened
            raise
        else:
            stack.remove(txn)
            _commit(txn)

    def transaction(self):
        return _cm(self)

    transaction._txncheck_orig = orig
    return transaction


def _store_classes():
    from lakesoul_tpu.meta.store import SqlMetadataStore

    out = [SqlMetadataStore]
    pending = list(SqlMetadataStore.__subclasses__())
    while pending:
        cls = pending.pop()
        out.append(cls)
        pending.extend(cls.__subclasses__())
    return out


def enable() -> None:
    """Interpose the store seams.  Idempotent.  ``SqliteMetadataStore``'s
    ``_exec`` override funnels through ``super()._exec``, so patching the
    base records each statement exactly once; ``transaction`` is patched
    on every class that defines it so the most-derived override is the
    one wrapped."""
    if _STATE.enabled:
        return
    for cls in _store_classes():
        if "_exec" in cls.__dict__ and cls.__name__ == "SqlMetadataStore":
            orig = cls.__dict__["_exec"]
            cls._exec = _traced_exec(orig)
            _STATE.patched.append((cls, "_exec", orig))
        if "transaction" in cls.__dict__:
            orig = cls.__dict__["transaction"]
            cls.transaction = _traced_transaction(orig)
            _STATE.patched.append((cls, "transaction", orig))
    _STATE.enabled = True


def disable() -> None:
    """Restore the real seams.  Recorded history stays for inspection and
    :func:`replay` until :func:`reset`."""
    if not _STATE.enabled:
        return
    for cls, attr, orig in _STATE.patched:
        setattr(cls, attr, orig)
    _STATE.patched.clear()
    _STATE.enabled = False


def enabled() -> bool:
    return _STATE.enabled


def env_requested() -> bool:
    return os.environ.get(_ENV, "").strip() == "1"


def violations() -> list[Violation]:
    with _STATE.lock:
        return list(_STATE.violations)


def transactions() -> list[Txn]:
    with _STATE.lock:
        return list(_STATE.txns)


def reset() -> None:
    with _STATE.lock:
        _STATE.txns.clear()
        _STATE.seq = 0
        _STATE.violations.clear()
        _STATE.reported.clear()


class Watch:
    def __init__(self, mark: int):
        self._mark = mark

    @property
    def violations(self) -> list[Violation]:
        return violations()[self._mark:]


class watch:
    """``with watch() as w:`` — enable for the block; call :func:`replay`
    (inside or after) and inspect ``w.violations``."""

    def __enter__(self) -> Watch:
        self._was_enabled = _STATE.enabled
        enable()
        return Watch(len(violations()))

    def __exit__(self, *exc):
        if not self._was_enabled:
            disable()
        return False


# ------------------------------------------------------------------- replay


def _rows_may_overlap(table: str, a: TxnStmt, b: TxnStmt) -> bool:
    """False only when some key column is bound by BOTH statements to
    provably disjoint value sets."""
    for col in _KEY_COLS.get(table, ()):
        va, vb = a.key_vals(col), b.key_vals(col)
        if va is not None and vb is not None and not (va & vb):
            return False
    return True


def _row_desc(table: str, s: TxnStmt) -> str:
    parts = []
    for col in _KEY_COLS.get(table, ()):
        vals = s.key_vals(col)
        if vals is not None:
            parts.append(f"{col}={sorted(map(repr, vals))[0] if len(vals) == 1 else sorted(map(repr, vals))}")
    return f"{table}[{', '.join(parts) or '*'}]"


def _values_differ(w: TxnStmt, peer: TxnStmt, cols) -> bool:
    """True only when some overlapping column has KNOWN, different values
    on both sides — unknowns must not manufacture alarms."""
    for col in cols:
        va = w.binds["write"].get(col)
        vb = peer.binds["write"].get(col)
        if va and vb and not (va & vb):
            return True
    return False


def _add_violation(kind: str, message: str, stacks: tuple, key) -> None:
    with _STATE.lock:
        if key in _STATE.reported:
            return
        _STATE.reported.add(key)
        _STATE.violations.append(Violation(kind, message, stacks))


def _check_lost_updates(txns: "list[Txn]") -> None:
    for t1 in txns:
        if t1.autocommit:
            continue  # a single statement cannot straddle a peer's commit
        for wi, w in enumerate(t1.stmts):
            if w.stmt.op != "update":
                continue
            table = w.stmt.table
            if table not in _KEY_COLS:
                continue
            if w.stmt.set_cols and w.stmt.set_cols <= w.stmt.relative_cols:
                continue  # SET x = f(x): the statement re-reads atomically
            reads = [
                r for r in t1.stmts[:wi]
                if r.stmt.op == "select" and r.stmt.table == table
                and not r.stmt.row_locked and _rows_may_overlap(table, r, w)
            ]
            if not reads:
                continue  # no splittable read-then-write in this txn
            for t2 in txns:
                if (t2 is t1 or t2.store_id != t1.store_id
                        or t2.thread_id == t1.thread_id):
                    continue
                for w2 in t2.stmts:
                    if w2.stmt.op not in ("update", "insert"):
                        continue
                    if w2.stmt.table != table:
                        continue
                    if not _rows_may_overlap(table, w, w2):
                        continue
                    peer_set = w2.written_cols()
                    if w.stmt.where_cols & peer_set:
                        continue  # CAS: the peer's write voids our WHERE
                    clobbered = (
                        (w.stmt.set_cols - w.stmt.relative_cols) & peer_set
                    )
                    if not clobbered:
                        continue
                    if not _values_differ(w, w2, clobbered):
                        continue  # idempotent (or unknowable) writes
                    row = _row_desc(table, w)
                    _add_violation(
                        "lost-update",
                        f"{t1.describe()} reads {row} without ROW_LOCK, "
                        f"then writes {sorted(clobbered)} re-checking only "
                        f"{sorted(w.stmt.where_cols)} — under READ "
                        f"COMMITTED the peer {t2.describe()} can commit "
                        "between the read and the write, and this UPDATE "
                        "silently overwrites it.  Offending interleaving: "
                        f"txn #{t1.seq} SELECT {row} -> txn #{t2.seq} "
                        f"commits {w2.stmt.op.upper()} {row} -> txn "
                        f"#{t1.seq} UPDATE {row} (matches anyway: WHERE "
                        "re-checks none of the peer's written columns)",
                        (
                            f"txn #{t1.seq} read:\n{reads[-1].stack}",
                            f"txn #{t1.seq} write:\n{w.stack}",
                            f"txn #{t2.seq} peer write:\n{w2.stack}",
                        ),
                        ("lost-update", t1.seq, t2.seq, w.stmt.text),
                    )


def _check_fencing(txns: "list[Txn]") -> None:
    """Token values written per (store, lease_key) must be non-decreasing
    in commit order.  A DELETE that could have removed lease rows (table
    resolved to lease, or unresolvable — ``clean_all_for_test``'s dynamic
    table names) resets that store's sequences: the row's history ended."""
    high: dict = {}
    for txn in txns:
        for s in txn.stmts:
            if s.stmt.op == "delete" and s.stmt.table in ("lease", None):
                high = {k: v for k, v in high.items() if k[0] != txn.store_id}
                continue
            if s.stmt.table != "lease" or "fencing_token" not in s.binds["write"]:
                continue
            keys = s.key_vals("lease_key")
            tokens = s.binds["write"]["fencing_token"]
            if not keys or not tokens:
                continue
            token = max(t for t in tokens if isinstance(t, int))
            for key in keys:
                prev = high.get((txn.store_id, key))
                if prev is not None and token < prev[0]:
                    _add_violation(
                        "fencing-regression",
                        f"lease[{key!r}] fencing token regressed "
                        f"{prev[0]} -> {token} (txn #{prev[1]} then txn "
                        f"#{txn.seq}) — a zombie ex-holder's stale token "
                        "would pass the commit guard again; tokens must "
                        "be monotonic per key for the table's lifetime",
                        (f"txn #{txn.seq} write:\n{s.stack}",),
                        ("fencing", txn.store_id, key, token),
                    )
                if prev is None or token > prev[0]:
                    high[(txn.store_id, key)] = (token, txn.seq)


def replay() -> list[Violation]:
    """Replay the committed history under READ COMMITTED interleavings.
    New violations are recorded (and returned) — never raised.  Idempotent
    over the same history: findings dedupe by identity."""
    with _STATE.lock:
        txns = list(_STATE.txns)
    if not txns:
        return []
    mark = len(violations())
    _check_lost_updates(txns)
    _check_fencing(txns)
    return violations()[mark:]
