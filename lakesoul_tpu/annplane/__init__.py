"""Sharded ANN plane: memory-bounded multi-shard build, ragged query
batching into the scoring kernels, fleet-scale QPS serving."""

from lakesoul_tpu.annplane.build import (
    ShardedAnnBuilder,
    build_table_ann_plane,
    iter_table_vectors,
)
from lakesoul_tpu.annplane.collective import cross_chip_topk, dryrun_multichip
from lakesoul_tpu.annplane.config import AnnPlaneConfig
from lakesoul_tpu.annplane.manifest import PlaneManifestStore
from lakesoul_tpu.annplane.search import AnnPlane
from lakesoul_tpu.annplane.serving import AnnPlaneBinding, ShardedAnnEndpoint

__all__ = [
    "AnnPlane",
    "AnnPlaneBinding",
    "AnnPlaneConfig",
    "PlaneManifestStore",
    "ShardedAnnBuilder",
    "ShardedAnnEndpoint",
    "build_table_ann_plane",
    "cross_chip_topk",
    "dryrun_multichip",
    "iter_table_vectors",
]
