"""Memory-bounded multi-shard plane build over a vector stream.

The builder consumes an ordered stream of ``(vectors [n, dim] f32,
ids [n] u64)`` batches — from the bounded scan path when the corpus lives in
a lakehouse table (:func:`iter_table_vectors` rides
``iter_scan_unit_batches``, so decode memory is governed by the table's
``memory_budget_bytes``) or from any deterministic generator — and cuts it
into shards of exactly ``config.rows_per_shard()`` rows.  Only ONE shard's
working set is ever resident; each shard trains/inserts through the
existing :class:`IvfRabitqIndex` and persists through the per-shard
``ManifestStore``, then a plane-level progress record lands atomically
(manifest.py).

Resume contract: the stream must be deterministic (the scan path is — same
plan, same order).  A restarted builder reads the newest plane record,
verifies the config digest, SKIPS exactly the rows covered by completed
shards, and continues with the next shard index — shard-exact, no partial
shard is ever visible."""

from __future__ import annotations

import time

import numpy as np

from lakesoul_tpu.annplane.config import AnnPlaneConfig
from lakesoul_tpu.annplane.manifest import PlaneManifestStore
from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.obs import registry
from lakesoul_tpu.vector.index import IvfRabitqIndex
from lakesoul_tpu.vector.manifest import ManifestStore

INSERT_CHUNK_ROWS = 262_144


def shard_root(root: str, shard: int) -> str:
    return f"{root.rstrip('/')}/shard_{shard:05d}"


class ShardedAnnBuilder:
    def __init__(
        self,
        root: str,
        config: AnnPlaneConfig,
        *,
        storage_options: dict | None = None,
    ):
        self.root = root.rstrip("/")
        self.config = config
        self.storage_options = storage_options or {}
        self.store = PlaneManifestStore(self.root, self.storage_options)
        reg = registry()
        self._c_rows = reg.counter("lakesoul_ann_build_rows_total")
        self._g_shards = reg.gauge("lakesoul_ann_plane_shards")
        self._h_shard = reg.histogram("lakesoul_ann_shard_build_seconds")

    # ------------------------------------------------------------------ build
    def build(self, batches, *, resume: bool = True) -> dict:
        """Stream ``batches`` into shards; returns the complete plane
        manifest.  ``resume=False`` forces a fresh generation regardless of
        prior progress."""
        digest = self.config.digest()
        shards: list[dict] = []
        generation = 1
        prior = self.store.read() if resume else None
        if prior is not None:
            if prior.get("config_digest") == digest:
                if prior.get("complete"):
                    return prior  # nothing to do: the plane is durable
                shards = list(prior.get("shards", ()))
                generation = prior["generation"]
            else:
                # layout changed (dim/bits/budget/...): row ranges no longer
                # line up — rebuild everything under a bumped generation so
                # a torn old plane can never be half-read as the new one
                generation = prior["generation"] + 1
        elif not resume:
            stale = self.store.read()
            if stale is not None:
                generation = stale["generation"] + 1

        rows_per_shard = self.config.rows_per_shard()
        resume_row = shards[-1]["row_end"] if shards else 0
        dim = self.config.index.dim

        buf_v: list[np.ndarray] = []
        buf_i: list[np.ndarray] = []
        buffered = 0
        cursor = 0  # absolute stream row position

        def flush_shard() -> None:
            nonlocal buffered
            vectors = np.concatenate(buf_v) if len(buf_v) > 1 else buf_v[0]
            ids = np.concatenate(buf_i) if len(buf_i) > 1 else buf_i[0]
            buf_v.clear()
            buf_i.clear()
            buffered = 0
            start = time.perf_counter()
            entry = self._build_shard(len(shards), vectors, ids)
            self._h_shard.observe(time.perf_counter() - start)
            entry["row_start"] = shards[-1]["row_end"] if shards else 0
            entry["row_end"] = entry["row_start"] + len(ids)
            shards.append(entry)
            self._c_rows.inc(len(ids))
            self._g_shards.set(len(shards))
            self.store.write(self._manifest(generation, digest, shards, False))

        for vectors, ids in batches:
            vectors = np.ascontiguousarray(vectors, dtype=np.float32)
            ids = np.asarray(ids, dtype=np.uint64)
            if vectors.ndim != 2 or vectors.shape[1] != dim:
                raise VectorIndexError(
                    f"expected [n, {dim}] vectors, got {vectors.shape}"
                )
            if len(ids) != len(vectors):
                raise VectorIndexError("ids/vectors length mismatch")
            n = len(ids)
            if cursor + n <= resume_row:  # fully covered by durable shards
                cursor += n
                continue
            if cursor < resume_row:  # batch straddles the resume point
                off = resume_row - cursor
                vectors, ids = vectors[off:], ids[off:]
                cursor = resume_row
                n = len(ids)
            cursor += n
            while len(ids):
                take = min(rows_per_shard - buffered, len(ids))
                buf_v.append(vectors[:take])
                buf_i.append(ids[:take])
                buffered += take
                vectors, ids = vectors[take:], ids[take:]
                if buffered == rows_per_shard:
                    flush_shard()

        if buffered:
            flush_shard()
        if not shards:
            raise VectorIndexError("no vectors to build an ANN plane from")
        manifest = self._manifest(generation, digest, shards, True)
        self.store.write(manifest)
        return manifest

    def _manifest(self, generation, digest, shards, complete) -> dict:
        return {
            "generation": generation,
            "config_digest": digest,
            "index_config": self.config.index.encode(),
            "keep_raw": self.config.keep_raw,
            "shard_budget_bytes": self.config.budget_bytes,
            "rows_per_shard": self.config.rows_per_shard(),
            "total_rows": shards[-1]["row_end"] if shards else 0,
            "complete": bool(complete),
            "shards": list(shards),
        }

    # ------------------------------------------------------------ shard build
    def _build_shard(self, shard: int, vectors: np.ndarray, ids: np.ndarray) -> dict:
        cfg = self.config.index
        sample_rows = self.config.train_sample_rows
        if len(vectors) <= sample_rows:
            index = IvfRabitqIndex.train(
                vectors, ids, cfg,
                keep_raw=self.config.keep_raw,
                kmeans_iters=self.config.kmeans_iters,
            )
        else:
            # k-means wants a sample, not the shard: train centroids on a
            # seeded unbiased subsample, then drop the sample rows and insert
            # EVERY row in bounded chunks (same discipline as the per-bucket
            # VectorShardIndexBuilder's oversized path)
            rng = np.random.default_rng(cfg.seed + shard)
            sel = rng.choice(len(vectors), sample_rows, replace=False)
            index = IvfRabitqIndex.train(
                vectors[sel], ids[sel], cfg,
                keep_raw=self.config.keep_raw,
                kmeans_iters=self.config.kmeans_iters,
            )
            index.clusters = [
                index._make_cluster(
                    np.zeros((0, cfg.dim), np.float32),
                    np.zeros(0, np.uint64),
                    index.centroids[c],
                )
                for c in range(len(index.centroids))
            ]
            for lo in range(0, len(vectors), INSERT_CHUNK_ROWS):
                index.insert_batch(
                    vectors[lo : lo + INSERT_CHUNK_ROWS],
                    ids[lo : lo + INSERT_CHUNK_ROWS],
                )
            index.merge_deltas()
        store = ManifestStore(shard_root(self.root, shard), self.storage_options)
        gen = store.write_index(index)
        return {
            "shard": shard,
            "num_vectors": int(index.num_vectors),
            "generation": gen,
        }


# ----------------------------------------------------------------- table feed
def iter_table_vectors(
    table,
    column: str,
    id_column: str,
    *,
    batch_size: int = 65_536,
    memory_budget_bytes: int | None = None,
    partitions: dict[str, str] | None = None,
):
    """Stream ``(vectors, ids)`` from a table column through the bounded
    scan path (``iter_scan_unit_batches``) — unit order follows the scan
    plan, so the stream is deterministic and resume-safe."""
    import pyarrow as pa

    from lakesoul_tpu.io.reader import iter_scan_unit_batches
    from lakesoul_tpu.vector.builder import extract_vectors

    info = table.info
    io_cfg = table.io_config()
    budget = (
        io_cfg.memory_budget_bytes if memory_budget_bytes is None
        else memory_budget_bytes
    )
    field = info.arrow_schema.field(column)
    dim = field.type.list_size if hasattr(field.type, "list_size") else None
    scan = table.scan()
    if partitions:
        scan = scan.partitions(partitions)
    for unit in scan.scan_plan():
        for batch in iter_scan_unit_batches(
            unit.data_files,
            unit.primary_keys,
            batch_size=batch_size,
            memory_budget_bytes=budget,
            file_sizes=getattr(unit, "file_sizes", None),
            schema=info.arrow_schema,
            partition_values=unit.partition_values,
            columns=[column, id_column],
            storage_options=table.catalog.storage_options,
        ):
            t = pa.Table.from_batches([batch])
            if len(t) == 0:
                continue
            if dim is None:
                first = t.column(column).combine_chunks()
                dim = len(first[0])
            yield extract_vectors(t, column, id_column, dim)


def build_table_ann_plane(
    table,
    column: str,
    *,
    root: str | None = None,
    config: AnnPlaneConfig | None = None,
    id_column: str | None = None,
    resume: bool = True,
    **cfg_kw,
) -> dict:
    """Build (or resume) the plane of a table's vector column.  The plane
    lives beside the table at ``{table_path}/_ann_plane/{column}`` unless
    ``root`` overrides it."""
    import pyarrow as pa

    from lakesoul_tpu.vector.config import VectorIndexConfig

    info = table.info
    if id_column is None:
        if len(info.primary_keys) != 1:
            raise VectorIndexError(
                "ann plane needs id_column= or a single-PK table; table has"
                f" PK {info.primary_keys}"
            )
        id_column = info.primary_keys[0]
    if config is None:
        t = info.arrow_schema.field(column).type
        if pa.types.is_fixed_size_list(t):
            dim = t.list_size
        elif "dim" in cfg_kw:
            dim = cfg_kw.pop("dim")
        else:
            raise VectorIndexError("dim required for non-fixed-size-list columns")
        budget = cfg_kw.pop("shard_budget_bytes", None)
        keep_raw = cfg_kw.pop("keep_raw", True)
        config = AnnPlaneConfig(
            index=VectorIndexConfig(column=column, dim=dim, **cfg_kw),
            shard_budget_bytes=budget,
            keep_raw=keep_raw,
        )
    if root is None:
        root = f"{info.table_path}/_ann_plane/{column}"
    builder = ShardedAnnBuilder(
        root, config, storage_options=table.catalog.storage_options
    )
    return builder.build(
        iter_table_vectors(table, column, id_column), resume=resume
    )
