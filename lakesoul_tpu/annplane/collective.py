"""Cross-chip top-k merge for device-sharded planes.

When shards live in different chips' HBM, each chip produces its local
top-k and the plane needs ONE global top-k without shipping full candidate
sets to the host.  The merge is a ``shard_map`` over the shard axis:
``lax.all_gather`` the (distances, local rows) pairs — k entries per chip,
tiny — then every chip computes the identical merged top-k with
``lax.top_k`` (replicated output, no host round-trip in the middle).

Row ids cross the collective as int32 LOCAL row indices (JAX x64 stays
off); the host maps (source shard, local row) back to u64 ids after the
single readback.  ``dryrun_multichip`` runs the whole merge on
``xla_force_host_platform_device_count`` CPU devices — the same discipline
as ``__graft_entry__.dryrun_multichip`` — and verifies against the host
oracle merge."""

from __future__ import annotations

import functools

import numpy as np

from lakesoul_tpu.errors import VectorIndexError

AXIS = "shards"


@functools.cache
def _merge_fn(n_dev: int, k: int):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from lakesoul_tpu.parallel._compat import shard_map

    devices = jax.devices()[:n_dev]
    mesh = Mesh(np.array(devices), (AXIS,))

    def body(d, r):
        from jax import lax

        gd = lax.all_gather(d[0], AXIS)            # [n_dev, k_local]
        gr = lax.all_gather(r[0], AXIS)            # [n_dev, k_local]
        k_local = gd.shape[1]
        flat_d = gd.reshape(-1)
        neg, idx = lax.top_k(-flat_d, k)
        src = (idx // k_local).astype(np.int32)
        slot = (idx % k_local).astype(np.int32)
        rows = gr.reshape(-1)[idx]
        return (-neg)[None], rows[None], src[None], slot[None]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None)),
        out_specs=(P(AXIS, None),) * 4,
        check_vma=False,
    )
    return jax.jit(fn), mesh


def cross_chip_topk(dists: np.ndarray, rows: np.ndarray, *, k: int | None = None):
    """Merge per-shard top-k candidates on-device.

    ``dists``/``rows``: [n_shards, k_local] (f32 / int32 local row indices);
    needs ``n_shards`` visible devices (one shard per chip).  Returns
    (merged dists [k], rows [k], source shard [k]) as numpy."""
    import jax

    dists = np.asarray(dists, np.float32)
    rows = np.asarray(rows, np.int32)
    n_dev, k_local = dists.shape
    if rows.shape != dists.shape:
        raise VectorIndexError("dists/rows shape mismatch")
    if len(jax.devices()) < n_dev:
        raise VectorIndexError(
            f"cross_chip_topk needs {n_dev} devices, only"
            f" {len(jax.devices())} visible"
        )
    k = k_local if k is None else min(k, n_dev * k_local)
    fn, _mesh = _merge_fn(n_dev, k)
    d, r, src, _slot = fn(dists, rows)
    # out specs shard the replicated result over the axis again; every
    # shard's slice is identical, so read shard 0's copy
    return np.asarray(d)[0], np.asarray(r)[0], np.asarray(src)[0]


def dryrun_multichip(n_devices: int = 8, *, k: int = 10, seed: int = 0) -> dict:
    """One cross-chip merge over ``n_devices`` with seeded candidates,
    verified against the host oracle.  Raises on any divergence; returns
    the merged result for the record."""
    rng = np.random.default_rng(seed)
    local_k = 2 * k
    dists = rng.random((n_devices, local_k)).astype(np.float32)
    rows = rng.integers(0, 1 << 20, (n_devices, local_k)).astype(np.int32)
    d, r, src = cross_chip_topk(dists, rows, k=k)

    flat_d = dists.reshape(-1)
    order = np.argsort(flat_d, kind="stable")[:k]
    np.testing.assert_allclose(d, flat_d[order], rtol=1e-6)
    np.testing.assert_array_equal(r, rows.reshape(-1)[order])
    np.testing.assert_array_equal(src, (order // local_k).astype(np.int32))
    return {"devices": n_devices, "k": k, "dists": d.tolist()}
