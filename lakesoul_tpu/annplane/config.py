"""ANN-plane configuration: memory-bounded shard sizing.

A plane is a sequence of IVF-RaBitQ shards over one vector stream; each
shard is sized so the BUILD of that shard (streamed raw buffer + quantized
arrays + the index's raw copy) fits ``shard_budget_bytes`` — the builder
never holds more than one shard's working set, so a 10M x 128d corpus
builds inside a laptop-sized RSS.  Shard row ranges derive from the budget,
which makes them part of the plane's identity: the config digest covers the
index config, raw retention, and the derived rows-per-shard, so a restarted
builder either resumes shard-exact or (on any mismatch) rebuilds from row 0
under a bumped generation."""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.rabitq import next_pow2

ENV_SHARD_BUDGET = "LAKESOUL_ANN_SHARD_BUDGET_BYTES"
DEFAULT_SHARD_BUDGET = 512 << 20


def _env_budget() -> int:
    raw = os.environ.get(ENV_SHARD_BUDGET)
    if raw is None:
        return DEFAULT_SHARD_BUDGET
    try:
        v = int(raw)
    except ValueError:
        raise VectorIndexError(f"{ENV_SHARD_BUDGET} must be an integer, got {raw!r}")
    if v <= 0:
        raise VectorIndexError(f"{ENV_SHARD_BUDGET} must be positive, got {v}")
    return v


@dataclass(frozen=True)
class AnnPlaneConfig:
    """One plane's build/search contract.

    ``shard_budget_bytes`` None resolves from ``LAKESOUL_ANN_SHARD_BUDGET_BYTES``
    (default 512 MiB) at construction time, so the frozen instance — and its
    digest — never depends on later environment changes."""

    index: VectorIndexConfig
    shard_budget_bytes: int | None = None
    keep_raw: bool = True
    train_sample_rows: int = 200_000
    kmeans_iters: int = 10
    # resolved at __post_init__; field so dataclass repr/eq include it
    _budget: int = field(default=0, repr=False)

    def __post_init__(self):
        budget = (
            _env_budget() if self.shard_budget_bytes is None
            else int(self.shard_budget_bytes)
        )
        if budget <= 0:
            raise VectorIndexError(f"shard budget must be positive, got {budget}")
        object.__setattr__(self, "_budget", budget)
        if budget < self.bytes_per_vector():
            raise VectorIndexError(
                f"shard budget {budget} bytes cannot hold even one"
                f" {self.index.dim}-d vector ({self.bytes_per_vector()} B/row)"
            )

    @property
    def budget_bytes(self) -> int:
        return self._budget

    def padded_dim(self) -> int:
        return (
            next_pow2(self.index.dim) if self.index.rotator == "fht" else self.index.dim
        )

    def bytes_per_vector(self) -> int:
        """Build-time working-set bytes per row: the streamed f32 buffer, the
        quantized arrays, per-row scalars + id, and (when kept) the index's
        raw copy — what one shard actually costs while it is being built."""
        d, dpad = self.index.dim, self.padded_dim()
        buffered_raw = d * 4
        if self.index.total_bits == 1:
            codes = dpad // 8
            scalars = 3 * 4  # norms, factors, code_dot_c
        else:
            codes = dpad * (1 if self.index.total_bits <= 8 else 2)
            scalars = 4 * 4  # + scales
        indexed_raw = d * 4 if self.keep_raw else 0
        return buffered_raw + codes + scalars + 8 + indexed_raw

    def rows_per_shard(self) -> int:
        return max(1, self._budget // self.bytes_per_vector())

    def digest(self) -> str:
        """Identity of the plane layout: anything that changes shard contents
        or row ranges changes the digest (and forces a fresh generation)."""
        key = "|".join(
            [
                self.index.encode(),
                str(self.keep_raw),
                str(self.rows_per_shard()),
                str(self.train_sample_rows),
                str(self.kmeans_iters),
            ]
        )
        return hashlib.sha256(key.encode()).hexdigest()[:16]
