"""Plane-level manifest: the atomic record of a multi-shard build.

Same pointer-swap discipline as the per-shard ``ManifestStore`` (vector/
manifest.py): every progress state is written as a fresh immutable
``plane/plane-<gen>-<seq>.json`` blob (CRC-wrapped), then the ``PLANE``
pointer is overwritten to name it — readers either see the previous complete
record or the new one, never a torn write.  The builder writes one record
per persisted shard, so the newest record doubles as the resume cursor:
``shards[-1].row_end`` is exactly how many stream rows are durably indexed."""

from __future__ import annotations

import json

from lakesoul_tpu.io.object_store import ensure_dir, filesystem_for
from lakesoul_tpu.runtime import atomicio
from lakesoul_tpu.vector.manifest import _crc_unwrap, _crc_wrap

POINTER = "PLANE"


class PlaneManifestStore:
    def __init__(self, root: str, storage_options: dict | None = None):
        self.root = root.rstrip("/")
        self.storage_options = storage_options or {}
        self.fs, self.root_path = filesystem_for(
            self.root, self.storage_options, write=True
        )

    # ------------------------------------------------------------------ write
    def write(self, manifest: dict) -> None:
        """Persist one progress/completion record and swap the pointer."""
        ensure_dir(f"{self.root}/plane", self.storage_options)
        rel = (
            f"plane/plane-{manifest['generation']}-"
            f"{len(manifest.get('shards', ())):05d}"
            f"{'c' if manifest.get('complete') else ''}.json"
        )
        self._write_blob(rel, _crc_wrap(json.dumps(manifest).encode()))
        self._write_blob(POINTER, _crc_wrap(rel.encode()))

    def _write_blob(self, rel: str, data: bytes) -> None:
        # the PLANE pointer is overwritten per progress record; atomicio
        # keeps a crashed overwrite old-or-new instead of torn
        atomicio.publish_bytes_fs(self.fs, f"{self.root_path}/{rel}", data)

    # ------------------------------------------------------------------- read
    def read(self) -> dict | None:
        """Newest durable record, or None when the plane was never written.
        A corrupt pointer or record raises (CRC mismatch is damage, not
        absence — silently restarting a 10M-row build hides it)."""
        try:
            with self.fs.open(f"{self.root_path}/{POINTER}", "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        rel = _crc_unwrap(blob, POINTER).decode()
        with self.fs.open(f"{self.root_path}/{rel}", "rb") as f:
            payload = f.read()
        return json.loads(_crc_unwrap(payload, rel))

    def exists(self) -> bool:
        return self.fs.exists(f"{self.root_path}/{POINTER}")
