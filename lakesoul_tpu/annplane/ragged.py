"""Ragged query batching for multi-shard ANN scoring.

A serving micro-batch holds Q concurrent queries with DIFFERENT ``nprobe``
and different probed-cluster sets.  The rectangular resident kernels
(vector/kernels.py) score every row for every query — fine at 200k rows,
three orders of magnitude of wasted MXU work at 10M.  This module is the
Ragged-Paged-Attention answer (arxiv 2604.15464): flatten the micro-batch
into (query, cluster-tile) WORK ITEMS, run one grid over the items, and let
scalar-prefetched item tables drive the BlockSpec index maps so each grid
step DMAs exactly its cluster tile and its query row — no (rows x queries)
rectangle ever exists.

Estimator (global query frame, shared with vector/kernels.py): per row
    est = b + csq - h * csum - a * g,      g = codes_f · P(query)
where ``codes_f``/``a``/``b``/``h`` are build-time per-row constants
(:func:`fold_cluster`, one definition for 1-bit and ex-codes) and
``csq``/``csum`` are per-(query, cluster) scalars the planner computes on
the host.  Three interchangeable executors, differential-tested:

- :func:`ragged_score_pallas` — the TPU kernel (PrefetchScalarGridSpec);
- :func:`ragged_score_jnp`    — same item layout in pure jnp (interpreter
  twin for CPU differential tests);
- :func:`ragged_topk_host`    — the host production path: per-cluster
  grouped GEMMs with a vectorized ragged transpose into query-major order
  (what actually serves on CPU fallback; identical math, no item padding).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 128  # rows per work item (a "page" in RPA terms)
# pad rows/items carry this additive constant: estimated distances become
# huge-but-finite (inf would poison a*g arithmetic), and the top-k tail
# treats anything above PAD_EST_VALID as a hole
PAD_B = np.float32(1e30)
PAD_EST_VALID = np.float32(1e29)


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def ragged_arange(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenate ``arange(s, s + c)`` for each (s, c) pair, vectorized."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    base = np.repeat(np.asarray(starts, np.int64), counts)
    resets = np.repeat(np.cumsum(counts) - counts, counts)
    return base + (np.arange(total, dtype=np.int64) - resets)


def fold_cluster(norms, factors, code_dot_c, *, d: int, ex: bool):
    """Fold per-row RaBitQ constants into the (a, b, h) form of the ragged
    estimator.  ``ex`` selects the ex-code estimator (csum unused, h = 0);
    the 1-bit path folds the 1/sqrt(D) bit-plane normalization in."""
    norms = np.asarray(norms, np.float32)
    factors = np.asarray(factors, np.float32)
    cdc = np.asarray(code_dot_c, np.float32)
    if ex:
        a = 2.0 * norms / factors
        b = norms * norms + a * cdc
        h = np.zeros_like(a)
    else:
        root_d = np.float32(np.sqrt(d))
        hh = 2.0 * norms / (factors * root_d)
        a = 2.0 * hh
        b = norms * norms + a * cdc
        h = hh
    return a.astype(np.float32), b.astype(np.float32), h.astype(np.float32)


# --------------------------------------------------------------------------
# Pallas kernel: one grid step = one (query, cluster-tile) work item
# --------------------------------------------------------------------------


def _ragged_score_kernel(
    item_q_ref, item_tile_ref, q_ref, csq_ref, csum_ref,
    codes_ref, a_ref, b_ref, h_ref, out_ref,
):
    """codes block [TILE, d] x this item's query row [1, d] → one MXU
    matvec, fused with the affine correction into estimated sq-distances.
    The scalar-prefetch refs (item_q/item_tile) are consumed by the
    BlockSpec index maps, not the body."""
    del item_q_ref, item_tile_ref
    g = jnp.dot(codes_ref[:], q_ref[:].T, preferred_element_type=jnp.float32)[:, 0]
    out_ref[0, :] = (
        b_ref[0, :]
        + csq_ref[0, 0]
        - h_ref[0, :] * csum_ref[0, 0]
        - a_ref[0, :] * g
    )


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _ragged_score_pallas_call(
    item_q, item_tile, csq, csum, q_glob, codes, a, b, h,
    *, tile: int, interpret: bool,
):
    m = item_q.shape[0]
    d = codes.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(m,),
        in_specs=[
            # this item's query row: the prefetched item table IS the index map
            pl.BlockSpec((1, d), lambda i, iq, it: (iq[i], 0)),
            pl.BlockSpec((1, 1), lambda i, iq, it: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, iq, it: (i, 0)),
            # this item's cluster tile
            pl.BlockSpec((tile, d), lambda i, iq, it: (it[i], 0)),
            pl.BlockSpec((1, tile), lambda i, iq, it: (0, it[i])),
            pl.BlockSpec((1, tile), lambda i, iq, it: (0, it[i])),
            pl.BlockSpec((1, tile), lambda i, iq, it: (0, it[i])),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i, iq, it: (i, 0)),
    )
    return pl.pallas_call(
        _ragged_score_kernel,
        out_shape=jax.ShapeDtypeStruct((m, tile), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        item_q, item_tile, q_glob, csq, csum,
        codes, a.reshape(1, -1), b.reshape(1, -1), h.reshape(1, -1),
    )


def ragged_score_pallas(
    item_q, item_tile, csq, csum, q_glob, codes, a, b, h,
    *, tile: int = TILE, interpret: bool = False,
):
    """Item scores [M, tile] via the Pallas grid.  M and Q are pow2-bucketed
    so repeated micro-batches of varying raggedness reuse compiled shapes;
    pad items point at tile 0 / query 0 and are dropped by the caller."""
    m = len(item_q)
    m_pad = _pow2(m)
    q_pad = _pow2(q_glob.shape[0])

    def pad1(x, n, const=0):
        x = np.asarray(x)
        return np.pad(x, [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1),
                      constant_values=const)

    out = _ragged_score_pallas_call(
        jnp.asarray(pad1(item_q, m_pad), jnp.int32),
        jnp.asarray(pad1(item_tile, m_pad), jnp.int32),
        jnp.asarray(pad1(np.asarray(csq, np.float32).reshape(-1, 1), m_pad)),
        jnp.asarray(pad1(np.asarray(csum, np.float32).reshape(-1, 1), m_pad)),
        jnp.asarray(pad1(np.asarray(q_glob, np.float32), q_pad)),
        jnp.asarray(codes),
        jnp.asarray(a), jnp.asarray(b), jnp.asarray(h),
        tile=tile, interpret=interpret,
    )
    return np.asarray(out)[:m]


@functools.partial(jax.jit, static_argnames=("tile",))
def _ragged_score_jnp_call(item_q, item_tile, csq, csum, q_glob, codes, a, b, h,
                           *, tile: int):
    rows = item_tile[:, None] * tile + jnp.arange(tile)[None, :]  # [M, tile]
    sub = codes[rows]                                             # [M, tile, d]
    qv = q_glob[item_q]                                           # [M, d]
    g = jnp.einsum("mtd,md->mt", sub, qv)
    return b[rows] + csq[:, None] - h[rows] * csum[:, None] - a[rows] * g


def ragged_score_jnp(item_q, item_tile, csq, csum, q_glob, codes, a, b, h,
                     *, tile: int = TILE):
    """jnp twin of the Pallas kernel (gathers materialize [M, tile, d] — a
    differential-test surface, not the host serving path)."""
    return np.asarray(
        _ragged_score_jnp_call(
            jnp.asarray(np.asarray(item_q, np.int32)),
            jnp.asarray(np.asarray(item_tile, np.int32)),
            jnp.asarray(np.asarray(csq, np.float32)),
            jnp.asarray(np.asarray(csum, np.float32)),
            jnp.asarray(np.asarray(q_glob, np.float32)),
            jnp.asarray(codes), jnp.asarray(a), jnp.asarray(b), jnp.asarray(h),
            tile=tile,
        )
    )


def plan_items(pairs_q, pairs_c, csq, csum, tile_start, tile_count):
    """Flatten (query, cluster) probe pairs into per-tile work items.
    Pairs must arrive query-major (sorted by query) so item rows stay
    query-contiguous for the top-k tail."""
    pairs_c = np.asarray(pairs_c, np.int64)
    reps = np.asarray(tile_count, np.int64)[pairs_c]
    item_q = np.repeat(np.asarray(pairs_q, np.int64), reps).astype(np.int32)
    item_tile = ragged_arange(np.asarray(tile_start, np.int64)[pairs_c], reps).astype(
        np.int32
    )
    item_csq = np.repeat(np.asarray(csq, np.float32), reps)
    item_csum = np.repeat(np.asarray(csum, np.float32), reps)
    return item_q, item_tile, item_csq, item_csum


def items_topk(est, item_q, item_tile, nq: int, s: int, *, tile: int = TILE):
    """Per-query top-``s`` over item scores: items are query-contiguous, so
    each query's candidate rows are one flat slice.  Returns
    (rows [nq, s] int64 with -1 holes, est [nq, s] f32 with +inf holes)."""
    rows = (item_tile.astype(np.int64)[:, None] * tile
            + np.arange(tile, dtype=np.int64)[None, :]).reshape(-1)
    flat = np.asarray(est, np.float32).reshape(-1)
    counts = np.bincount(item_q, minlength=nq) * tile
    offsets = np.concatenate([[0], np.cumsum(counts)])
    out_rows = np.full((nq, s), -1, np.int64)
    out_est = np.full((nq, s), np.inf, np.float32)
    for q in range(nq):
        seg = flat[offsets[q] : offsets[q + 1]]
        if not len(seg):
            continue
        s_eff = min(s, len(seg))
        if s_eff < len(seg):
            part = np.argpartition(seg, s_eff - 1)[:s_eff]
        else:
            part = np.arange(len(seg))
        vals = seg[part]
        valid = vals < PAD_EST_VALID
        out_est[q, : s_eff][valid] = vals[valid]
        out_rows[q, : s_eff][valid] = rows[offsets[q] : offsets[q + 1]][part][valid]
    return out_rows, out_est


# --------------------------------------------------------------------------
# host production path: grouped GEMMs + vectorized ragged transpose
# --------------------------------------------------------------------------


def ragged_topk_host(
    codes, a, b, h, row_start, row_count,
    pairs_q, pairs_c, csq, csum, q_glob, nq: int, s: int,
):
    """Per-query top-``s`` estimator candidates on the host.

    GEMMs group by CLUSTER (each probed cluster's codes are touched once per
    micro-batch, against the queries that probed it); results land in a
    QUERY-major flat buffer via a precomputed ragged permutation, so the
    per-query top-k is one ``argpartition`` over a contiguous slice.  Same
    math, same results as the item kernels — without tile padding."""
    pairs_q = np.asarray(pairs_q, np.int64)
    pairs_c = np.asarray(pairs_c, np.int64)
    csq = np.asarray(csq, np.float32)
    csum = np.asarray(csum, np.float32)
    row_start = np.asarray(row_start, np.int64)
    row_count = np.asarray(row_count, np.int64)
    s = min(int(s), max(1, int(row_count.sum())))
    out_rows = np.full((nq, s), -1, np.int64)
    out_est = np.full((nq, s), np.inf, np.float32)
    if not len(pairs_q):
        return out_rows, out_est

    from lakesoul_tpu import native

    if native.available():
        # the C kernel runs the whole scan + top-s in ONE GIL-released call
        # (cluster-major groups, per-query heaps) — python pays one dispatch
        # per SHARD instead of several per probed cluster, and shard passes
        # parallelize for real on the worker pool
        corder = np.argsort(pairs_c, kind="stable")
        pc = pairs_c[corder]
        uniq, grp_start = np.unique(pc, return_index=True)
        grp_off = np.append(grp_start, len(pc)).astype(np.int64)
        use_csum = bool(np.any(h)) and bool(np.any(csum))
        return native.ann_ragged_topk(
            codes, a, b, h if use_csum else None,
            row_start, row_count,
            np.ascontiguousarray(q_glob, np.float32),
            uniq.astype(np.int32), grp_off,
            np.ascontiguousarray(pairs_q[corder], np.int32),
            np.ascontiguousarray(csq[corder], np.float32),
            np.ascontiguousarray(csum[corder], np.float32) if use_csum else None,
            s,
        )

    n_pair = row_count[pairs_c]
    # destination layout: query-major, pairs in stable query order
    q_tot = np.bincount(pairs_q, weights=n_pair, minlength=nq).astype(np.int64)
    q_off = np.concatenate([[0], np.cumsum(q_tot)])
    qorder = np.argsort(pairs_q, kind="stable")
    n_sorted = n_pair[qorder]
    cum = np.cumsum(n_sorted) - n_sorted
    _, first = np.unique(pairs_q[qorder], return_index=True)
    group_of = np.searchsorted(first, np.arange(len(qorder)), side="right") - 1
    within = cum - cum[first][group_of]
    dest_start = np.empty(len(pairs_q), np.int64)
    dest_start[qorder] = q_off[np.unique(pairs_q)][group_of] + within

    use_csum = bool(np.any(h)) and bool(np.any(csum))
    total = int(q_off[-1])
    est_flat = np.empty(total, np.float32)

    # cluster-major execution order
    corder = np.argsort(pairs_c, kind="stable")
    pc, pq = pairs_c[corder], pairs_q[corder]
    pcsq, pcsum = csq[corder], csum[corder]
    uniq, grp_start = np.unique(pc, return_index=True)
    grp_end = np.append(grp_start[1:], len(pc))
    for gi in range(len(uniq)):
        c = int(uniq[gi])
        rs, n_c = int(row_start[c]), int(row_count[c])
        if n_c == 0:
            continue
        s0, s1 = int(grp_start[gi]), int(grp_end[gi])
        qs = pq[s0:s1]
        block = codes[rs : rs + n_c]
        g = block @ q_glob[qs].T  # [n_c, m] — ONE pass over the cluster
        # fuse the affine correction in place (no temporaries: the group
        # loop runs thousands of times per micro-batch); the csum term only
        # exists on 1-bit shards (ex-code planes fold h = 0)
        g *= -a[rs : rs + n_c, None]
        g += b[rs : rs + n_c, None]
        g += pcsq[s0:s1][None, :]
        if use_csum:
            g -= h[rs : rs + n_c, None] * pcsum[s0:s1][None, :]
        # land every probing query's column at its query-major destination
        # slice — plain contiguous copies; the flat candidate-row array the
        # naive transpose would also build is never materialized (candidate
        # rows are recovered below for the TOP-S survivors only)
        dest = dest_start[corder[s0:s1]]
        for j in range(s1 - s0):
            d0 = dest[j]
            est_flat[d0 : d0 + n_c] = g[:, j]

    # per-query top-s over contiguous segments, then map the surviving flat
    # positions back to shard rows: dest_start is globally ascending in
    # query-sorted pair order, so one searchsorted finds each survivor's
    # pair, and its offset inside the pair is its offset inside the cluster
    sorted_dest = dest_start[qorder]
    pair_cluster_sorted = pairs_c[qorder]
    gpos_all, q_all, s_all = [], [], []
    for q in range(nq):
        seg = est_flat[q_off[q] : q_off[q + 1]]
        if not len(seg):
            continue
        s_eff = min(s, len(seg))
        if s_eff < len(seg):
            part = np.argpartition(seg, s_eff - 1)[:s_eff]
        else:
            part = np.arange(len(seg))
        out_est[q, :s_eff] = seg[part]
        gpos_all.append(q_off[q] + part)
        q_all.append(np.full(s_eff, q, np.int64))
        s_all.append(np.arange(s_eff, dtype=np.int64))
    if gpos_all:
        gpos = np.concatenate(gpos_all)
        pair_pos = np.searchsorted(sorted_dest, gpos, side="right") - 1
        rows = (
            row_start[pair_cluster_sorted[pair_pos]]
            + (gpos - sorted_dest[pair_pos])
        )
        out_rows[np.concatenate(q_all), np.concatenate(s_all)] = rows
    return out_rows, out_est
