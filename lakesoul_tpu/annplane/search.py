"""Multi-shard ANN search: global probe selection, ragged dispatch,
cross-shard candidate union + exact re-rank.

Opening a plane loads every shard's IVF-RaBitQ index into a RESIDENT layout
(cluster-sorted rows, tile-aligned so the same arrays feed both the host
grouped-GEMM path and the Pallas item kernel).  A search micro-batch:

1. **probe selection** — one gram matmul of the batch against ALL shards'
   centroids; each query takes its ``nprobe`` nearest clusters *globally*
   (a hot query may spend its whole probe budget in one shard, a cold one
   fans out — per-query, not per-shard).  Rotation is orthonormal, so the
   same distance matrix doubles as the estimator's per-(query, cluster)
   ``csq`` — probe selection is free for the estimator.
2. **ragged scoring** — per shard, the (query, cluster) pairs that landed
   there become one ragged dispatch (annplane/ragged.py); every shard
   returns per-query estimator top-``shortlist`` candidates.
3. **exact re-rank + union** — candidates re-rank against raw vectors
   per shard (one batched einsum), then the per-query union across shards
   cuts to top-k by exact distance.  With ``keep_raw=False`` planes the
   union merges estimator distances instead.
"""

from __future__ import annotations

import time

import numpy as np

from lakesoul_tpu.annplane.config import AnnPlaneConfig
from lakesoul_tpu.annplane.manifest import PlaneManifestStore
from lakesoul_tpu.annplane.ragged import (
    TILE,
    PAD_B,
    fold_cluster,
    items_topk,
    plan_items,
    ragged_score_jnp,
    ragged_score_pallas,
    ragged_topk_host,
)
from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.obs import registry
from lakesoul_tpu.vector.config import VectorIndexConfig
from lakesoul_tpu.vector.index import SearchParams
from lakesoul_tpu.vector.kernels import PAD_RAW
from lakesoul_tpu.vector.manifest import ManifestStore
from lakesoul_tpu.vector.rabitq import RabitqQuantizer


class _ShardResident:
    """One shard's arrays in the ragged-search layout.

    Rows are cluster-sorted and padded per cluster to a TILE multiple; the
    pad rows carry ``b = PAD_B`` so any executor that touches them scores
    them out.  ``row_start/row_count`` index the REAL rows (host path),
    ``tile_start/tile_count`` the padded tiles (Pallas path) — same arrays,
    same row coordinates."""

    def __init__(self, index, *, tile: int = TILE):
        if index.centroids is None:
            raise VectorIndexError("shard index is not trained")
        cfg = index.config
        ex = cfg.total_bits > 1
        dpad = index.quantizer.padded_dim
        nlist = len(index.centroids)
        self.centroids = np.asarray(index.centroids, np.float32)
        self.tile = tile

        segs_per_cluster = [
            [s for s in index._cluster_segments(c) if len(s.ids)]
            for c in range(nlist)
        ]
        counts = np.array(
            [sum(len(s.ids) for s in segs) for segs in segs_per_cluster], np.int64
        )
        padded = (counts + tile - 1) // tile * tile
        n_pad = int(padded.sum()) or tile
        self.tile_start = np.concatenate(
            [[0], np.cumsum(padded[:-1] // tile)]
        ).astype(np.int32)
        self.tile_count = (padded // tile).astype(np.int32)
        self.row_start = (self.tile_start.astype(np.int64) * tile)
        self.row_count = counts

        self.codes = np.zeros((n_pad, dpad), np.float32)
        self.a = np.zeros(n_pad, np.float32)
        self.b = np.full(n_pad, PAD_B, np.float32)
        self.h = np.zeros(n_pad, np.float32)
        self.ids = np.zeros(n_pad, np.uint64)
        self.raw = (
            np.full((n_pad, cfg.dim), PAD_RAW, np.float32)
            if index.keep_raw else None
        )
        self.num_vectors = int(counts.sum())
        for c, segs in enumerate(segs_per_cluster):
            pos = int(self.row_start[c])
            for seg in segs:
                n = len(seg.ids)
                if ex:
                    if seg.scales is None:
                        raise VectorIndexError(
                            "ex-bits shard segment has no scales — rebuild"
                        )
                    self.codes[pos : pos + n] = (
                        seg.codes.astype(np.float32) * seg.scales[:, None]
                    )
                else:
                    bits = np.unpackbits(seg.codes, axis=1)[:, :dpad]
                    self.codes[pos : pos + n] = bits.astype(np.float32)
                a, b, h = fold_cluster(
                    seg.norms, seg.factors, np.asarray(seg.code_dot_c),
                    d=dpad, ex=ex,
                )
                self.a[pos : pos + n] = a
                self.b[pos : pos + n] = b
                self.h[pos : pos + n] = h
                self.ids[pos : pos + n] = seg.ids
                if self.raw is not None and seg.raw is not None:
                    self.raw[pos : pos + n] = seg.raw
                pos += n


class AnnPlane:
    """A loaded multi-shard plane, ready to serve ragged micro-batches."""

    def __init__(
        self,
        config: AnnPlaneConfig,
        shards: list[_ShardResident],
        *,
        manifest: dict | None = None,
        use_pallas: bool | None = None,
        pallas_interpret: bool = False,
    ):
        from lakesoul_tpu.vector.kernels import _on_tpu

        if not shards:
            raise VectorIndexError("ANN plane has no shards")
        self.plane_config = config
        self.config: VectorIndexConfig = config.index
        self.shards = shards
        self.manifest = manifest or {}
        self.use_pallas = _on_tpu() if use_pallas is None else use_pallas
        self.pallas_interpret = pallas_interpret
        # host path: score independent shards concurrently on the runtime
        # pool (numpy/BLAS release the GIL on the heavy ops); flip off for
        # single-core boxes or when the caller already parallelizes batches
        self.parallel_shards = True
        self.quantizer = RabitqQuantizer(
            self.config.dim, rotator=self.config.rotator, seed=self.config.seed
        )
        # plane-global cluster table: concatenated centroids with a
        # (shard, local cluster) map for every global cluster id
        self.centroids = np.concatenate([s.centroids for s in shards])
        self.shard_of = np.concatenate(
            [np.full(len(s.centroids), i, np.int32) for i, s in enumerate(shards)]
        )
        local = np.concatenate(
            [np.arange(len(s.centroids), dtype=np.int32) for s in shards]
        )
        self.local_cluster = local
        self._cent_sq = np.sum(self.centroids**2, axis=1)
        cent_rot = self.quantizer.rotate(self.centroids)
        self._cent_rot_sum = np.sum(cent_rot, axis=1).astype(np.float32)
        reg = registry()
        self._c_queries = reg.counter("lakesoul_ann_ragged_queries_total")
        self._c_pairs = reg.counter("lakesoul_ann_ragged_pairs_total")
        self._h_dispatch = reg.histogram("lakesoul_ann_ragged_dispatch_seconds")

    # ------------------------------------------------------------------- load
    @classmethod
    def open(
        cls,
        root: str,
        storage_options: dict | None = None,
        *,
        use_pallas: bool | None = None,
        pallas_interpret: bool = False,
        tile: int = TILE,
    ) -> "AnnPlane":
        store = PlaneManifestStore(root, storage_options)
        manifest = store.read()
        if manifest is None:
            raise VectorIndexError(f"no ANN plane at {root}")
        if not manifest.get("complete"):
            raise VectorIndexError(
                f"ANN plane at {root} is mid-build"
                f" ({len(manifest.get('shards', ()))} shard(s) durable);"
                " resume the builder first"
            )
        index_cfg = VectorIndexConfig.parse(manifest["index_config"])
        config = AnnPlaneConfig(
            index=index_cfg,
            shard_budget_bytes=manifest["shard_budget_bytes"],
            keep_raw=manifest["keep_raw"],
        )
        from lakesoul_tpu.annplane.build import shard_root

        shards = []
        for entry in manifest["shards"]:
            sstore = ManifestStore(
                shard_root(root, entry["shard"]), storage_options
            )
            # load the generation the plane record PINNED, not LATEST: a
            # concurrent rebuild bumps shard stores one by one, and reading
            # their moving pointers would mix generations into one plane
            shards.append(
                _ShardResident(sstore.read_at(entry["generation"]), tile=tile)
            )
        return cls(
            config, shards, manifest=manifest,
            use_pallas=use_pallas, pallas_interpret=pallas_interpret,
        )

    @property
    def dim(self) -> int:
        return self.config.dim

    @property
    def num_vectors(self) -> int:
        return sum(s.num_vectors for s in self.shards)

    # ----------------------------------------------------------------- search
    def search(self, query: np.ndarray, params: SearchParams = SearchParams()):
        ids, dists = self.batch_search(np.asarray(query, np.float32)[None, :], params)
        return ids[0], dists[0]

    def batch_search(
        self,
        queries: np.ndarray,
        params: SearchParams = SearchParams(),
        *,
        nprobes: np.ndarray | None = None,
    ):
        """→ (ids per query, dists per query).  ``nprobes`` overrides
        ``params.nprobe`` per query — the ragged dispatch fuses the mixed
        probe depths into one scoring pass per shard."""
        start = time.perf_counter()
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        nq = len(queries)
        n_clusters = len(self.centroids)
        if nprobes is None:
            nprobes = np.full(nq, params.nprobe, np.int64)
        else:
            nprobes = np.asarray(nprobes, np.int64)
            if len(nprobes) != nq:
                raise VectorIndexError("nprobes length must match queries")
        nprobes = np.clip(nprobes, 1, n_clusters)
        s = params.shortlist()

        # global probe selection; cd doubles as the estimator csq (rotation
        # preserves distances)
        cd = (
            np.sum(queries**2, axis=1, keepdims=True)
            - 2.0 * queries @ self.centroids.T
            + self._cent_sq[None, :]
        ).astype(np.float32)
        max_np = int(nprobes.max())
        if max_np < n_clusters:
            sel = np.argpartition(cd, max_np - 1, axis=1)[:, :max_np]
        else:
            sel = np.broadcast_to(np.arange(n_clusters), (nq, n_clusters)).copy()
        sel_d = np.take_along_axis(cd, sel, axis=1)
        order = np.argsort(sel_d, axis=1)
        sel = np.take_along_axis(sel, order, axis=1)
        sel_d = np.take_along_axis(sel_d, order, axis=1)

        keep = np.arange(sel.shape[1])[None, :] < nprobes[:, None]
        pairs_q = np.repeat(np.arange(nq, dtype=np.int64), keep.sum(axis=1))
        pairs_gc = sel[keep]          # query-major by construction
        pairs_csq = sel_d[keep]
        self._c_queries.inc(nq)
        self._c_pairs.inc(len(pairs_gc))

        q_glob = self.quantizer.rotate(queries)
        ex = self.config.total_bits > 1
        if ex:
            pairs_csum = np.zeros(len(pairs_gc), np.float32)
        else:
            pairs_csum = (
                self._cent_rot_sum[pairs_gc]
                - np.sum(q_glob, axis=1).astype(np.float32)[pairs_q]
            )

        cand_ids: list[list[np.ndarray]] = [[] for _ in range(nq)]
        cand_d: list[list[np.ndarray]] = [[] for _ in range(nq)]
        shard_sel = self.shard_of[pairs_gc]
        jobs = []
        for si, shard in enumerate(self.shards):
            m = shard_sel == si
            if not m.any():
                continue
            sub_q = pairs_q[m]
            uq, inv = np.unique(sub_q, return_inverse=True)
            jobs.append((
                uq,
                (shard, queries[uq], q_glob[uq], inv,
                 self.local_cluster[pairs_gc[m]],
                 pairs_csq[m], pairs_csum[m], len(uq), s),
            ))
        # shards are independent read-only scans: fan them out on the shared
        # runtime pool (BLAS/numpy release the GIL, so a 9-shard plane uses
        # 9 cores per dispatch instead of serializing on the worker thread)
        from lakesoul_tpu.runtime.pool import get_pool

        pool = get_pool()
        if len(jobs) > 1 and self.parallel_shards and not pool.in_worker():
            futs = [
                (uq, pool.submit(self._shard_pass, *args)) for uq, args in jobs
            ]
            results = [(uq, f.result()) for uq, f in futs]
        else:
            results = [(uq, self._shard_pass(*args)) for uq, args in jobs]
        for uq, (ids_s, d_s) in results:
            for li, gq in enumerate(uq):
                cand_ids[gq].append(ids_s[li])
                cand_d[gq].append(d_s[li])

        out_ids, out_d = [], []
        for q in range(nq):
            if not cand_ids[q]:
                out_ids.append(np.zeros(0, np.uint64))
                out_d.append(np.zeros(0, np.float32))
                continue
            ids = np.concatenate(cand_ids[q])
            d = np.concatenate(cand_d[q])
            valid = np.isfinite(d)
            ids, d = ids[valid], d[valid]
            top = np.argsort(d, kind="stable")[: params.top_k]
            out_ids.append(ids[top])
            out_d.append(d[top])
        self._h_dispatch.observe(time.perf_counter() - start)
        return out_ids, out_d

    # ------------------------------------------------------------- internals
    def _shard_pass(self, shard, queries_sub, q_glob_sub, pairs_lq, pairs_lc,
                    csq, csum, nq_sub: int, s: int):
        """One shard's complete contribution: ragged score → shortlist →
        exact re-rank.  Pure function of read-only shard arrays — safe to
        run on any pool worker."""
        rows, est = self._score_shard(
            shard, q_glob_sub, pairs_lq, pairs_lc, csq, csum, nq_sub, s
        )
        return self._rerank_shard(shard, queries_sub, rows, est)

    def _score_shard(self, shard, q_glob_sub, pairs_lq, pairs_lc, csq, csum,
                     nq_sub: int, s: int):
        if self.use_pallas:
            item_q, item_tile, icsq, icsum = plan_items(
                pairs_lq, pairs_lc, csq, csum,
                shard.tile_start, shard.tile_count,
            )
            est = ragged_score_pallas(
                item_q, item_tile, icsq, icsum, q_glob_sub,
                shard.codes, shard.a, shard.b, shard.h,
                tile=shard.tile, interpret=self.pallas_interpret,
            )
            return items_topk(est, item_q, item_tile, nq_sub, s, tile=shard.tile)
        return ragged_topk_host(
            shard.codes, shard.a, shard.b, shard.h,
            shard.row_start, shard.row_count,
            pairs_lq, pairs_lc, csq, csum, q_glob_sub, nq_sub, s,
        )

    def _rerank_shard(self, shard, queries_sub, rows, est):
        """Exact re-rank of one shard's candidate rows (raw kept), else the
        estimator distances pass through; -1 rows stay +inf holes."""
        safe = np.clip(rows, 0, None)
        ids = shard.ids[safe]
        if shard.raw is None:
            d = est.copy()
            d[rows < 0] = np.inf
            return ids, d
        from lakesoul_tpu import native

        if native.available():
            exact = native.ann_exact_rerank(
                shard.raw, np.ascontiguousarray(rows, np.int64),
                np.ascontiguousarray(queries_sub, np.float32),
            )
            return ids, exact
        sub = shard.raw[safe]                       # [nq, s, dim]
        exact = (
            np.sum(sub * sub, axis=2)
            - 2.0 * np.einsum("qsd,qd->qs", sub, queries_sub)
            + np.sum(queries_sub * queries_sub, axis=1)[:, None]
        ).astype(np.float32)
        exact[rows < 0] = np.inf
        return ids, exact


def jnp_score_shard(plane: AnnPlane, shard: _ShardResident, q_glob_sub,
                    pairs_lq, pairs_lc, csq, csum, nq_sub: int, s: int):
    """jnp item-kernel twin of a shard scoring pass — the differential-test
    hook that pins host GEMMs == item kernel == Pallas(interpret)."""
    item_q, item_tile, icsq, icsum = plan_items(
        pairs_lq, pairs_lc, csq, csum, shard.tile_start, shard.tile_count
    )
    est = ragged_score_jnp(
        item_q, item_tile, icsq, icsum, q_glob_sub,
        shard.codes, shard.a, shard.b, shard.h, tile=shard.tile,
    )
    return items_topk(est, item_q, item_tile, nq_sub, s, tile=shard.tile)
