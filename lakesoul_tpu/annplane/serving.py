"""Fleet-scale ANN serving over a sharded plane.

Same micro-batching discipline as the single-shard ``AnnEndpoint`` (collect
concurrent requests for up to ``max_wait_ms``, run ONE fused dispatch, fan
results out) — but the fused dispatch is the RAGGED multi-shard search:
requests in one window may carry different ``nprobe`` values and will probe
different shard/cluster sets, and all of them still ride one scoring pass
per shard (annplane/ragged.py).  Overload behavior is inherited unchanged:
the pending queue is bounded (``LAKESOUL_ANN_MAX_PENDING`` when the ctor
doesn't say), beyond it ``submit`` raises a typed ``OverloadedError`` the
Flight gateway maps to UNAVAILABLE.  Latency lands in the same
``lakesoul_ann_request_seconds`` histogram, so ``stats()`` exposes the same
``latency_p50``/``latency_p99`` keys as the single-shard endpoint."""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.vector.index import SearchParams
from lakesoul_tpu.vector.serving import AnnEndpoint

ENV_MAX_PENDING = "LAKESOUL_ANN_MAX_PENDING"


def _env_max_pending() -> int | None:
    raw = os.environ.get(ENV_MAX_PENDING)
    if raw is None:
        return None
    try:
        v = int(raw)
    except ValueError:
        raise VectorIndexError(f"{ENV_MAX_PENDING} must be an integer, got {raw!r}")
    if v < 1:
        raise VectorIndexError(f"{ENV_MAX_PENDING} must be >= 1, got {v}")
    return v


class ShardedAnnEndpoint(AnnEndpoint):
    """Micro-batching front end over an :class:`AnnPlane`."""

    def __init__(
        self,
        plane,
        params: SearchParams | None = None,
        *,
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        max_pending: int | None = None,
        name: str = "default",
    ):
        if max_pending is None:
            max_pending = _env_max_pending()
        self.plane = plane
        super().__init__(
            plane, params,
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_pending=max_pending, name=name,
        )

    def submit(self, query: np.ndarray, *, nprobe: int | None = None):
        """Enqueue one query; ``nprobe`` overrides the endpoint default for
        THIS request only — mixed probe depths fuse into the same ragged
        dispatch.  Raises ``OverloadedError`` past the pending bound."""
        if nprobe is not None and nprobe < 1:
            raise ValueError(f"nprobe must be >= 1, got {nprobe}")
        return self._submit(query, nprobe)

    def search(self, query: np.ndarray, timeout: float | None = None,
               *, nprobe: int | None = None):
        return self.submit(query, nprobe=nprobe).result(timeout)

    def _execute(self, queries, extras):
        nprobes = np.array(
            [self.params.nprobe if e is None else int(e) for e in extras],
            np.int64,
        )
        return self.plane.batch_search(
            np.stack(queries), self.params, nprobes=nprobes
        )


@dataclass(frozen=True)
class AnnPlaneBinding:
    """A served plane's registration with the Flight gateway: requests pass
    the gateway's JWT auth, then RBAC-check against the TABLE the plane
    indexes — the plane inherits exactly the table's access story."""

    endpoint: ShardedAnnEndpoint
    namespace: str
    table: str
