"""User-facing catalog API: LakeSoulCatalog / LakeSoulTable / LakeSoulScan.

Python surface parity with the reference's ``python/src/lakesoul/catalog.py``
(LakeSoulCatalog:39, LakeSoulTable:277, LakeSoulScan:596): catalog-backed
table lifecycle, Arrow write + ACID commit, lazy immutable scans with
select/filter/shard, and delivery into JAX (replacing the reference's
``to_torch``-first surface with ``to_jax_iter`` while keeping torch/HF
adapters).
"""

from __future__ import annotations

import copy
from typing import Any, Iterable, Iterator

import numpy as np
import pyarrow as pa

from lakesoul_tpu.errors import CommitConflictError, ConfigError, MetadataError
from lakesoul_tpu.io.config import IOConfig
from lakesoul_tpu.io.filters import Filter, extract_pk_equalities
from lakesoul_tpu.io.reader import iter_scan_unit_batches, read_scan_unit
from lakesoul_tpu.io.writer import TableWriter
from lakesoul_tpu.meta import (
    CommitOp,
    DataFileOp,
    MetaDataClient,
    ScanPlanPartition,
)
from lakesoul_tpu.runtime import pipeline as rt_pipeline
from lakesoul_tpu.meta.entity import (
    CDC_DEFAULT_COLUMN,
    PROP_CDC_CHANGE_COLUMN,
    PROP_HASH_BUCKET_NUM,
    TableInfo,
)
from lakesoul_tpu.utils import spark_hash


class LakeSoulCatalog:
    """Warehouse-rooted catalog over a metadata store."""

    def __init__(
        self,
        warehouse: str,
        *,
        db_path: str | None = None,
        client: MetaDataClient | None = None,
        storage_options: dict | None = None,
    ):
        self.warehouse = str(warehouse).rstrip("/")
        if client is None:
            if db_path is None:
                from lakesoul_tpu.io.object_store import ensure_dir

                ensure_dir(self.warehouse, storage_options)
                db_path = f"{self.warehouse}/.lakesoul_meta.db"
            client = MetaDataClient(db_path=db_path)
        self.client = client
        self.storage_options = storage_options or {}
        self._recover_on_open()
        # scan.cache() storage: LRU of decoded tables, keyed by scan
        # parameters + partition-version digest (commits invalidate naturally).
        # BYTE-bounded, not count-bounded: four 2M-row tables are GBs — the
        # pressure valve must see sizes (VERDICT r1 weak #9)
        self._scan_cache: dict = {}
        self._scan_cache_max_bytes = 512 << 20
        self._scan_cache_bytes = 0

    def _recover_on_open(self) -> None:
        """Crash-safe open: commits a killed process left between the two
        metadata phases are rolled forward/back before the catalog serves
        its first plan (MetaDataClient.recover_incomplete_commits).  Only
        commits older than ``LAKESOUL_RECOVER_MIN_AGE_MS`` (default 1 h)
        are swept, so live writers sharing the store are never raced; a
        failing recovery must never fail the open itself."""
        import logging
        import os

        raw = os.environ.get("LAKESOUL_RECOVER_MIN_AGE_MS", "").strip()
        try:
            min_age_ms = int(raw) if raw else 3_600_000
        except ValueError:
            min_age_ms = 3_600_000
        try:
            self.client.recover_incomplete_commits(
                min_age_ms=min_age_ms, storage_options=self.storage_options
            )
        except Exception:
            logging.getLogger(__name__).exception(
                "commit recovery on catalog open failed; continuing"
            )

    def _scan_cache_get(self, key):
        hit = self._scan_cache.pop(key, None)
        if hit is not None:
            self._scan_cache[key] = hit  # LRU refresh
        return hit

    def _scan_cache_put(self, key, table) -> None:
        size = table.nbytes
        if size > self._scan_cache_max_bytes:
            return  # larger than the whole budget: caching it evicts everything
        prev = self._scan_cache.pop(key, None)
        if prev is not None:
            self._scan_cache_bytes -= prev.nbytes
        self._scan_cache[key] = table
        self._scan_cache_bytes += size
        while self._scan_cache_bytes > self._scan_cache_max_bytes and self._scan_cache:
            oldest = next(iter(self._scan_cache))  # insertion order = LRU order
            self._scan_cache_bytes -= self._scan_cache.pop(oldest).nbytes

    # ------------------------------------------------------------------- DDL
    def create_table(
        self,
        name: str,
        schema: pa.Schema,
        *,
        primary_keys: list[str] | None = None,
        range_partitions: list[str] | None = None,
        hash_bucket_num: int | None = None,
        cdc: bool = False,
        cdc_column: str | None = None,
        properties: dict | None = None,
        merge_operators: dict[str, str] | None = None,
        namespace: str = "default",
        table_path: str | None = None,
    ) -> "LakeSoulTable":
        props = dict(properties or {})
        if hash_bucket_num is not None:
            props[PROP_HASH_BUCKET_NUM] = str(hash_bucket_num)
        for colname, op in (merge_operators or {}).items():
            # persisted in table properties → every surface (table API, SQL
            # WITH(...), Flight) reads back the same per-column operators
            props[IOConfig.PROP_MERGE_OP_PREFIX + colname] = op
        if cdc or cdc_column:
            cdc_column = cdc_column or CDC_DEFAULT_COLUMN
            props[PROP_CDC_CHANGE_COLUMN] = cdc_column
            if cdc_column not in schema.names:
                schema = schema.append(pa.field(cdc_column, pa.string()))
        info = self.client.create_table(
            name,
            table_path or f"{self.warehouse}/{namespace}/{name}",
            schema,
            primary_keys=primary_keys,
            range_partitions=range_partitions,
            properties=props,
            namespace=namespace,
        )
        return LakeSoulTable(self, info)

    def table(self, name: str, namespace: str = "default") -> "LakeSoulTable":
        return LakeSoulTable(self, self.client.get_table_info_by_name(name, namespace))

    def table_by_path(self, path: str) -> "LakeSoulTable":
        return LakeSoulTable(self, self.client.get_table_info_by_path(path))

    def drop_table(self, name: str, namespace: str = "default") -> None:
        self.client.drop_table(name, namespace)

    def table_exists(self, name: str, namespace: str = "default") -> bool:
        return self.client.table_exists(name, namespace)

    def list_tables(self, namespace: str = "default") -> list[str]:
        return self.client.list_tables(namespace)

    def create_namespace(self, name: str) -> None:
        self.client.create_namespace(name)

    def drop_namespace(self, name: str) -> None:
        self.client.drop_namespace(name)

    def list_namespaces(self) -> list[str]:
        return self.client.list_namespaces()

    def scan(self, name: str, namespace: str = "default") -> "LakeSoulScan":
        return self.table(name, namespace).scan()


class LakeSoulTable:
    """Handle to one table: writes, upserts, compaction, scans."""

    def __init__(self, catalog: LakeSoulCatalog, info: TableInfo):
        self.catalog = catalog
        self._info = info

    # refresh metadata (another writer may have altered schema/properties)
    def refresh(self) -> "LakeSoulTable":
        self._info = self.catalog.client.get_table_info_by_name(
            self._info.table_name, self._info.table_namespace
        )
        return self

    @property
    def info(self) -> TableInfo:
        return self._info

    @property
    def name(self) -> str:
        return self._info.table_name

    @property
    def schema(self) -> pa.Schema:
        return self._info.arrow_schema

    @property
    def primary_keys(self) -> list[str]:
        return self._info.primary_keys

    def io_config(self, **overrides) -> IOConfig:
        cfg = IOConfig.for_table(self._info)
        cfg.object_store_options = dict(self.catalog.storage_options)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg

    def set_properties(self, props: dict[str, str]) -> "LakeSoulTable":
        """Merge properties into the table (ALTER TABLE SET TBLPROPERTIES
        role): per-table IO knobs, TTLs, and mergeOperator.* entries become
        effective for subsequent reads/writes.  A value of None removes the
        key.  Structural properties (hashBucketNum, the CDC column) are
        immutable — existing files were written under them."""
        immutable = {PROP_HASH_BUCKET_NUM, PROP_CDC_CHANGE_COLUMN}
        bad = immutable & set(props)
        if bad:
            raise MetadataError(
                f"properties {sorted(bad)} are structural and cannot change"
            )

        def merge(current: dict) -> dict:
            merged = dict(current or {})
            for k, v in props.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = str(v)
            return merged

        # the merge runs inside the store's locked transaction: merging
        # against a cached self._info snapshot and writing the result back
        # blind would drop a concurrent peer's property update
        self.catalog.client.store.merge_table_properties(
            self._info.table_id, merge
        )
        return self.refresh()

    # ---------------------------------------------------------------- writes
    def write_arrow(
        self,
        data: pa.Table | pa.RecordBatch | Iterable[pa.RecordBatch],
        *,
        op: CommitOp | str | None = None,
        commit_id_by_partition: dict[str, str] | None = None,
    ) -> list[DataFileOp]:
        """Write Arrow data and commit atomically.  PK tables default to a
        MergeCommit (upsert semantics on read), plain tables to AppendCommit —
        matching LakeSoulTable.write_arrow (catalog.py:401)."""
        if op is None:
            op = CommitOp.MERGE if self._info.primary_keys else CommitOp.APPEND
        elif isinstance(op, str):
            op = CommitOp(op)
        writer = TableWriter(self.io_config(), self._info.table_path)
        try:
            if isinstance(data, (pa.Table, pa.RecordBatch)):
                writer.write_batch(data)
            else:
                for b in data:
                    writer.write_batch(b)
            outputs = writer.close()
        except Exception:
            writer.abort()
            raise
        files_by_partition: dict[str, list[DataFileOp]] = {}
        for out in outputs:
            files_by_partition.setdefault(out.partition_desc, []).append(
                DataFileOp(
                    path=out.path,
                    file_op="add",
                    size=out.size,
                    file_exist_cols=out.file_exist_cols,
                )
            )
        try:
            self.catalog.client.commit_data_files(
                self._info,
                files_by_partition,
                op,
                commit_id_by_partition=commit_id_by_partition,
                storage_options=self.catalog.storage_options,
            )
        except CommitConflictError:
            # conflict = the partition-version insert never landed, so the
            # staged files are provably invisible → safe to delete (close()
            # already took ownership from the writer, so delete explicitly)
            from lakesoul_tpu.io.object_store import delete_file

            for out in outputs:
                delete_file(out.path, self.catalog.storage_options, missing_ok=True)
            raise
        except Exception:
            # any other failure may have happened AFTER the snapshot became
            # visible (e.g. in mark_committed) — deleting files a snapshot
            # references would corrupt the table; leave them for the cleaner
            raise
        return [f for ops in files_by_partition.values() for f in ops]

    def upsert(self, data) -> list[DataFileOp]:
        if not self._info.primary_keys:
            raise MetadataError("upsert requires a primary-key table")
        return self.write_arrow(data, op=CommitOp.MERGE)

    def delete_partitions(self, partitions: dict[str, str] | None = None) -> None:
        """Drop data (DeleteCommit clears the partition snapshot)."""
        from lakesoul_tpu.meta.entity import MetaInfo, PartitionInfo

        heads = self.catalog.client._select_partitions(self._info, partitions)
        if not heads:
            return
        self.catalog.client.commit_data(
            MetaInfo(
                table_info=self._info,
                list_partition=[
                    PartitionInfo(self._info.table_id, h.partition_desc) for h in heads
                ],
            ),
            CommitOp.DELETE,
        )

    # ------------------------------------------------------------- row DML
    def _commit_partition_rewrite(self, head, outputs, old_files, commit_op,
                                  *, lease=None) -> None:
        """Shared tail of every partition-rewrite operation (compaction and
        row DML): build the file ops, commit against the read head, delete
        staged files on a provably-invisible conflict, queue replaced files
        for the cleaner.  ``lease`` fences the commit on a coordination
        lease (leased compaction services); a fenced commit is just as
        provably invisible as a conflicted one, so its staged files are
        cleaned up the same way."""
        from lakesoul_tpu.errors import LeaseFencedError

        client = self.catalog.client
        files_by_partition: dict[str, list[DataFileOp]] = {head.partition_desc: []}
        for out in outputs:
            files_by_partition.setdefault(out.partition_desc, []).append(
                DataFileOp(path=out.path, file_op="add", size=out.size,
                           file_exist_cols=out.file_exist_cols)
            )
        try:
            client.commit_data_files(
                self._info,
                files_by_partition,
                commit_op,
                read_partition_info=[head],
                lease=lease,
                # the except below deletes the staged outputs, so the
                # phase-1 rows must die with them (see commit_data_files)
                staged_deleted_on_conflict=True,
            )
        except (CommitConflictError, LeaseFencedError):
            from lakesoul_tpu.io.object_store import delete_file

            for out in outputs:
                delete_file(out.path, self.catalog.storage_options, missing_ok=True)
            raise
        for f in old_files:
            client.store.insert_discard_file(f, self._info.table_path, head.partition_desc)

    @staticmethod
    def _partition_constraints(flt: Filter, range_cols: list[str]) -> dict[str, str]:
        """AND-of-equality constraints on partition columns (conservative:
        anything under OR/NOT is ignored) → prune partitions before reading."""
        out: dict[str, str] = {}

        def walk(f: Filter):
            if f.op == "and":
                for a in f.args:
                    walk(a)
            elif f.op == "eq" and f.col in range_cols:
                out[f.col] = str(f.value)

        walk(flt)
        return out

    def _match_mask(self, table: pa.Table, flt: Filter) -> np.ndarray:
        """Boolean row mask for the predicate with SQL three-valued logic:
        NULL-predicate rows are NOT matched (kept by DELETE, skipped by
        UPDATE)."""
        import pyarrow.dataset as pads

        idx = pa.array(np.arange(len(table), dtype=np.int64))
        with_idx = table.append_column("__idx", idx)
        matched = np.asarray(
            pads.dataset(with_idx).to_table(filter=flt.to_arrow()).column("__idx")
        )
        mask = np.zeros(len(table), dtype=bool)
        mask[matched] = True
        return mask

    def _rewrite_where(self, flt: Filter | None, mutate, *, mask_fn=None) -> int:
        """Shared engine for row-level UPDATE/DELETE (reference:
        lakesoul-datafusion update/delete planning): per matching partition,
        rewrite the merged data with ``mutate(table, mask)`` applied and
        commit an UpdateCommit (snapshot replace, conflict checked against
        the read head).  Returns affected row count.

        ``mask_fn(table) -> bool ndarray`` replaces the Filter-derived match
        mask for predicates the pushdown AST cannot express (function
        calls, subqueries — the SQL layer's general evaluator); with no
        Filter, every partition is scanned."""
        client = self.catalog.client
        total_affected = 0
        constraints = (
            self._partition_constraints(flt, self._info.range_partition_columns)
            if flt is not None else {}
        )
        heads = client._select_partitions(self._info, constraints or None)
        for head in heads:
            units = client.get_scan_plan_partitions(
                self._info.table_name, namespace=self._info.table_namespace,
                snapshot=[head],
            )
            tables = []
            for unit in units:
                t = read_scan_unit(
                    unit.data_files,
                    unit.primary_keys,
                    schema=self.schema,
                    partition_values=unit.partition_values,
                    merge_operators=self.io_config().merge_operators,
                    cdc_column=self._info.cdc_column,
                    drop_cdc_deletes=True,
                    storage_options=self.catalog.storage_options,
                )
                if len(t):
                    tables.append(t)
            if not tables:
                continue
            merged = pa.concat_tables(tables)
            mask = (
                mask_fn(merged) if mask_fn is not None
                else self._match_mask(merged, flt)
            )
            affected = int(mask.sum())
            if affected == 0:
                continue
            new_table = mutate(merged, mask)
            writer = TableWriter(self.io_config(), self._info.table_path)
            if len(new_table):
                writer.write_batch(new_table)
            outputs = writer.close()
            old_files = [f for unit in units for f in unit.data_files]
            self._commit_partition_rewrite(head, outputs, old_files, CommitOp.UPDATE)
            total_affected += affected
        return total_affected

    def delete_where(self, flt: Filter | None, *, mask_fn=None) -> int:
        """Row-level delete: rewrite matching partitions without the matching
        rows.  Returns the number of rows deleted."""

        def mutate(table, mask):
            return table.filter(pa.array(~mask))

        return self._rewrite_where(flt, mutate, mask_fn=mask_fn)

    def update_where(self, flt: Filter | None, assignments: dict, *,
                     mask_fn=None, expr_assignments: dict | None = None) -> int:
        """Row-level update: SET column=value on rows matching the filter.
        ``assignments`` maps columns to plain Python literals;
        ``expr_assignments`` maps columns to callables ``fn(table) ->
        Array`` evaluated over the merged partition (the SQL layer's
        SET-expression path).  Returns the number of rows updated."""
        import pyarrow.compute as pc

        expr_assignments = expr_assignments or {}
        schema = self.schema
        for col_name in (*assignments, *expr_assignments):
            if col_name not in schema.names:
                raise MetadataError(f"unknown column {col_name!r} in UPDATE")
            if col_name in self._info.primary_keys:
                raise MetadataError("cannot UPDATE a primary-key column")
            if col_name in self._info.range_partition_columns:
                # moving rows between partitions would replace the target
                # partition's snapshot outside the conflict check
                raise MetadataError("cannot UPDATE a range-partition column")

        def mutate(table, mask):
            import numpy as np

            mask_arr = pa.array(mask)
            # SET expressions evaluate over the MATCHED rows only (standard
            # SQL): a non-matching row must not be able to abort the
            # statement (e.g. SET v = 10 / k WHERE k <> 0)
            matched = table.take(pa.array(np.nonzero(mask)[0]))
            arrays = []
            for fld in schema:
                col = table.column(fld.name)
                if fld.name in assignments:
                    val = pa.scalar(assignments[fld.name], type=fld.type)
                    col = pc.if_else(mask_arr, val, col)
                elif fld.name in expr_assignments:
                    try:
                        new = pc.cast(
                            expr_assignments[fld.name](matched),
                            options=pc.CastOptions(
                                target_type=fld.type, allow_float_truncate=True
                            ),
                        )
                    except (pa.lib.ArrowInvalid,
                            pa.lib.ArrowNotImplementedError) as e:
                        raise MetadataError(
                            f"UPDATE SET {fld.name}: CAST failed: {e}"
                        )
                    if isinstance(new, pa.ChunkedArray):
                        new = new.combine_chunks()
                    col = pc.replace_with_mask(
                        col.combine_chunks() if isinstance(col, pa.ChunkedArray)
                        else col,
                        mask_arr, new,
                    )
                arrays.append(col)
            return pa.table(arrays, schema=schema)

        return self._rewrite_where(flt, mutate, mask_fn=mask_fn)

    # ----------------------------------------------------------- maintenance
    def rollback(
        self,
        *,
        to_version: int | None = None,
        to_timestamp_ms: int | None = None,
        partitions: dict[str, str] | None = None,
    ) -> int:
        """Roll partitions back to an earlier state by committing a NEW
        version carrying the old snapshot (history is preserved — parity with
        Spark LakeSoulTable.rollback, tables/LakeSoulTable.scala:341-551).
        Returns the number of partitions rolled back."""
        if (to_version is None) == (to_timestamp_ms is None):
            raise ConfigError("rollback needs exactly one of to_version / to_timestamp_ms")
        client = self.catalog.client
        store = client.store
        heads = client._select_partitions(self._info, partitions)
        from lakesoul_tpu.meta.entity import MetaInfo, PartitionInfo

        # all partitions in ONE commit: a mid-loop conflict must not leave the
        # table half rolled back
        list_partition: list[PartitionInfo] = []
        read_info: list[PartitionInfo] = []
        for head in heads:
            if to_version is not None:
                target = store.get_partition_info_at_version(
                    self._info.table_id, head.partition_desc, to_version
                )
            else:
                target = store.get_partition_at_timestamp(
                    self._info.table_id, head.partition_desc, to_timestamp_ms
                )
            if target is None or target.version == head.version:
                continue
            list_partition.append(
                PartitionInfo(
                    table_id=self._info.table_id,
                    partition_desc=head.partition_desc,
                    snapshot=list(target.snapshot),
                )
            )
            read_info.append(head)
        if not list_partition:
            return 0
        client.commit_data(
            MetaInfo(
                table_info=self._info,
                list_partition=list_partition,
                read_partition_info=read_info,
            ),
            CommitOp.UPDATE,  # snapshot REPLACE with conflict detection
        )
        return len(list_partition)

    def add_columns(self, fields: list[pa.Field] | pa.Field) -> "LakeSoulTable":
        """Schema evolution: append nullable columns.  Existing files stay
        untouched; reads fill the new columns with nulls (reference: Flink
        auto DDL sync + CanCastSchemaBuilder semantics)."""
        if isinstance(fields, pa.Field):
            fields = [fields]
        schema = self.schema
        for f in fields:
            if f.name in schema.names:
                raise MetadataError(f"column {f.name!r} already exists")
            if not f.nullable:
                raise MetadataError(f"added column {f.name!r} must be nullable")
            schema = schema.append(f)
        self.catalog.client.update_table_schema(self._info.table_id, schema)
        return self.refresh()

    # ------------------------------------------------------------ compaction
    def compact(self, partitions: dict[str, str] | None = None, *, lease=None) -> int:
        """Merge each (partition, bucket)'s file stack into a single file and
        commit with CompactionCommit; replaced files go to the discard list
        for the cleaner.  Mirrors Spark CompactionCommand + CompactBucketIO.
        ``lease`` (from a leased compaction service) fences the commit and
        stamps its fencing token into the version row's expression.
        Returns the number of partitions compacted."""
        client = self.catalog.client
        heads = client._select_partitions(self._info, partitions)
        count = 0
        for head in heads:
            units = client.get_scan_plan_partitions(
                self._info.table_name,
                namespace=self._info.table_namespace,
                snapshot=[head],
            )
            if not units or all(len(u.data_files) <= 1 and not u.primary_keys for u in units):
                continue
            cfg = self.io_config()
            writer = TableWriter(cfg, self._info.table_path)
            old_files = []
            for unit in units:
                # streamed merge: a bucket deeper than the byte budget compacts
                # with flat memory (merged windows feed the writer, whose own
                # budget rolls oversized cells into several sorted files)
                for batch in iter_scan_unit_batches(
                    unit.data_files,
                    unit.primary_keys,
                    batch_size=cfg.batch_size,
                    memory_budget_bytes=cfg.memory_budget_bytes,
                    file_sizes=unit.file_sizes,
                    schema=self.schema,
                    partition_values=unit.partition_values,
                    merge_operators=cfg.merge_operators,
                    cdc_column=None,  # keep CDC rows through compaction
                ):
                    if len(batch):
                        writer.write_batch(batch)
                old_files.extend(unit.data_files)
            outputs = writer.close()
            self._commit_partition_rewrite(
                head, outputs, old_files, CommitOp.COMPACTION, lease=lease
            )
            count += 1
        return count

    # ---------------------------------------------------------- vector index
    def build_vector_index(self, column: str, **config_kwargs) -> int:
        """Train+persist per-(partition, bucket) ANN shards for a vector
        column (reference: LakeSoulTable.build_vector_index, catalog.py:496).
        Returns the number of vectors indexed."""
        from lakesoul_tpu.vector.builder import build_table_vector_index

        return build_table_vector_index(self, column, **config_kwargs)

    def vector_search(
        self,
        column: str,
        query,
        *,
        top_k: int = 10,
        nprobe: int = 8,
        partitions: dict[str, str] | None = None,
    ):
        """ANN search → (pk ids, distances), nearest first."""
        from lakesoul_tpu.vector.builder import search_table_vector_index

        return search_table_vector_index(
            self, column, query, top_k=top_k, nprobe=nprobe, partitions=partitions
        )

    # ------------------------------------------------------------------ scan
    def scan(self) -> "LakeSoulScan":
        return LakeSoulScan(self)

    def to_arrow(self) -> pa.Table:
        return self.scan().to_arrow()


class LakeSoulScan:
    """Lazy immutable scan builder (reference: LakeSoulScan, catalog.py:596).

    Chainable: ``table.scan().select(...).filter(...).shard(r, w).to_jax_iter()``.
    """

    def __init__(self, table: LakeSoulTable):
        self._table = table
        self._columns: list[str] | None = None
        self._filter: Filter | None = None
        self._partitions: dict[str, str] = {}
        self._rank: int | None = None
        self._world: int | None = None
        self._batch_size = 8192
        self._snapshot_ts: int | None = None
        self._incremental: tuple[int, int | None] | None = None
        self._keep_cdc_deletes = False
        self._vector_search: tuple | None = None
        self._cache = False
        self._limit: int | None = None
        # batch-source seam (data/batch_source.py): None = decode in this
        # process; a factory (scan → source) = remote delivery, e.g. a
        # scan-plane fleet via via_scanplane()
        self._batch_source_factory = None

    def _replace(self, **kw) -> "LakeSoulScan":
        s = copy.copy(self)
        for k, v in kw.items():
            setattr(s, k, v)
        return s

    # --------------------------------------------------------------- builder
    def select(self, columns: list[str]) -> "LakeSoulScan":
        return self._replace(_columns=list(columns))

    def filter(self, flt: "Filter | str") -> "LakeSoulScan":
        """Add a pushdown predicate: a Filter node, or a WHERE-style string
        (``scan.filter("f > 100 AND id IN (1, 2)")``) parsed by the SQL
        predicate grammar."""
        if isinstance(flt, str):
            from lakesoul_tpu.sql.parser import parse_predicate

            flt = parse_predicate(flt)
        elif not isinstance(flt, Filter):
            raise ConfigError(
                f"filter() takes a Filter or a predicate string, got {type(flt).__name__}"
            )
        new = flt if self._filter is None else (self._filter & flt)
        return self._replace(_filter=new)

    def partitions(self, parts: dict[str, str]) -> "LakeSoulScan":
        return self._replace(_partitions={**self._partitions, **{k: str(v) for k, v in parts.items()}})

    def shard(self, rank: int, world_size: int) -> "LakeSoulScan":
        """Explicit distributed shard: scan units are assigned round-robin
        ``i % world_size == rank`` (reference: arrow/dataset.py:366-397)."""
        if not 0 <= rank < world_size:
            raise ConfigError(f"invalid shard rank={rank} world={world_size}")
        return self._replace(_rank=rank, _world=world_size)

    def auto_shard(self) -> "LakeSoulScan":
        """Shard by this process's position on the data axis — the
        TPU-native analogue of the reference's torch.distributed
        auto-detection (arrow/dataset.py:353).  The axis resolves through
        the fleet plane (``jax.process_index()/process_count()``, with the
        ``LAKESOUL_FLEET_PROCESS_INDEX``/``_COUNT`` emulation override), so
        every consumer — jax, torch, ray — shards identically."""
        from lakesoul_tpu.fleet.multihost import process_axis

        index, count = process_axis()
        if count > 1:
            return self.shard(index, count)
        return self

    def batch_size(self, n: int) -> "LakeSoulScan":
        return self._replace(_batch_size=int(n))

    def limit(self, n: int) -> "LakeSoulScan":
        """Stop after ``n`` rows (arbitrary subset, like SQL LIMIT without
        ORDER BY): batch iteration ends early, skipping unread units."""
        if n < 0:
            raise ConfigError(f"limit must be non-negative, got {n}")
        return self._replace(_limit=int(n))

    def snapshot_at(self, timestamp_ms: int) -> "LakeSoulScan":
        return self._replace(_snapshot_ts=int(timestamp_ms))

    def incremental(self, start_ts_ms: int, end_ts_ms: int | None = None) -> "LakeSoulScan":
        return self._replace(_incremental=(int(start_ts_ms), end_ts_ms))

    def with_cdc_deletes(self) -> "LakeSoulScan":
        """Keep CDC delete rows (needed by incremental CDC consumers)."""
        return self._replace(_keep_cdc_deletes=True)

    def cache(self) -> "LakeSoulScan":
        """Cache this scan's decoded Arrow table in memory (tf.data
        ``cache()`` role): epochs 2+ of a training loop skip decode+merge
        entirely.  The cache key includes the partition version digest, so
        any commit to the table invalidates it automatically."""
        return self._replace(_cache=True)

    def via_scanplane(self, target, **client_kwargs) -> "LakeSoulScan":
        """Source this scan's batches from a scan-plane gateway instead of
        decoding in-process: ``target`` is a gateway location
        (``grpc://host:port``) or an existing
        :class:`~lakesoul_tpu.scanplane.client.ScanPlaneClient`.  Chainable
        like every builder method; every consumer downstream —
        ``to_batches``/``to_jax_iter``/``to_torch``/ray — then streams
        from the fleet with byte-identical results (``device_put``,
        collate, and loader stats stay client-side)."""
        from lakesoul_tpu.scanplane.client import ScanPlaneClient

        client = (
            target
            if isinstance(target, ScanPlaneClient)
            else ScanPlaneClient(target, **client_kwargs)
        )
        return self._replace(_batch_source_factory=client.source)

    def _cache_key(self) -> tuple:
        info = self._table.info
        heads = self._table.catalog.client.store.get_all_latest_partition_info(
            info.table_id
        )
        version_digest = tuple(sorted((h.partition_desc, h.version) for h in heads))
        import hashlib

        schema_digest = hashlib.md5(info.table_schema_arrow_ipc).hexdigest()
        return (
            info.table_id,
            schema_digest,  # add_columns invalidates even without a commit
            version_digest,
            tuple(self._columns) if self._columns is not None else None,
            self._filter.to_json() if self._filter is not None else None,
            tuple(sorted(self._partitions.items())),
            self._rank,
            self._world,
            self._snapshot_ts,
            self._incremental,
            self._keep_cdc_deletes,
            # _limit intentionally absent: limited reads recurse through the
            # unlimited scan, so the cache holds (and shares) the full result
        )

    def vector_search(self, column: str, query, *, top_k: int = 10, nprobe: int = 8) -> "LakeSoulScan":
        """ANN-filtered scan: search the table's index shards and inject a
        ``pk IN (matched ids)`` filter, so the scan returns the matching rows
        through the normal MOR path (reference:
        inject_vector_search_filter, reader.rs:250-344).

        Lazy like every other builder method: the search executes at read
        time, so partition filters chained before OR after this call narrow
        which shards are searched."""
        return self._replace(_vector_search=(column, query, int(top_k), int(nprobe)))

    def _resolve_vector_search(self) -> "LakeSoulScan":
        if self._vector_search is None:
            return self
        if self._snapshot_ts is not None or self._incremental is not None:
            raise ConfigError(
                "vector_search cannot be combined with snapshot/incremental scans:"
                " index shards always reflect the latest table state"
            )
        column, query, top_k, nprobe = self._vector_search
        ids, _ = self._table.vector_search(
            column, query, top_k=top_k, nprobe=nprobe,
            partitions=self._partitions or None,
        )
        pk = self._table.info.primary_keys[0]
        resolved = self._replace(_vector_search=None)
        return resolved.filter(Filter(op="in", col=pk, value=[int(i) for i in ids]))

    # ------------------------------------------------------------------ plan
    def scan_plan(self) -> list[ScanPlanPartition]:
        if self._vector_search is not None:
            return self._resolve_vector_search().scan_plan()
        return self._restrict_units(self._plan_units())

    def _plan_units(self) -> list[ScanPlanPartition]:
        """Scan units after partition selection, before bucket pruning and
        rank sharding (metadata only)."""
        client = self._table.catalog.client
        info = self._table.info
        if self._incremental is not None:
            units = client.incremental_scan_plan(
                info.table_name, self._incremental[0], self._incremental[1],
                namespace=info.table_namespace,
            )
            return self._filter_partitions(units)
        if self._snapshot_ts is not None:
            snapshot = client.get_snapshot_at_timestamp(
                info.table_name, self._snapshot_ts, namespace=info.table_namespace
            )
            return client.get_scan_plan_partitions(
                info.table_name, self._partitions, namespace=info.table_namespace,
                snapshot=snapshot,
            )
        return client.get_scan_plan_partitions(
            info.table_name, self._partitions, namespace=info.table_namespace
        )

    def explain(self) -> dict:
        """What this scan WILL do, from metadata alone — no data is read and
        a pending vector search is not executed.  The observability role of
        the reference's EXPLAIN over its TableProvider (DataFusion shows
        pushed filters and file groups); here the plan also quantifies
        partition/bucket pruning and merge work."""
        from lakesoul_tpu.io.filters import zone_conjuncts

        info = self._table.info
        out: dict[str, Any] = {
            "table": info.table_name,
            "columns": list(self._columns) if self._columns is not None else None,
            "filter": self._filter._to_dict() if self._filter is not None else None,
            "zone_predicates": [
                {"col": c, "op": op, "value": v}
                for c, op, v in zone_conjuncts(self._filter)
            ],
            "partitions": dict(self._partitions) or None,
            "snapshot_ts": self._snapshot_ts,
            "incremental": self._incremental,
            "limit": self._limit,
            "shard": (
                {"rank": self._rank, "world": self._world}
                if self._rank is not None
                else None
            ),
        }
        if self._vector_search is not None:
            col, _, top_k, nprobe = self._vector_search
            out["vector_search"] = {"column": col, "top_k": top_k, "nprobe": nprobe}
            out["note"] = "vector search resolves at read time to a pk IN filter"
            return out
        base = self._plan_units()
        pruned = self._prune_buckets(base)
        final = (
            pruned
            if self._rank is None
            else [u for i, u in enumerate(pruned) if i % self._world == self._rank]
        )
        files = [f for u in final for f in u.data_files]
        sizes = [s for u in final for s in (u.file_sizes or [])]
        by_ext: dict[str, int] = {}
        for f in files:
            by_ext[f.rsplit(".", 1)[-1]] = by_ext.get(f.rsplit(".", 1)[-1], 0) + 1
        # prune accounting: units are (partition × bucket) entries; on
        # multi-partition tables len(base)-len(pruned) overstates *bucket*
        # pruning (ADVICE r2), so report units_pruned plus the distinct
        # bucket ids that vanished entirely
        kept_buckets = {u.bucket_id for u in pruned}
        out.update(
            units=len(final),
            units_before_bucket_prune=len(base),
            units_pruned=len(base) - len(pruned),
            buckets_pruned=len(
                {u.bucket_id for u in base if u.bucket_id not in kept_buckets}
            ),
            merge_units=sum(1 for u in final if u.primary_keys),
            files=len(files),
            bytes_known=sum(sizes) if sizes else None,
            file_formats=by_ext,
        )
        return out

    def _filter_partitions(self, units: list[ScanPlanPartition]) -> list[ScanPlanPartition]:
        if not self._partitions:
            return units
        return [
            u
            for u in units
            if all(u.partition_values.get(k) == v for k, v in self._partitions.items())
        ]

    def _restrict_units(
        self, units: list[ScanPlanPartition], *, stable_shard: bool = False
    ) -> list[ScanPlanPartition]:
        """Shared unit restriction: bucket pruning + DP rank sharding.

        Batch scans shard round-robin by plan index (every rank computes the
        same full plan, so indices agree).  Streaming follow() must use
        ``stable_shard``: each rank polls with independent cursors and
        timing, so assignment has to key on stable unit identity, not
        enumeration order — otherwise a commit can be skipped by every rank
        or delivered twice."""
        units = self._prune_buckets(units)
        if self._rank is None:
            return units
        if not stable_shard:
            return [u for i, u in enumerate(units) if i % self._world == self._rank]
        import zlib

        def owner(u: ScanPlanPartition) -> int:
            ident = f"{u.partition_desc}/{u.bucket_id}"
            if u.bucket_id < 0 and u.data_files:
                ident += "/" + u.data_files[0].rsplit("/", 1)[-1]
            return zlib.crc32(ident.encode()) % self._world

        return [u for u in units if owner(u) == self._rank]

    def _prune_buckets(self, units: list[ScanPlanPartition]) -> list[ScanPlanPartition]:
        """Hash-bucket pruning: a PK-equality filter can only match rows in
        the buckets its values hash to (reader.rs:164-225)."""
        info = self._table.info
        pks = info.primary_keys
        if self._filter is None or len(pks) != 1:
            return units
        equalities = extract_pk_equalities(self._filter, pks)
        if not equalities:
            return units
        schema = info.arrow_schema
        dtype = schema.field(pks[0]).type
        n = info.hash_bucket_num
        live = {spark_hash.bucket_id_for_scalar(v, n, dtype) for _, v in equalities}
        return [u for u in units if u.bucket_id < 0 or u.bucket_id in live]

    # -------------------------------------------------------------- delivery
    def _unit_kwargs(self, unit: ScanPlanPartition) -> dict[str, Any]:
        info = self._table.info
        cfg = self._table.io_config()
        return dict(
            schema=info.arrow_schema,
            partition_values=unit.partition_values,
            filter=self._filter,
            merge_operators=cfg.merge_operators,
            cdc_column=info.cdc_column,
            drop_cdc_deletes=not self._keep_cdc_deletes,
            columns=self._columns,
            storage_options=self._table.catalog.storage_options,
        )

    def projected_schema(self) -> pa.Schema:
        """The Arrow schema this scan's batches carry (projection applied)
        — THE one definition, shared by local delivery and the scan
        plane's spool writer + gateway stream so they can never drift."""
        base = self._table.info.arrow_schema
        if self._columns is not None:
            return pa.schema([base.field(c) for c in self._columns])
        return base

    def _projected_empty_table(self) -> pa.Table:
        return self.projected_schema().empty_table()

    def to_arrow(self, *, parallel: bool | None = None) -> pa.Table:
        """Materialize the scan.  ``parallel=None`` (auto) decodes scan
        units concurrently on the shared runtime pool when there is more
        than one; unit order is preserved, so the result is byte-identical
        to ``parallel=False``."""
        if self._limit is not None or self._batch_source_factory is not None:
            batches = list(self.to_batches())
            if batches:
                return pa.Table.from_batches(batches)
            return self._projected_empty_table()
        if self._vector_search is not None:
            return self._resolve_vector_search().to_arrow(parallel=parallel)
        if self._cache:
            key = self._cache_key()
            hit = self._table.catalog._scan_cache_get(key)
            if hit is not None:
                return hit
            result = self._replace(_cache=False).to_arrow(parallel=parallel)
            self._table.catalog._scan_cache_put(key, result)
            return result
        units = self.scan_plan()

        def _read_unit(unit: ScanPlanPartition) -> pa.Table:
            return read_scan_unit(
                unit.data_files, unit.primary_keys, **self._unit_kwargs(unit)
            )

        if parallel is None:
            parallel = len(units) > 1
        if parallel and len(units) > 1:
            # ordered parallel fan-out over scan units (MOR merge of unit k
            # overlaps fetch+decode of units k+1..): deterministic unit
            # order in, deterministic table out
            decoded = rt_pipeline("scan").source(units).map_parallel(
                _read_unit, name="unit"
            ).run()
            tables = [t for t in decoded if len(t)]
        else:
            tables = [t for t in map(_read_unit, units) if len(t)]
        if not tables:
            return self._projected_empty_table()
        return pa.concat_tables(tables, promote_options="default").combine_chunks()

    def to_batches(
        self, num_threads: int | None = None, skip_rows: int = 0
    ) -> Iterator[pa.RecordBatch]:
        """Stream record batches.  ``num_threads > 1`` decodes scan units on a
        thread pool (unit order preserved, bounded in-flight window) — parquet
        decode and the numpy merge release the GIL, so multi-core hosts
        overlap unit decodes like the reference's per-bucket tokio readers.

        ``skip_rows`` resumes mid-stream (the LoaderCheckpoint path): whole
        scan units before the position are dropped via metadata/footer row
        counts — no decode — when the count is provably the delivered count
        (no filter/vector search/limit, unit needs no PK merge: the same
        conditions as the count_rows shortcut); the residual lands inside one
        unit and only that prefix is decoded and discarded."""
        if self._batch_source_factory is not None:
            # remote delivery (via_scanplane): the source owns limit/skip
            # semantics and yields the byte-identical stream
            yield from self._batch_source_factory(self).iter_batches(
                num_threads=num_threads, skip_rows=skip_rows
            )
            return
        if skip_rows:
            skip = skip_rows
            fast_ok = (
                self._filter is None
                and self._vector_search is None
                and not self._cache
                and self._limit is None
                # CDC: compacted files retain delete rows the decode drops,
                # so footer counts != delivered counts unless deletes are kept
                and (self._table.info.cdc_column is None or self._keep_cdc_deletes)
            )
            if fast_ok:
                from lakesoul_tpu.io.formats import format_for

                opts = self._table.catalog.storage_options
                units = self.scan_plan()
                idx = 0
                while idx < len(units) and skip:
                    u = units[idx]
                    if u.primary_keys:
                        break  # merge can collapse rows: count != delivered
                    n = sum(format_for(f).count_rows(f, opts) for f in u.data_files)
                    if n > skip:
                        break
                    skip -= n
                    idx += 1
                inner = self._iter_unit_batches(units[idx:], num_threads)
            else:
                inner = self.to_batches(num_threads)
            try:
                for b in inner:
                    if skip >= len(b):
                        skip -= len(b)
                        continue
                    if skip:
                        b = b.slice(skip)
                        skip = 0
                    yield b
            finally:
                inner.close()  # stop producer threads on early exit
            return
        if self._limit is not None:
            inner = self._replace(_limit=None).to_batches(num_threads)
            remaining = self._limit
            try:
                # check BEFORE pulling: advancing the iterator decodes the
                # next unit, which must not happen once the limit is met
                while remaining > 0:
                    b = next(inner, None)
                    if b is None:
                        break
                    if len(b) > remaining:
                        yield b.slice(0, remaining)
                        remaining = 0
                        break
                    remaining -= len(b)
                    yield b
            finally:
                inner.close()  # stop producer threads on early exit
            return
        if self._vector_search is not None:
            yield from self._resolve_vector_search().to_batches(num_threads)
            return
        if self._cache:
            key = self._cache_key()
            hit = self._table.catalog._scan_cache_get(key)
            if hit is None:
                uncached = self._replace(_cache=False)
                batches = list(uncached.to_batches(num_threads))
                hit = (
                    pa.Table.from_batches(batches)
                    if batches
                    else uncached.to_arrow()
                )
                self._table.catalog._scan_cache_put(key, hit)
            yield from hit.to_batches(max_chunksize=self._batch_size)
            return
        yield from self._iter_unit_batches(self.scan_plan(), num_threads)

    def _iter_unit_batches(
        self, units: list[ScanPlanPartition], num_threads: int | None
    ) -> Iterator[pa.RecordBatch]:
        """Batch production over an explicit unit list (unit order preserved)."""
        if not num_threads or num_threads <= 1 or len(units) <= 1:
            budget = self._table.io_config().memory_budget_bytes
            for unit in units:
                yield from iter_scan_unit_batches(
                    unit.data_files,
                    unit.primary_keys,
                    batch_size=self._batch_size,
                    memory_budget_bytes=budget,
                    file_sizes=unit.file_sizes,
                    **self._unit_kwargs(unit),
                )
            return
        # work items: merge units stay whole (the merge needs all streams of
        # a bucket), plain units split per file; every item STREAMS its
        # batches through the runtime pipeline's bounded per-slot queues, so
        # the in-flight window holds a few batches per unit — never a
        # materialized unit.  The byte budget splits across the concurrent
        # units.  Slot order = item order, so the batch stream is
        # byte-identical to the serial path.
        items: list[tuple[ScanPlanPartition, list[str], list[int] | None]] = []
        cfg = self._table.io_config()
        for u in units:
            if u.primary_keys or cfg.merge_operators:
                items.append((u, u.data_files, u.file_sizes))
            elif u.file_sizes and len(u.file_sizes) == len(u.data_files):
                items.extend(
                    (u, [f], [s]) for f, s in zip(u.data_files, u.file_sizes)
                )
            else:
                items.extend((u, [f], None) for f in u.data_files)

        unit_budget = max(8 << 20, cfg.memory_budget_bytes // (num_threads + 1))

        def stream_item(item):
            unit, files, sizes = item
            return iter_scan_unit_batches(
                files,
                unit.primary_keys,
                batch_size=self._batch_size,
                memory_budget_bytes=unit_budget,
                file_sizes=sizes,
                **self._unit_kwargs(unit),
            )

        it = (
            rt_pipeline("scan")
            .source(items)
            .flat_map_parallel(
                stream_item, workers=num_threads, buffer=4, name="unit_stream"
            )
            .run()
        )
        try:
            yield from it
        finally:
            it.close()  # abandoned generator: stop producers promptly

    def count_rows(self) -> int:
        """Row count; metadata-only when no decode is needed (reference:
        EmptyScanCountExec shortcut, session.rs:1036).  The shortcut applies
        when there is no filter/vector search and no unit needs a PK merge —
        merge can collapse duplicate keys, so merged units must be counted
        the slow way (a single PK file may itself hold duplicates)."""
        if (
            self._filter is None
            and self._vector_search is None
            and not self._cache
            # CDC: compacted files retain delete rows the decode drops
            and (self._table.info.cdc_column is None or self._keep_cdc_deletes)
        ):
            units = self.scan_plan()
            if all(not u.primary_keys for u in units):
                from lakesoul_tpu.io.formats import format_for

                opts = self._table.catalog.storage_options
                n = sum(
                    format_for(f).count_rows(f, opts)
                    for u in units
                    for f in u.data_files
                )
                return n if self._limit is None else min(n, self._limit)
        return sum(len(b) for b in self.to_batches())

    def follow(
        self,
        start_timestamp_ms: int | None = None,
        *,
        poll_interval: float = 1.0,
        stop_event=None,
        settle_ms=None,  # deprecated no-op, kept for API compat (see note)
        cursors: dict | None = None,
        state=None,
        slo=None,
        retry_policy=None,
    ) -> Iterator[pa.RecordBatch]:
        """Unbounded incremental source: yield batches for every commit after
        ``start_timestamp_ms`` (default: now), then keep polling for new
        commits — the role of the reference's unbounded Flink source
        (LakeSoulSource + dynamic split enumerator).  Stops when
        ``stop_event`` (threading.Event) is set; the idle wait rides
        ``stop_event.wait(poll_interval)``, so shutdown latency is bounded
        by ONE poll tick.

        The loop is the freshness follower
        (:class:`lakesoul_tpu.freshness.follower.FreshFollower`): polls and
        unit decodes run under the shared
        :class:`~lakesoul_tpu.runtime.resilience.RetryPolicy` (transient
        store/meta faults retry on the seeded schedule instead of killing
        the stream; permanent failures raise typed), and an attached
        ``slo`` (:class:`~lakesoul_tpu.freshness.slo.SloMonitor`) observes
        each delivered commit's commit-to-visible latency.

        Resume, two grains:

        - ``cursors`` (a dict the stream mutates in place; serialize with
          ``meta.client.follow_cursors_to_json``): commit-grained — a
          restarted consumer continues after the last *enumerated* commit
          (the pending-splits checkpointing of the reference's Flink
          source).
        - ``state`` (a :class:`~lakesoul_tpu.freshness.follower.
          FollowerState` or its JSON): row-exact — replays the recorded
          undelivered units, so a killed consumer resumes with no
          duplicated and no lost row.

        .. deprecated:: PR 12
            ``settle_ms`` has been a no-op since follow moved to version
            cursors (a commit is either visible with a new version number
            or it is not); the parameter is retained so existing callers
            keep working and will be removed in a future PR.
        """
        if settle_ms is not None:
            import warnings

            warnings.warn(
                "LakeSoulScan.follow(settle_ms=...) is deprecated and has"
                " no effect: version cursors made the settle window"
                " obsolete",
                DeprecationWarning,
                stacklevel=2,
            )
        from lakesoul_tpu.freshness.follower import FollowerState, FreshFollower

        if isinstance(state, str):
            state = FollowerState.from_json(state)
        follower = FreshFollower(
            self,
            start_timestamp_ms=start_timestamp_ms,
            state=state,
            cursors=cursors,
            poll_interval=poll_interval,
            stop_event=stop_event,
            retry_policy=retry_policy,
            slo=slo,
        )
        yield from follower.iter_batches()

    # jax / torch / huggingface delivery
    def to_jax_iter(self, **kwargs):
        """Double-buffered iterator of device-resident batches — see
        lakesoul_tpu.data.jax_iter.JaxBatchIterator."""
        from lakesoul_tpu.data.jax_iter import JaxBatchIterator

        return JaxBatchIterator(self, **kwargs)

    def to_torch(self):
        from lakesoul_tpu.data.torch_adapter import TorchIterableDataset

        return TorchIterableDataset(self)

    def to_huggingface(self, **kwargs):
        from lakesoul_tpu.data.hf_adapter import to_hf_dataset

        return to_hf_dataset(self, **kwargs)

    def to_pandas(self):
        return self.to_arrow().to_pandas()
