from lakesoul_tpu.compaction.service import (
    CompactionService,
    LeasedCompactionService,
)
from lakesoul_tpu.compaction.cleaner import Cleaner

__all__ = ["CompactionService", "LeasedCompactionService", "Cleaner"]
