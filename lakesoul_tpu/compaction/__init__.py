from lakesoul_tpu.compaction.service import CompactionService
from lakesoul_tpu.compaction.cleaner import Cleaner

__all__ = ["CompactionService", "Cleaner"]
