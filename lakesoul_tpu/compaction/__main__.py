"""``python -m lakesoul_tpu.compaction`` — the standalone compaction
service process (the role of the reference's Spark compaction-service
job): polls the shared metadata store for committed-version gaps and
compacts them under per-partition leases, so any number of these
processes can run against one warehouse without double-compacting.

The chaos suite (tests/test_topology.py) runs THIS entry point as the
child it SIGKILLs — what is tested is what deploys."""

from __future__ import annotations

import argparse
import json
import logging


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "lakesoul-compactor",
        description="leased compaction service over a lakesoul_tpu warehouse",
    )
    p.add_argument("--warehouse", required=True)
    p.add_argument("--db-path", default=None)
    p.add_argument("--lease-ttl-s", type=float, default=None,
                   help="lease TTL (default LAKESOUL_LEASE_TTL_S or 30)")
    p.add_argument("--poll-s", type=float, default=None,
                   help="poll interval (default LAKESOUL_COMPACTION_POLL_S or 5)")
    p.add_argument("--min-file-num", type=int, default=2)
    p.add_argument("--version-gap", type=int, default=None,
                   help="committed-version gap that marks a partition as a"
                        " compaction candidate (default: store trigger gap)")
    p.add_argument("--service-id", default=None)
    p.add_argument("--once", action="store_true",
                   help="one poll+work cycle, print outcome counts as JSON, exit")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.compaction.service import LeasedCompactionService
    from lakesoul_tpu.obs import fleet

    catalog = LakeSoulCatalog(args.warehouse, db_path=args.db_path)
    svc = LeasedCompactionService(
        catalog,
        service_id=args.service_id,
        lease_ttl_s=args.lease_ttl_s,
        poll_interval_s=args.poll_s,
        min_file_num=args.min_file_num,
        version_gap=args.version_gap,
    )
    fleet.arm("compactor", service_id=svc.service_id)
    if args.once:
        print(json.dumps(svc.poll_once()), flush=True)
        return 0
    print(
        f"compaction service {svc.service_id} polling every"
        f" {svc.poll_interval_s}s (lease ttl {svc.lease_ttl_s}s)",
        flush=True,
    )
    try:
        svc.run_forever()
    except KeyboardInterrupt:
        svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
