"""Cleaner: expire old snapshots and delete discarded/orphaned data files.

Role parity with the reference's Spark cleaner
(lakesoul-spark/…/clean/CleanExpiredData.scala): per table it
1. drops partition versions older than the retention window — but never the
   current head, and never versions newer than the latest CompactionCommit
   at-or-before the cutoff (time travel inside the window keeps working);
2. deletes data files that no surviving snapshot references;
3. deletes files queued in ``discard_compressed_file_info`` (compaction
   leftovers) past their grace period."""

from __future__ import annotations

import logging

from lakesoul_tpu.io.object_store import delete_file
from lakesoul_tpu.meta.entity import now_millis

logger = logging.getLogger(__name__)


class Cleaner:
    def __init__(self, catalog, *, retention_ms: int = 7 * 24 * 3600 * 1000,
                 discard_grace_ms: int = 3600 * 1000, deleter=None):
        """``deleter`` routes object deletes somewhere other than the store
        directly — pass ``ProxyDeleter`` (service/storage_proxy.py) to push
        the cleaner's destructive traffic through the RBAC-enforcing proxy
        (the reference proxies every verb, s3-proxy/src/main.rs:350); the
        default talks to the object store like the reference's Spark
        cleaner does."""
        self.catalog = catalog
        self.retention_ms = retention_ms
        self.discard_grace_ms = discard_grace_ms
        self._delete = deleter or delete_file

    def _version_retention_for(self, info) -> int:
        """``lakesoul.version.retention`` (days) beats the cleaner default;
        absent/invalid values fall back (logged in TableInfo parsing terms:
        accessor returns None)."""
        days = info.version_retention_days
        if days is None and "lakesoul.version.retention" in (info.properties or {}):
            logger.warning(
                "table %s has invalid lakesoul.version.retention=%r; using default",
                info.table_name, info.properties.get("lakesoul.version.retention"),
            )
        if days is None:
            return self.retention_ms
        return int(days * 24 * 3600 * 1000)

    def expire_partitions(self, table_name: str, namespace: str = "default",
                          *, now_ms: int | None = None) -> int:
        """``partition.ttl`` (days) = partition DATA lifetime, matching the
        reference's semantics: a partition whose NEWEST commit is older than
        the ttl is deleted outright (DeleteCommit + live files removed).
        Returns the number of partitions expired."""
        now_ms = now_ms or now_millis()
        client = self.catalog.client
        info = client.get_table_info_by_name(table_name, namespace)
        days = info.partition_ttl_days
        if days is None:
            if "partition.ttl" in (info.properties or {}):
                logger.warning(
                    "table %s has invalid partition.ttl=%r; skipping expiry",
                    info.table_name, info.properties.get("partition.ttl"),
                )
            return 0
        cutoff = now_ms - int(days * 24 * 3600 * 1000)
        from lakesoul_tpu.meta.entity import CommitOp, MetaInfo, PartitionInfo

        expired = 0
        for head in client.store.get_all_latest_partition_info(info.table_id):
            if head.timestamp > cutoff or not head.snapshot:
                continue
            live = client._files_for_partition(head)
            client.commit_data(
                MetaInfo(
                    table_info=info,
                    list_partition=[PartitionInfo(info.table_id, head.partition_desc)],
                ),
                CommitOp.DELETE,
            )
            for f in live:
                self._delete(f.path, self.catalog.storage_options, missing_ok=True)
            logger.info(
                "expired partition %s of %s (%d files)",
                head.partition_desc, table_name, len(live),
            )
            expired += 1
        return expired

    def clean_table(self, table_name: str, namespace: str = "default",
                    *, now_ms: int | None = None) -> dict:
        """Returns {"versions_dropped": n, "files_deleted": n}."""
        now_ms = now_ms or now_millis()
        client = self.catalog.client
        info = client.get_table_info_by_name(table_name, namespace)
        cutoff = now_ms - self._version_retention_for(info)
        store = client.store
        versions_dropped = 0
        files_deleted = 0

        for head in store.get_all_latest_partition_info(info.table_id):
            versions = store.get_partition_versions(info.table_id, head.partition_desc)
            # newest version at-or-before the cutoff that we can anchor on:
            # everything strictly older is reconstructible from it only if it
            # is a CompactionCommit; otherwise keep the chain
            keep_from = 0
            for v in versions:
                if v.timestamp <= cutoff and v.commit_op.value == "CompactionCommit":
                    keep_from = v.version
            if keep_from == 0:
                continue
            # commits still referenced by surviving versions
            surviving = {c for v in versions if v.version >= keep_from for c in v.snapshot}
            dropped = store.delete_partition_versions_before(
                info.table_id, head.partition_desc, keep_from
            )
            versions_dropped += len(dropped)
            dead_commits = {
                c for v in dropped for c in v.snapshot if c not in surviving
            }
            for cid in dead_commits:
                try:
                    commits = store.get_data_commit_info(
                        info.table_id, head.partition_desc, [cid]
                    )
                except Exception:
                    continue
                for commit in commits:
                    for op in commit.file_ops:
                        self._delete(op.path, self.catalog.storage_options, missing_ok=True)
                        files_deleted += 1
                store.delete_data_commit_info(info.table_id, head.partition_desc, [cid])
        return {"versions_dropped": versions_dropped, "files_deleted": files_deleted}

    def clean_discarded_files(self, *, now_ms: int | None = None) -> int:
        """Delete compaction-replaced files past the grace period
        (reference: discard_compressed_file_info consumption)."""
        now_ms = now_ms or now_millis()
        store = self.catalog.client.store
        rows = store.list_discard_files(older_than_ms=now_ms - self.discard_grace_ms)
        deleted = []
        for file_path, _table_path, _desc in rows:
            self._delete(file_path, self.catalog.storage_options, missing_ok=True)
            deleted.append(file_path)
        store.delete_discard_files(deleted)
        return len(deleted)

    def clean_all(self, *, now_ms: int | None = None) -> dict:
        out = {
            "versions_dropped": 0,
            "files_deleted": 0,
            "discarded_deleted": 0,
            "partitions_expired": 0,
        }
        for ns in self.catalog.list_namespaces():
            for name in self.catalog.list_tables(ns):
                out["partitions_expired"] += self.expire_partitions(name, ns, now_ms=now_ms)
                r = self.clean_table(name, ns, now_ms=now_ms)
                out["versions_dropped"] += r["versions_dropped"]
                out["files_deleted"] += r["files_deleted"]
        out["discarded_deleted"] = self.clean_discarded_files(now_ms=now_ms)
        return out
