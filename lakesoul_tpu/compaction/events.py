"""Compaction event delivery across process boundaries.

The reference's compaction service LISTENs on a PG channel that a trigger
NOTIFYs (meta_init.sql:101-150).  This module gives the Python stack the
same *shape* — a :class:`CompactionNotifier` that pushes
:class:`~lakesoul_tpu.meta.store.CompactionEvent`\\ s to subscribed
callbacks — over two transports:

- :class:`StoreTriggerNotifier`: the PR-6-era in-process path — the store
  fires listeners synchronously in the committing writer's process.  Fast,
  but events die with the process and never cross one.
- :class:`PollingWatermarkNotifier`: the cross-process path for SQLite
  deployments.  Events are **derived, not messaged**: each ``poll()``
  re-computes the partitions whose committed head is ≥ ``version_gap``
  versions past their last CompactionCommit
  (``store.get_compaction_candidates``).  The consumer's watermark is the
  last CompactionCommit version already in ``partition_info`` — committed
  state, not consumer memory — so a SIGKILLed consumer loses nothing: the
  gap persists and the next poll, in any process, re-emits the event.
  A PostgreSQL deployment drops in a LISTEN/NOTIFY notifier behind the
  same three methods and the service code does not change.

Deduplication is deliberately the *consumer's* job (in-flight sets,
per-partition leases): at-least-once delivery is the crash-safe default,
and the leases make the redundant deliveries harmless.
"""

from __future__ import annotations

import logging
from typing import Callable

from lakesoul_tpu.meta.store import (
    COMPACTION_TRIGGER_VERSION_GAP,
    CompactionEvent,
)

logger = logging.getLogger(__name__)


class CompactionNotifier:
    """LISTEN/NOTIFY-shaped event source: ``listen`` registers a callback,
    ``poll`` pumps pending events for pull-based transports (push-based
    ones no-op it), ``close`` detaches."""

    def listen(self, fn: Callable[[CompactionEvent], None]) -> None:
        raise NotImplementedError

    def unlisten(self, fn: Callable[[CompactionEvent], None]) -> None:
        raise NotImplementedError

    def poll(self) -> int:
        """Deliver pending events to listeners; returns how many."""
        return 0

    def close(self) -> None:
        pass


class StoreTriggerNotifier(CompactionNotifier):
    """In-process push transport: adapts the store's synchronous trigger
    listeners (``SqliteMetadataStore._fire_compaction_triggers``) to the
    notifier API.  Events fire inside the committing writer's process —
    the single-process deployment shape."""

    def __init__(self, store):
        self.store = store
        self._fns: list[Callable[[CompactionEvent], None]] = []

    def listen(self, fn) -> None:
        self._fns.append(fn)
        self.store.add_compaction_listener(fn)

    def unlisten(self, fn) -> None:
        try:
            self._fns.remove(fn)
            self.store.remove_compaction_listener(fn)
        except ValueError:
            pass

    def close(self) -> None:
        for fn in list(self._fns):
            self.unlisten(fn)


class PollingWatermarkNotifier(CompactionNotifier):
    """Pull transport over committed-version gaps (see module docstring).

    ``poll()`` is cheap — one grouped SQL scan of ``partition_info`` — and
    *stateless across crashes*: the watermark each partition is compared
    against is its last CompactionCommit version, which only a successful
    compaction advances.  Every open gap is re-delivered on every poll
    (at-least-once); suppressing repeats is the consumer's job — the
    leased service already tracks not-compactable heads, and per-partition
    leases make redundant deliveries harmless.

    Failure isolation (the long-running-service contract): the candidate
    derivation runs under the shared
    :class:`~lakesoul_tpu.runtime.resilience.RetryPolicy` (transient store
    blips retry on the seeded schedule; exhaustion/permanent errors fail
    THIS poll only — logged, counted, re-derived next tick, because the
    watermark is committed state and loses nothing).  A raising listener
    no longer aborts the poll: its exception is logged once with the
    active trace id, counted into
    ``lakesoul_notifier_listener_errors_total``, and the remaining
    listeners and events still see the delivery."""

    def __init__(
        self,
        store,
        *,
        version_gap: int = COMPACTION_TRIGGER_VERSION_GAP,
        retry_policy=None,
    ):
        from lakesoul_tpu.runtime.resilience import RetryPolicy

        self.store = store
        self.version_gap = version_gap
        self._fns: list[Callable[[CompactionEvent], None]] = []
        self._policy = retry_policy or RetryPolicy.from_env()

    def listen(self, fn) -> None:
        self._fns.append(fn)

    def unlisten(self, fn) -> None:
        try:
            self._fns.remove(fn)
        except ValueError:
            pass

    def _candidates(self) -> list[CompactionEvent]:
        from lakesoul_tpu.obs import registry

        def attempt():
            return list(self.store.get_compaction_candidates(self.version_gap))

        try:
            return self._policy.run(attempt, op="notifier.poll")
        except Exception:
            # candidates are RE-DERIVED every poll from committed state: a
            # failed derivation delays delivery by one tick, it must never
            # kill the owning service loop
            registry().counter("lakesoul_notifier_poll_errors_total").inc()
            logger.exception(
                "compaction candidate derivation failed; retrying next poll"
            )
            return []

    def poll(self) -> int:
        if not self._fns:
            return 0
        from lakesoul_tpu.obs import registry
        from lakesoul_tpu.obs.tracing import current_span

        delivered = 0
        for ev in self._candidates():
            for fn in list(self._fns):
                try:
                    fn(ev)
                except Exception:
                    # isolate: one bad listener must not starve the others
                    # (or later events) of the delivery
                    registry().counter(
                        "lakesoul_notifier_listener_errors_total"
                    ).inc()
                    sp = current_span()
                    logger.exception(
                        "compaction listener %r failed for %s/%s (trace %s)",
                        getattr(fn, "__qualname__", fn),
                        ev.table_id,
                        ev.partition_desc,
                        sp.trace_id if sp is not None else "-",
                    )
            delivered += 1
        return delivered

    def close(self) -> None:
        self._fns.clear()
