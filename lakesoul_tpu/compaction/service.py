"""Automatic compaction service.

Role parity with the reference's Spark compaction service
(lakesoul-spark/…/compaction/NewCompactionTask.scala:22-150): it LISTENs for
`lakesoul_compaction_notify` events that the PG trigger emits when a
partition's version gap since the last CompactionCommit reaches the threshold
(meta_init.sql:101-150), hashes the partition onto a worker pool, and runs
the compaction through the normal write path.

Here the metadata store fires the same event synchronously
(SqliteMetadataStore._fire_compaction_triggers); the service runs jobs on
the shared execution runtime's worker pool (lakesoul_tpu/runtime/pool.py —
no dedicated threads), bounded to ``workers`` concurrent jobs over a
bounded pending queue, deduplicates in-flight partitions, and also supports
size-tiered scheduled sweeps (the reference's "new compaction" path with
file-number/size limits)."""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from lakesoul_tpu.errors import CommitConflictError
from lakesoul_tpu.meta.store import CompactionEvent
from lakesoul_tpu.obs import registry, span
from lakesoul_tpu.runtime import get_pool

logger = logging.getLogger(__name__)


@dataclass
class CompactionStats:
    triggered: int = 0
    compacted: int = 0
    skipped: int = 0
    conflicts: int = 0
    errors: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def bump(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        # mirrored into the shared registry so one /metrics endpoint covers
        # the compaction service next to streams/cache/loader
        registry().counter("lakesoul_compaction_events_total", kind=name).inc()


class CompactionService:
    """Consume compaction events for one catalog and compact them as jobs on
    the shared runtime pool (at most ``workers`` concurrently).

    Usage::

        svc = CompactionService(catalog, workers=2)
        svc.start()           # subscribes to the store's trigger events
        ...                   # writes keep committing; gaps trigger events
        svc.drain(); svc.stop()
    """

    def __init__(
        self,
        catalog,
        *,
        workers: int = 2,
        min_file_num: int = 2,
        queue_size: int = 256,
    ):
        self.catalog = catalog
        self.workers = workers
        self.min_file_num = min_file_num
        self.queue_size = queue_size
        self.stats = CompactionStats()
        self._lock = threading.Lock()
        self._pending: list[CompactionEvent] = []
        self._running = 0
        self._in_flight: set[tuple[str, str]] = set()
        self._idle = threading.Condition(self._lock)
        self._stop = threading.Event()
        # updated with inc/dec DELTAS, never set(): several services in one
        # process (one per catalog) then aggregate instead of clobbering
        # each other's snapshots
        reg = registry()
        self._g_pending = reg.gauge("lakesoul_compaction_pending")
        self._g_running = reg.gauge("lakesoul_compaction_running")

    # --------------------------------------------------------------- control
    def start(self) -> None:
        self._stop.clear()
        self.catalog.client.store.add_compaction_listener(self._on_event)

    def stop(self, timeout: float = 5.0) -> None:
        """Unsubscribe, drop queued events, wait (bounded) for running jobs."""
        self._stop.set()
        try:
            self.catalog.client.store.remove_compaction_listener(self._on_event)
        except ValueError:
            pass
        import time

        deadline = time.time() + timeout
        with self._idle:
            for ev in self._pending:
                self._in_flight.discard((ev.table_id, ev.partition_desc))
            self._g_pending.dec(len(self._pending))
            self._pending.clear()
            while self._running:
                left = deadline - time.time()
                if left <= 0:
                    break
                self._idle.wait(timeout=left)

    def drain(self, timeout: float = 30.0) -> None:
        """Block until no events are pending and no job is running."""
        import time

        deadline = time.time() + timeout
        with self._idle:
            while self._pending or self._running:
                left = deadline - time.time()
                if left <= 0:
                    return
                self._idle.wait(timeout=min(left, 0.1))

    # ---------------------------------------------------------------- events
    def _on_event(self, event: CompactionEvent) -> None:
        self.stats.bump("triggered")
        key = (event.table_id, event.partition_desc)
        with self._lock:
            if self._stop.is_set():
                return
            if key in self._in_flight:
                return  # already queued/running for this partition
            if len(self._pending) >= self.queue_size:
                logger.warning("compaction queue full; dropping event for %s", key)
                return
            self._in_flight.add(key)
            self._pending.append(event)
            self._g_pending.inc()
        self._pump()

    def _pump(self) -> None:
        """Submit pending events to the pool up to the ``workers`` bound."""
        while True:
            with self._lock:
                if self._stop.is_set() or self._running >= self.workers or not self._pending:
                    return
                event = self._pending.pop(0)
                self._running += 1
                self._g_pending.dec()
                self._g_running.inc()
            get_pool().submit(self._job, event)

    def _job(self, event: CompactionEvent) -> None:
        key = (event.table_id, event.partition_desc)
        try:
            # a job that was queued behind other pool work may only get a
            # worker AFTER stop() — it must not compact against a catalog
            # the caller already tore down
            if not self._stop.is_set():
                self._compact_one(event)
        except Exception:
            self.stats.bump("errors")
            logger.exception("compaction failed for %s", key)
        finally:
            with self._idle:
                self._in_flight.discard(key)
                self._running -= 1
                self._g_running.dec()
                self._idle.notify_all()
            self._pump()

    def _compact_one(self, event: CompactionEvent) -> None:
        sp = span("compaction.job", partition=event.partition_desc)
        try:
            with sp:
                self._compact_one_inner(event)
        finally:
            # the span already timed the job (duration_s is set even when
            # the body raised) — feed the histogram from it
            registry().histogram("lakesoul_compaction_job_seconds").observe(
                sp.duration_s or 0.0
            )

    def _compact_one_inner(self, event: CompactionEvent) -> None:
        from lakesoul_tpu.meta.client import partition_desc_to_dict
        from lakesoul_tpu.runtime.resilience import RetryPolicy

        info = self.catalog.client.store.get_table_info_by_id(event.table_id)
        if info is None:
            self.stats.bump("skipped")
            return
        table = self.catalog.table(info.table_name, info.table_namespace)
        parts = partition_desc_to_dict(event.partition_desc) or None

        # writers may advance the partition mid-compact; each retry re-reads
        # the fresh head, like the reference re-running on the next notify —
        # now with backoff between attempts (a hot writer gets a beat to
        # finish its burst) and a lakesoul_retry_exhausted_total{op=
        # compaction.conflict} signal when the job gives up, instead of the
        # old silent fixed-3 loop
        def attempt() -> str:
            if not self._needs_compaction(table, event.partition_desc):
                return "skipped"
            try:
                return "compacted" if table.compact(parts) else "skipped"
            except CommitConflictError:
                self.stats.bump("conflicts")
                raise

        policy = RetryPolicy.from_env(
            max_attempts=3,
            base_delay_s=0.02,
            max_delay_s=0.25,
            classify=lambda e: isinstance(e, CommitConflictError),
        )
        try:
            outcome = policy.run(attempt, op="compaction.conflict")
        except CommitConflictError:
            logger.info(
                "compaction kept losing races for %s; deferring", event.partition_desc
            )
            return
        self.stats.bump(outcome)

    def _needs_compaction(self, table, partition_desc: str) -> bool:
        """Size-tiered gate: only compact when some bucket stacks at least
        min_file_num files (reference: file num/size limits in the
        new-compaction path)."""
        units = table.scan().scan_plan()
        for u in units:
            if u.partition_desc == partition_desc and len(u.data_files) >= self.min_file_num:
                return True
        return False

    # ------------------------------------------------------------- full sweep
    def sweep(self) -> int:
        """Compact every table/partition that crosses the file threshold —
        the scheduled fallback when no trigger fired (e.g. after restarts)."""
        total = 0
        for ns in self.catalog.list_namespaces():
            for name in self.catalog.list_tables(ns):
                table = self.catalog.table(name, ns)
                units = table.scan().scan_plan()
                descs = {
                    u.partition_desc
                    for u in units
                    if len(u.data_files) >= self.min_file_num
                }
                for desc in descs:
                    from lakesoul_tpu.meta.client import partition_desc_to_dict

                    try:
                        total += table.compact(partition_desc_to_dict(desc) or None)
                    except CommitConflictError:
                        self.stats.bump("conflicts")
        return total
