"""Automatic compaction services.

Role parity with the reference's Spark compaction service
(lakesoul-spark/…/compaction/NewCompactionTask.scala:22-150): it LISTENs for
`lakesoul_compaction_notify` events that the PG trigger emits when a
partition's version gap since the last CompactionCommit reaches the threshold
(meta_init.sql:101-150), hashes the partition onto a worker pool, and runs
the compaction through the normal write path.

Two deployment shapes:

- :class:`CompactionService` — single process: the metadata store fires the
  trigger event synchronously in the committing writer's process
  (SqliteMetadataStore._fire_compaction_triggers); jobs run on the shared
  runtime worker pool, bounded and deduplicated.
- :class:`LeasedCompactionService` — the **multi-process topology**: a
  standalone service process (``python -m lakesoul_tpu.compaction``) that
  discovers work by polling committed-version gaps
  (:class:`~lakesoul_tpu.compaction.events.PollingWatermarkNotifier` — the
  LISTEN/NOTIFY-shaped source, so a PG transport drops in later), takes a
  **per-partition lease** with a TTL and a fencing token
  (``meta/store.py`` lease table) before compacting, and commits with the
  lease as an atomic guard.  A SIGKILLed holder's lease expires after one
  TTL and any peer takes over (``lakesoul_compaction_takeovers_total``);
  the dead holder, were it ever to wake, is fenced at commit time — never
  a double-compaction.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from lakesoul_tpu.errors import CommitConflictError, LeaseFencedError
from lakesoul_tpu.meta.store import CompactionEvent
from lakesoul_tpu.obs import registry, span
from lakesoul_tpu.runtime import get_pool

logger = logging.getLogger(__name__)

from lakesoul_tpu.runtime.resilience import _env_float

ENV_LEASE_TTL_S = "LAKESOUL_LEASE_TTL_S"
ENV_POLL_S = "LAKESOUL_COMPACTION_POLL_S"


def needs_compaction(table, partition_desc: str, min_file_num: int) -> bool:
    """Size-tiered gate shared by both services: only compact when some
    bucket stacks at least ``min_file_num`` files (reference: file
    num/size limits in the new-compaction path)."""
    units = table.scan().scan_plan()
    for u in units:
        if u.partition_desc == partition_desc and len(u.data_files) >= min_file_num:
            return True
    return False


def _run_conflict_retried_compaction(
    table, event: CompactionEvent, stats: "CompactionStats", min_file_num: int,
    *, lease=None, pre_attempt=None,
) -> str:
    """THE compaction attempt-loop, shared by the in-process service and the
    leased service so its conflict-retry tuning lives in one place.

    Writers may advance the partition mid-compact; each retry re-reads the
    fresh head, like the reference re-running on the next notify — with
    backoff between attempts (a hot writer gets a beat to finish its burst)
    and a ``lakesoul_retry_exhausted_total{op=compaction.conflict}`` signal
    when the job gives up.  ``pre_attempt`` runs before each try (the leased
    service fences on a lapsed heartbeat there).  Returns the outcome
    counter name; ``"conflicts"`` when retries exhaust."""
    from lakesoul_tpu.meta.client import partition_desc_to_dict
    from lakesoul_tpu.runtime.resilience import RetryPolicy

    parts = partition_desc_to_dict(event.partition_desc) or None

    def attempt() -> str:
        if pre_attempt is not None:
            pre_attempt()
        if not needs_compaction(table, event.partition_desc, min_file_num):
            return "skipped"
        try:
            return "compacted" if table.compact(parts, lease=lease) else "skipped"
        except CommitConflictError:
            stats.bump("conflicts")
            raise

    policy = RetryPolicy.from_env(
        max_attempts=3,
        base_delay_s=0.02,
        max_delay_s=0.25,
        classify=lambda e: isinstance(e, CommitConflictError),
    )
    try:
        return policy.run(attempt, op="compaction.conflict")
    except CommitConflictError:
        logger.info(
            "compaction kept losing races for %s; deferring to a later"
            " poll", event.partition_desc,
        )
        return "conflicts"


@dataclass
class CompactionStats:
    triggered: int = 0
    compacted: int = 0
    skipped: int = 0
    conflicts: int = 0
    errors: int = 0
    # leased-service outcomes
    lease_held: int = 0
    fenced: int = 0
    takeovers: int = 0

    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def bump(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        # mirrored into the shared registry so one /metrics endpoint covers
        # the compaction service next to streams/cache/loader
        registry().counter("lakesoul_compaction_events_total", kind=name).inc()


class CompactionService:
    """Consume compaction events for one catalog and compact them as jobs on
    the shared runtime pool (at most ``workers`` concurrently).

    Usage::

        svc = CompactionService(catalog, workers=2)
        svc.start()           # subscribes to the store's trigger events
        ...                   # writes keep committing; gaps trigger events
        svc.drain(); svc.stop()
    """

    def __init__(
        self,
        catalog,
        *,
        workers: int = 2,
        min_file_num: int = 2,
        queue_size: int = 256,
        notifier=None,
    ):
        from lakesoul_tpu.compaction.events import StoreTriggerNotifier

        self.catalog = catalog
        self.notifier = notifier or StoreTriggerNotifier(catalog.client.store)
        self.workers = workers
        self.min_file_num = min_file_num
        self.queue_size = queue_size
        self.stats = CompactionStats()
        self._lock = threading.Lock()
        self._pending: list[CompactionEvent] = []
        self._running = 0
        self._in_flight: set[tuple[str, str]] = set()
        self._idle = threading.Condition(self._lock)
        self._stop = threading.Event()
        # updated with inc/dec DELTAS, never set(): several services in one
        # process (one per catalog) then aggregate instead of clobbering
        # each other's snapshots
        reg = registry()
        self._g_pending = reg.gauge("lakesoul_compaction_pending")
        self._g_running = reg.gauge("lakesoul_compaction_running")

    # --------------------------------------------------------------- control
    def start(self) -> None:
        self._stop.clear()
        self.notifier.listen(self._on_event)

    def stop(self, timeout: float = 5.0) -> None:
        """Unsubscribe, drop queued events, wait (bounded) for running jobs."""
        self._stop.set()
        self.notifier.unlisten(self._on_event)
        # monotonic: an NTP step during shutdown must not turn a 5 s grace
        # period into 0 (or an hour) — enforced by the wall-clock-lease lint
        deadline = time.monotonic() + timeout
        with self._idle:
            for ev in self._pending:
                self._in_flight.discard((ev.table_id, ev.partition_desc))
            self._g_pending.dec(len(self._pending))
            self._pending.clear()
            while self._running:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._idle.wait(timeout=left)

    def drain(self, timeout: float = 30.0) -> None:
        """Block until no events are pending and no job is running."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._pending or self._running:
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._idle.wait(timeout=min(left, 0.1))

    # ---------------------------------------------------------------- events
    def _on_event(self, event: CompactionEvent) -> None:
        self.stats.bump("triggered")
        key = (event.table_id, event.partition_desc)
        with self._lock:
            if self._stop.is_set():
                return
            if key in self._in_flight:
                return  # already queued/running for this partition
            if len(self._pending) >= self.queue_size:
                logger.warning("compaction queue full; dropping event for %s", key)
                return
            self._in_flight.add(key)
            self._pending.append(event)
            self._g_pending.inc()
        self._pump()

    def _pump(self) -> None:
        """Submit pending events to the pool up to the ``workers`` bound."""
        while True:
            with self._lock:
                if self._stop.is_set() or self._running >= self.workers or not self._pending:
                    return
                event = self._pending.pop(0)
                self._running += 1
                self._g_pending.dec()
                self._g_running.inc()
            get_pool().submit(self._job, event)

    def _job(self, event: CompactionEvent) -> None:
        key = (event.table_id, event.partition_desc)
        try:
            # a job that was queued behind other pool work may only get a
            # worker AFTER stop() — it must not compact against a catalog
            # the caller already tore down
            if not self._stop.is_set():
                self._compact_one(event)
        except Exception:
            self.stats.bump("errors")
            logger.exception("compaction failed for %s", key)
        finally:
            with self._idle:
                self._in_flight.discard(key)
                self._running -= 1
                self._g_running.dec()
                self._idle.notify_all()
            self._pump()

    def _compact_one(self, event: CompactionEvent) -> None:
        sp = span("compaction.job", partition=event.partition_desc)
        try:
            with sp:
                self._compact_one_inner(event)
        finally:
            # the span already timed the job (duration_s is set even when
            # the body raised) — feed the histogram from it
            registry().histogram("lakesoul_compaction_job_seconds").observe(
                sp.duration_s or 0.0
            )

    def _compact_one_inner(self, event: CompactionEvent) -> None:
        info = self.catalog.client.store.get_table_info_by_id(event.table_id)
        if info is None:
            self.stats.bump("skipped")
            return
        table = self.catalog.table(info.table_name, info.table_namespace)
        outcome = _run_conflict_retried_compaction(
            table, event, self.stats, self.min_file_num
        )
        if outcome != "conflicts":
            self.stats.bump(outcome)

    # ------------------------------------------------------------- full sweep
    def sweep(self) -> int:
        """Compact every table/partition that crosses the file threshold —
        the scheduled fallback when no trigger fired (e.g. after restarts)."""
        total = 0
        for ns in self.catalog.list_namespaces():
            for name in self.catalog.list_tables(ns):
                table = self.catalog.table(name, ns)
                units = table.scan().scan_plan()
                descs = {
                    u.partition_desc
                    for u in units
                    if len(u.data_files) >= self.min_file_num
                }
                for desc in descs:
                    from lakesoul_tpu.meta.client import partition_desc_to_dict

                    try:
                        total += table.compact(partition_desc_to_dict(desc) or None)
                    except CommitConflictError:
                        self.stats.bump("conflicts")
        return total


class _LeaseHeartbeat:
    """Keeps the store-side lease row alive while a long job runs.

    Renews at TTL/3 on a daemon thread; each successful renewal extends
    ``valid_until`` (monotonic clock).  Without this, any job longer than
    one TTL is guaranteed fenced at commit — the staged output dies, a
    peer re-runs the same doomed job, and the partition livelocks.  A
    failed renewal means a peer fenced past us: the job observes
    ``fenced`` and aborts instead of wasting the rest of the pass (the
    commit-time lease guard stays the correctness backstop)."""

    def __init__(self, store, key: str, holder: str, token: int, ttl_ms: int):
        self._store = store
        self._key = key
        self._holder = holder
        self._token = token
        self._ttl_ms = ttl_ms
        self._ttl_s = ttl_ms / 1000.0
        self._period_s = max(self._ttl_s / 3.0, 0.05)
        # published by the heartbeat thread, read by the job thread: every
        # post-init write holds _guard so the hand-off is a clean release/
        # acquire (racecheck-proven), not a torn unlocked publish
        self._guard = threading.Lock()
        self.valid_until = time.monotonic() + self._ttl_s
        self.fenced = False
        self._stop = threading.Event()
        self._thread = threading.Thread(  # lakelint: ignore[raw-thread] lease keepalive must tick while the job itself occupies pool workers
            target=self._run, name=f"lease-heartbeat-{key}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self._period_s):
            try:
                renewed = self._store.renew_lease(
                    self._key, self._holder, self._token, self._ttl_ms
                )
            except Exception:
                # transient store error: the old window still stands, but a
                # PERSISTENT failure quietly lapses into a fenced job — log
                # each miss so that path is diagnosable after the fact
                logger.warning(
                    "lease renewal for %s failed; local validity lapses in"
                    " %.1fs", self._key,
                    max(self.valid_until - time.monotonic(), 0.0),
                    exc_info=True,
                )
                continue
            if renewed is None:
                with self._guard:
                    self.fenced = True  # expired or fenced: never revive, re-acquire
                return
            with self._guard:
                self.valid_until = time.monotonic() + self._ttl_s


class LeasedCompactionService:
    """Standalone leased compaction service — one per *process*, any number
    of processes per warehouse.

    Discovery: a polling watermark consumer over committed-version gaps
    (:class:`~lakesoul_tpu.compaction.events.PollingWatermarkNotifier`);
    the watermark is the last CompactionCommit version in the store, so a
    killed service loses no events — any peer's next poll re-derives them.

    Coordination: one lease per (table, partition) in the metadata store's
    lease table.  ``acquire`` → work → fenced commit → ``release``.  The
    holder tracks its LOCAL validity with ``time.monotonic()`` (wall-clock
    jumps cannot extend or shrink it); the store compares expiry on its
    own shared timebase; and the **fencing token**, checked atomically
    inside the commit transaction, is what actually prevents a zombie's
    late commit — clocks only bound *liveness* (takeover within one TTL),
    never correctness.

    Obs: ``lakesoul_lease_state{key=}`` (1 while held here),
    ``lakesoul_compaction_takeovers_total``, plus the shared
    ``lakesoul_compaction_events_total{kind=}`` outcome counters.
    """

    LEASE_PREFIX = "compaction/"

    def __init__(
        self,
        catalog,
        *,
        service_id: str | None = None,
        lease_ttl_s: float | None = None,
        poll_interval_s: float | None = None,
        min_file_num: int = 2,
        version_gap: int | None = None,
    ):
        import os
        import uuid

        from lakesoul_tpu.compaction.events import PollingWatermarkNotifier
        from lakesoul_tpu.meta.store import COMPACTION_TRIGGER_VERSION_GAP

        self.catalog = catalog
        self.service_id = service_id or f"compactor-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.lease_ttl_s = (
            _env_float(ENV_LEASE_TTL_S, 30.0) if lease_ttl_s is None else float(lease_ttl_s)
        )
        self.poll_interval_s = (
            _env_float(ENV_POLL_S, 5.0) if poll_interval_s is None else float(poll_interval_s)
        )
        self.min_file_num = min_file_num
        self.version_gap = (
            COMPACTION_TRIGGER_VERSION_GAP if version_gap is None else version_gap
        )
        self.stats = CompactionStats()
        self.notifier = PollingWatermarkNotifier(
            catalog.client.store, version_gap=self.version_gap
        )
        self.notifier.listen(self._on_event)
        self._stop = threading.Event()
        self._poll_events: list[CompactionEvent] = []
        # (table_id, desc) → head version we already judged not-compactable
        # (version gap present but no bucket stacks min_file_num files, e.g.
        # after a DML rewrite).  Gap-derived discovery would re-emit such a
        # candidate on EVERY poll forever; suppressing it until its head
        # ADVANCES turns that into one lease+scan_plan per new commit
        # instead of one per poll interval.
        self._skipped_heads: dict[tuple[str, str], int] = {}

    # ----------------------------------------------------------------- events
    def _on_event(self, event: CompactionEvent) -> None:
        self.stats.bump("triggered")
        self._poll_events.append(event)

    def _lease_key(self, event: CompactionEvent) -> str:
        return f"{self.LEASE_PREFIX}{event.table_id}/{event.partition_desc}"

    def poll_once(self) -> dict:
        """One discovery + work cycle; returns outcome counts.  Candidates a
        live peer is already leasing are skipped (``lease_held``) and will
        be re-derived next poll if their gap survives the peer's job."""
        self._poll_events = []
        self.notifier.poll()
        # a skipped head stays a candidate while its gap is open; once it
        # compacts (or its table drops) it leaves the candidate set — prune
        # so a long-running service doesn't pin every head ever judged
        live = {(e.table_id, e.partition_desc) for e in self._poll_events}
        for k in [k for k in self._skipped_heads if k not in live]:
            del self._skipped_heads[k]
        counts = {
            "candidates": len(self._poll_events),
            "compacted": 0, "skipped": 0, "lease_held": 0,
            "fenced": 0, "conflicts": 0, "errors": 0,
        }
        for event in self._poll_events:
            if self._stop.is_set():
                break
            if self._skipped_heads.get(
                (event.table_id, event.partition_desc), -1
            ) >= event.version:
                counts["skipped"] += 1
                continue
            try:
                outcome = self._compact_candidate(event)
            except Exception:
                outcome = "errors"
                self.stats.bump("errors")
                logger.exception(
                    "leased compaction failed for %s/%s",
                    event.table_id, event.partition_desc,
                )
            key = (event.table_id, event.partition_desc)
            if outcome == "skipped":
                self._skipped_heads[key] = event.version
            elif outcome == "compacted":
                self._skipped_heads.pop(key, None)
            counts[outcome] = counts.get(outcome, 0) + 1
        return counts

    def _compact_candidate(self, event: CompactionEvent) -> str:
        from lakesoul_tpu.runtime import faults

        store = self.catalog.client.store
        key = self._lease_key(event)
        ttl_ms = int(self.lease_ttl_s * 1000)
        lease = store.acquire_lease(key, self.service_id, ttl_ms)
        if lease is None:
            self.stats.bump("lease_held")
            return "lease_held"
        # heartbeat renews the store row at TTL/3 and tracks local validity
        # on the monotonic clock (wall jumps cannot resurrect a lapsed
        # lease); jobs longer than one TTL stay held instead of fencing
        heartbeat = _LeaseHeartbeat(
            store, key, self.service_id, lease.fencing_token, ttl_ms
        )
        gauge = registry().gauge("lakesoul_lease_state", key=key)
        try:
            # everything after acquire runs under the finally that stops
            # the heartbeat and releases the lease — a raise anywhere here
            # must not leak a perpetually-renewed lease
            heartbeat.start()
            gauge.set(1)
            if lease.taken_over:
                self.stats.bump("takeovers")
                registry().counter("lakesoul_compaction_takeovers_total").inc()
                logger.info(
                    "%s took over lease %s (fencing token %d)",
                    self.service_id, key, lease.fencing_token,
                )
            # chaos point: a service hung (or killed) HERE still holds the
            # lease — the takeover tests SIGKILL inside this window
            faults.maybe_inject("compaction.leased_job")
            info = store.get_table_info_by_id(event.table_id)
            if info is None:
                self.stats.bump("skipped")
                return "skipped"
            table = self.catalog.table(info.table_name, info.table_namespace)

            def check_lease() -> None:
                if heartbeat.fenced or time.monotonic() >= heartbeat.valid_until:
                    # the heartbeat lost the lease (or stalled past the
                    # window): abort before more work — the commit guard
                    # would catch it anyway, but a whole compact pass
                    # would be wasted
                    raise LeaseFencedError(f"lease {key} lapsed locally")

            outcome = _run_conflict_retried_compaction(
                table, event, self.stats, self.min_file_num,
                lease=lease, pre_attempt=check_lease,
            )
            if outcome != "conflicts":
                self.stats.bump(outcome)
            return outcome
        except LeaseFencedError:
            self.stats.bump("fenced")
            if heartbeat.fenced:
                # the store rejected our renewal outright: token stale —
                # a peer fenced past us
                logger.warning(
                    "%s fenced on %s: a peer took over; abandoning the job",
                    self.service_id, key,
                )
            else:
                # local validity lapsed (renewals erroring — see the
                # heartbeat warnings) or the commit-time guard rejected
                # the token; don't blame a peer the logs can't prove
                logger.warning(
                    "%s abandoned %s: lease no longer provably held"
                    " (lapsed local validity or commit-guard rejection)",
                    self.service_id, key,
                )
            return "fenced"
        finally:
            heartbeat.stop()
            gauge.set(0)
            store.release_lease(key, self.service_id, lease.fencing_token)

    # ---------------------------------------------------------------- control
    def run_forever(self, *, max_polls: int | None = None) -> None:
        """Poll → work → sleep until :meth:`stop` (or ``max_polls``)."""
        polls = 0
        while not self._stop.is_set():
            counts = self.poll_once()
            if counts["candidates"]:
                logger.info("%s poll: %s", self.service_id, counts)
            polls += 1
            if max_polls is not None and polls >= max_polls:
                return
            self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        self.notifier.close()
