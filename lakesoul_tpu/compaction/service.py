"""Automatic compaction service.

Role parity with the reference's Spark compaction service
(lakesoul-spark/…/compaction/NewCompactionTask.scala:22-150): it LISTENs for
`lakesoul_compaction_notify` events that the PG trigger emits when a
partition's version gap since the last CompactionCommit reaches the threshold
(meta_init.sql:101-150), hashes the partition onto a worker pool, and runs
the compaction through the normal write path.

Here the metadata store fires the same event synchronously
(SqliteMetadataStore._fire_compaction_triggers); the service consumes them on
a bounded queue with N worker threads, deduplicates in-flight partitions, and
also supports size-tiered scheduled sweeps (the reference's "new compaction"
path with file-number/size limits)."""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field

from lakesoul_tpu.errors import CommitConflictError
from lakesoul_tpu.meta.store import CompactionEvent
from lakesoul_tpu.obs import registry, span

logger = logging.getLogger(__name__)


@dataclass
class CompactionStats:
    triggered: int = 0
    compacted: int = 0
    skipped: int = 0
    conflicts: int = 0
    errors: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def bump(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        # mirrored into the shared registry so one /metrics endpoint covers
        # the compaction service next to streams/cache/loader
        registry().counter("lakesoul_compaction_events_total", kind=name).inc()


class CompactionService:
    """Consume compaction events for one catalog and compact on worker threads.

    Usage::

        svc = CompactionService(catalog, workers=2)
        svc.start()           # subscribes to the store's trigger events
        ...                   # writes keep committing; gaps trigger events
        svc.drain(); svc.stop()
    """

    def __init__(
        self,
        catalog,
        *,
        workers: int = 2,
        min_file_num: int = 2,
        queue_size: int = 256,
    ):
        self.catalog = catalog
        self.workers = workers
        self.min_file_num = min_file_num
        self.stats = CompactionStats()
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._in_flight: set[tuple[str, str]] = set()
        self._in_flight_lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # --------------------------------------------------------------- control
    def start(self) -> None:
        self.catalog.client.store.add_compaction_listener(self._on_event)
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, name=f"compaction-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self.catalog.client.store.remove_compaction_listener(self._on_event)
        except ValueError:
            pass
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def drain(self, timeout: float = 30.0) -> None:
        """Block until the event queue is empty and workers are idle."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._in_flight_lock:
                busy = bool(self._in_flight)
            if self._queue.empty() and not busy:
                return
            time.sleep(0.02)

    # ---------------------------------------------------------------- events
    def _on_event(self, event: CompactionEvent) -> None:
        self.stats.bump("triggered")
        key = (event.table_id, event.partition_desc)
        with self._in_flight_lock:
            if key in self._in_flight:
                return  # already queued/running for this partition
            self._in_flight.add(key)
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            with self._in_flight_lock:
                self._in_flight.discard(key)
            logger.warning("compaction queue full; dropping event for %s", key)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            key = (event.table_id, event.partition_desc)
            try:
                self._compact_one(event)
            except Exception:
                self.stats.bump("errors")
                logger.exception("compaction failed for %s", key)
            finally:
                with self._in_flight_lock:
                    self._in_flight.discard(key)
                self._queue.task_done()

    def _compact_one(self, event: CompactionEvent) -> None:
        sp = span("compaction.job", partition=event.partition_desc)
        try:
            with sp:
                self._compact_one_inner(event)
        finally:
            # the span already timed the job (duration_s is set even when
            # the body raised) — feed the histogram from it
            registry().histogram("lakesoul_compaction_job_seconds").observe(
                sp.duration_s or 0.0
            )

    def _compact_one_inner(self, event: CompactionEvent) -> None:
        from lakesoul_tpu.meta.client import partition_desc_to_dict

        info = self.catalog.client.store.get_table_info_by_id(event.table_id)
        if info is None:
            self.stats.bump("skipped")
            return
        table = self.catalog.table(info.table_name, info.table_namespace)
        parts = partition_desc_to_dict(event.partition_desc) or None
        # writers may advance the partition mid-compact; each retry re-reads
        # the fresh head, like the reference re-running on the next notify
        for attempt in range(3):
            if not self._needs_compaction(table, event.partition_desc):
                self.stats.bump("skipped")
                return
            try:
                n = table.compact(parts)
                self.stats.bump("compacted" if n else "skipped")
                return
            except CommitConflictError:
                self.stats.bump("conflicts")
        logger.info("compaction kept losing races for %s; deferring", event.partition_desc)

    def _needs_compaction(self, table, partition_desc: str) -> bool:
        """Size-tiered gate: only compact when some bucket stacks at least
        min_file_num files (reference: file num/size limits in the
        new-compaction path)."""
        units = table.scan().scan_plan()
        for u in units:
            if u.partition_desc == partition_desc and len(u.data_files) >= self.min_file_num:
                return True
        return False

    # ------------------------------------------------------------- full sweep
    def sweep(self) -> int:
        """Compact every table/partition that crosses the file threshold —
        the scheduled fallback when no trigger fired (e.g. after restarts)."""
        total = 0
        for ns in self.catalog.list_namespaces():
            for name in self.catalog.list_tables(ns):
                table = self.catalog.table(name, ns)
                units = table.scan().scan_plan()
                descs = {
                    u.partition_desc
                    for u in units
                    if len(u.data_files) >= self.min_file_num
                }
                for desc in descs:
                    from lakesoul_tpu.meta.client import partition_desc_to_dict

                    try:
                        total += table.compact(partition_desc_to_dict(desc) or None)
                    except CommitConflictError:
                        self.stats.bump("conflicts")
        return total
