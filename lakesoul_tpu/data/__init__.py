from lakesoul_tpu.data.jax_iter import JaxBatchIterator

__all__ = ["JaxBatchIterator"]
