"""The batch-source seam: ONE place adapters get their Arrow batches.

Every delivery adapter (``to_jax_iter``, torch, ray, huggingface) used to
call ``scan.to_batches()`` directly, which hard-wired them to in-process
decode.  The seam splits "which batches" (the scan) from "who produces
them" (this process, or a scan-plane fleet): a scan carries an optional
source FACTORY (set by :meth:`LakeSoulScan.via_scanplane`), and
:func:`batch_source_for` resolves it to an object with one method —

    ``iter_batches(*, num_threads=None, skip_rows=0) -> Iterator[RecordBatch]``

with ``to_batches``-identical semantics (limit applied, deterministic
order, generators close cleanly on abandonment).  Local scans resolve to
:class:`ScanBatchSource` (a thin ``to_batches`` wrapper); remote scans to
:class:`lakesoul_tpu.scanplane.client.RemoteBatchSource`.  Adapters that
consume the seam get remote scan FOR FREE — the parity tests pin that the
two sources are byte-identical.
"""

from __future__ import annotations


class ScanBatchSource:
    """In-process batch source: the scan's own ``to_batches``."""

    remote = False

    def __init__(self, scan):
        self._scan = scan

    def iter_batches(self, *, num_threads=None, skip_rows: int = 0):
        return self._scan.to_batches(num_threads=num_threads, skip_rows=skip_rows)


def batch_source_for(scan):
    """Resolve a scan to its batch source (remote factory wins)."""
    factory = getattr(scan, "_batch_source_factory", None)
    if factory is not None:
        return factory(scan)
    return ScanBatchSource(scan)
