"""The batch-source seam: ONE place adapters get their Arrow batches.

Every delivery adapter (``to_jax_iter``, torch, ray, huggingface) used to
call ``scan.to_batches()`` directly, which hard-wired them to in-process
decode.  The seam splits "which batches" (the scan) from "who produces
them" (this process, or a scan-plane fleet): a scan carries an optional
source FACTORY (set by :meth:`LakeSoulScan.via_scanplane`), and
:func:`batch_source_for` resolves it to an object with one method —

    ``iter_batches(*, num_threads=None, skip_rows=0) -> Iterator[RecordBatch]``

with ``to_batches``-identical semantics (limit applied, deterministic
order, generators close cleanly on abandonment).  Local scans resolve to
:class:`ScanBatchSource` (a thin ``to_batches`` wrapper); remote scans to
:class:`lakesoul_tpu.scanplane.client.RemoteBatchSource`; continuous
scans (``to_jax_iter(follow=...)``) to
:class:`lakesoul_tpu.freshness.follower.FollowBatchSource` — an unbounded
retry-hardened stream over the table's commit log with an exactly-once
resumable position.  Adapters that consume the seam get remote AND
follow delivery FOR FREE — the parity tests pin that the sources are
byte-identical where they overlap.
"""

from __future__ import annotations


class ScanBatchSource:
    """In-process batch source: the scan's own ``to_batches``."""

    remote = False

    def __init__(self, scan):
        self._scan = scan

    def iter_batches(self, *, num_threads=None, skip_rows: int = 0):
        return self._scan.to_batches(num_threads=num_threads, skip_rows=skip_rows)


def batch_source_for(scan, follow=None):
    """Resolve a scan to its batch source.

    ``follow`` turns the scan into a CONTINUOUS source: ``True`` follows
    from now, a dict passes :class:`~lakesoul_tpu.freshness.follower.
    FreshFollower` options (``start_timestamp_ms``, ``state``,
    ``poll_interval``, ``stop_event``, ``slo``, ``retry_policy``), a
    persisted position (``FollowerState`` or its JSON) resumes from it,
    an existing :class:`~lakesoul_tpu.freshness.follower.
    FollowBatchSource` is used as-is.  Any other value raises — a typo'd
    ``follow=`` must never silently become follow-from-now, discarding
    the caller's resume position.  Otherwise the remote factory
    (``via_scanplane``) wins, then in-process decode."""
    if follow is not None and follow is not False:
        from lakesoul_tpu.errors import ConfigError
        from lakesoul_tpu.freshness.follower import (
            FollowBatchSource,
            FollowerState,
        )

        if isinstance(follow, FollowBatchSource):
            return follow
        if follow is True:
            opts = {}
        elif isinstance(follow, dict):
            opts = follow
        elif isinstance(follow, (str, FollowerState)):
            opts = {"state": follow}
        else:
            raise ConfigError(
                f"follow must be True, an options dict, a FollowerState"
                f" (or its JSON), or a FollowBatchSource — got"
                f" {type(follow).__name__}"
            )
        return FollowBatchSource(scan, **opts)
    factory = getattr(scan, "_batch_source_factory", None)
    if factory is not None:
        return factory(scan)
    return ScanBatchSource(scan)
