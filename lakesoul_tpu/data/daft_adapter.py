"""Daft adapter (parity with python/src/lakesoul/daft/__init__.py:31,44)."""

from __future__ import annotations


def read_lakesoul(scan):
    """LakeSoulScan → daft.DataFrame."""
    try:
        import daft
    except ImportError as e:  # pragma: no cover - daft not in the TPU image
        raise ImportError("daft is required for read_lakesoul") from e
    return daft.from_arrow(scan.to_arrow())


def write_lakesoul(df, table) -> None:
    """daft.DataFrame → table (single ACID commit)."""
    try:
        import daft  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError("daft is required for write_lakesoul") from e
    table.write_arrow(df.to_arrow())
