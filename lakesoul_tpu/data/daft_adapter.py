"""Daft adapter (parity with python/src/lakesoul/daft/__init__.py:31,44).

Partition-wise on both sides (VERDICT r3 item 6):

- ``read_lakesoul`` hands daft a LAZY iterator of per-scan-unit Arrow
  tables (the reference's `_iter_lakesoul_tables` shape): each
  (range-partition, hash-bucket) unit decodes and MOR-merges independently,
  so daft starts consuming before the scan finishes and nothing requires
  the whole table in memory at once.
- ``write_lakesoul`` streams ``DataFrame.to_arrow_iter()`` partitions
  through the TableWriter (range+hash split per batch, bounded buffering,
  abort-on-error) and the driver commits every staged file in ONE ACID
  commit — the reference's writer-stream + `_commit_write_result` shape.

daft is not in the TPU image; tests/test_adapters.py pins the daft API
surface used here (``from_arrow`` accepting a table OR an iterable of
tables, ``to_arrow_iter`` yielding tables/batches, ``to_arrow`` fallback)
with a wire-faithful stub.
"""

from __future__ import annotations


def read_lakesoul(scan):
    """LakeSoulScan → daft.DataFrame (lazy, one Arrow table per scan unit)."""
    try:
        import daft
    except ImportError as e:  # pragma: no cover - daft not in the TPU image
        raise ImportError("daft is required for read_lakesoul") from e

    units = [
        (u.data_files, u.primary_keys, scan._unit_kwargs(u))
        for u in scan.scan_plan()
    ]
    if not units:
        return daft.from_arrow(scan.to_arrow())  # empty: table carries schema

    def unit_tables():
        from lakesoul_tpu.io.reader import read_scan_unit

        for files, pks, kwargs in units:
            yield read_scan_unit(files, pks, **kwargs)

    return daft.from_arrow(unit_tables())


def write_lakesoul(df, table):
    """daft.DataFrame → table: stream partitions through the writer, commit
    once.  Returns the committed DataFileOps."""
    try:
        import daft  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError("daft is required for write_lakesoul") from e

    to_arrow_iter = getattr(df, "to_arrow_iter", None)
    if to_arrow_iter is not None:
        return table.write_arrow(iter(to_arrow_iter()))
    return table.write_arrow(df.to_arrow())
