"""HuggingFace datasets adapter (parity with
python/src/lakesoul/huggingface/from_lakesoul.py:17-39)."""

from __future__ import annotations


def _generate_rows(units: list[dict]):
    """Module-level generator so `datasets` can pickle/fingerprint it; the
    scan plan is passed as plain picklable kwargs, not live catalog objects."""
    from lakesoul_tpu.io.reader import read_scan_unit

    for u in units:
        # no mutation: datasets re-invokes the generator every epoch with the
        # same gen_kwargs dicts
        kwargs = {k: v for k, v in u.items() if k not in ("data_files", "primary_keys")}
        table = read_scan_unit(u["data_files"], u["primary_keys"], **kwargs)
        yield from table.to_pylist()


def to_hf_dataset(scan, streaming: bool = True):
    """Expose a LakeSoulScan as a datasets.IterableDataset (streaming) or an
    in-memory datasets.Dataset."""
    try:
        import datasets
    except ImportError as e:  # pragma: no cover
        raise ImportError("the 'datasets' package is required for to_huggingface()") from e

    if streaming:
        units = [
            {"data_files": u.data_files, "primary_keys": u.primary_keys, **scan._unit_kwargs(u)}
            for u in scan.scan_plan()
        ]
        return datasets.IterableDataset.from_generator(
            _generate_rows, gen_kwargs={"units": units}
        )
    return datasets.Dataset.from_list(scan.to_arrow().to_pylist())
