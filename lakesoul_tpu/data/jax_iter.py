"""JAX delivery: stream a table scan into TPU HBM.

This is the north-star path (BASELINE.json): merged RecordBatches from the
host data plane are re-batched to a fixed size (jit needs static shapes),
converted zero-copy to numpy, and moved to device with **double-buffered
``jax.device_put``** so host decode/merge overlaps the device step — the
role CUDA pinned-memory staging plays for the reference's GPU loaders.

Pipeline:  scan units → [runtime pipeline: read + merge → collate →
           prefetch(bounded queue)] → [foreground: device_put k batches
           ahead] → training loop

The host side runs on the shared execution runtime
(:mod:`lakesoul_tpu.runtime`): a ``collate`` map stage feeding a bounded
``prefetch`` pump replaces the hand-rolled producer thread, so the loader
inherits the pipeline contract — backpressure, cooperative cancellation
(an abandoned training loop stops the decode promptly), propagated
exceptions with the scan's trace id, deadlines, and
``LAKESOUL_FAULTS`` fault injection — and its queue depth / stage
latencies land in the ``lakesoul_runtime_*`` obs series.

Sharding: ``LakeSoulScan.shard()/auto_shard()`` splits scan units across
processes (data parallelism over the pod); within a process, batches can be
placed on a ``jax.sharding.Sharding`` (e.g. batch-sharded over a local mesh)
so a ``pjit`` step consumes them without resharding.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np
import pyarrow as pa

from lakesoul_tpu.obs import registry
from lakesoul_tpu.obs.stages import stage_histogram
from lakesoul_tpu.runtime import pipeline as rt_pipeline
from lakesoul_tpu.tensorplane.dlpack import aligned_empty, delivery_copies


class LoaderStats:
    """Thread-safe loader-throughput telemetry (the Deep Lake fetch/decode/
    collate visibility role): rows/sec, batches/sec, producer-queue depth,
    consumer stall time, per-epoch totals.

    ``snapshot()`` is what training loops read between steps; the same
    counters feed the process registry (``lakesoul_loader_*``), so a
    gateway's ``/metrics`` shows loader throughput next to everything else.
    Elapsed time counts only time spent inside epochs — an iterator parked
    between epochs does not dilute rows/sec."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rows = 0
        self.batches = 0
        self.epochs = 0
        self.stall_s = 0.0
        self.queue_depth = 0
        self.epoch_rows: list[int] = []
        self._active_s = 0.0
        self._epoch_start: float | None = None
        self._cur_epoch_rows = 0
        self._reported_depth = 0  # this loader's share of the depth gauge
        # hot path: fetch each registry metric ONCE (the obs contract), not
        # per delivered batch — delivery then pays only the metric's own lock
        reg = registry()
        self._m_rows = reg.counter("lakesoul_loader_rows_total")
        self._m_batches = reg.counter("lakesoul_loader_batches_total")
        self._m_stall = reg.counter("lakesoul_loader_stall_seconds_total")
        self._m_epochs = reg.counter("lakesoul_loader_epochs_total")
        self._m_depth = reg.gauge("lakesoul_loader_queue_depth")

    def epoch_begin(self) -> None:
        with self._lock:
            self._epoch_start = time.perf_counter()
            self._cur_epoch_rows = 0

    def epoch_end(self, completed: bool) -> None:
        with self._lock:
            if self._epoch_start is not None:
                self._active_s += time.perf_counter() - self._epoch_start
                self._epoch_start = None
            if completed:
                self.epochs += 1
                self.epoch_rows.append(self._cur_epoch_rows)
                del self.epoch_rows[:-64]  # bound the history
            # settle this loader's contribution to the shared depth gauge:
            # a parked/finished loader must not pin a stale depth
            settle = self._reported_depth
            self._reported_depth = 0
        if settle:
            self._m_depth.dec(settle)
        if completed:
            self._m_epochs.inc()

    def delivered(self, rows: int, stall_s: float, queue_depth: int) -> None:
        with self._lock:
            self.rows += rows
            self.batches += 1
            self._cur_epoch_rows += rows
            self.stall_s += stall_s
            self.queue_depth = queue_depth
            # DELTA update on the shared gauge: concurrent loaders (train +
            # eval) then aggregate on /metrics instead of clobbering each
            # other's last write
            delta = queue_depth - self._reported_depth
            self._reported_depth = queue_depth
        self._m_rows.inc(rows)
        self._m_batches.inc()
        self._m_stall.inc(stall_s)
        if delta:
            self._m_depth.inc(delta)

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = self._active_s
            if self._epoch_start is not None:
                elapsed += time.perf_counter() - self._epoch_start
            return {
                "rows": self.rows,
                "batches": self.batches,
                "epochs": self.epochs,
                "epoch_rows": list(self.epoch_rows),
                "elapsed_s": elapsed,
                "rows_per_sec": (self.rows / elapsed) if elapsed > 0 else 0.0,
                "batches_per_sec": (self.batches / elapsed) if elapsed > 0 else 0.0,
                "stall_s": self.stall_s,
                "queue_depth": self.queue_depth,
            }


class LoaderCheckpoint:
    """Mid-epoch input-stream position (tf.data-checkpoint role).

    The trainer persists this NEXT TO its model checkpoint: after resuming,
    a loader built with the restored object continues exactly after the
    last delivered batch — no replayed or skipped rows.  Position is the
    delivered-row count over the scan's deterministic unit order, guarded by
    a digest of the table version (a commit in between makes the position
    meaningless, so resume refuses it).

    ::

        ckpt = LoaderCheckpoint()
        for batch in scan.to_jax_iter(checkpoint=ckpt):
            step(batch)
            save(model_state, ckpt.to_json())   # atomically, per N steps
        # after a crash:
        ckpt = LoaderCheckpoint.from_json(saved)
        for batch in scan.to_jax_iter(checkpoint=ckpt):  # resumes mid-epoch
            ...
    """

    def __init__(self, rows_delivered: int = 0, plan_digest: str | None = None):
        self.rows_delivered = rows_delivered
        self.plan_digest = plan_digest

    def to_json(self) -> str:
        import json

        return json.dumps(
            {"rows_delivered": self.rows_delivered, "plan_digest": self.plan_digest}
        )

    @classmethod
    def from_json(cls, s: str) -> "LoaderCheckpoint":
        import json

        d = json.loads(s)
        return cls(d["rows_delivered"], d.get("plan_digest"))


def _is_stringlike(t: pa.DataType) -> bool:
    """String/binary columns (incl. dictionary-encoded ones, which Parquet
    readers commonly produce) keep the documented stay-as-object contract."""
    if pa.types.is_dictionary(t):
        return _is_stringlike(t.value_type)
    return (
        pa.types.is_string(t)
        or pa.types.is_large_string(t)
        or pa.types.is_binary(t)
        or pa.types.is_large_binary(t)
    )


def _default_collate(
    batch: pa.RecordBatch | pa.Table,
    tensor_shapes: "dict[str, tuple[int, ...]] | None" = None,
) -> dict[str, np.ndarray]:
    """Arrow → dict of numpy arrays (zero-copy where possible).  Fixed-width
    columns map directly; ``fixed_size_list`` tensor columns (token rows,
    image pixels) collate to real fixed-width arrays — 2-D by default, or
    the full declared logical shape when the loader resolved one from the
    table's tensor declarations (``tensor_shapes``, computed ONCE per
    loader from the projected schema instead of re-probing Arrow types per
    batch); strings stay as object arrays (caller should tokenize/encode
    upstream for TPU consumption).  Anything that only lowers to
    dtype=object (variable lists, structs, maps) fails LOUDLY: the old
    object-array fallback survived until ``jax.device_put`` rejected the
    batch deep inside the pipeline, with no hint of which column was
    responsible."""
    from lakesoul_tpu.errors import ConfigError

    out: dict[str, np.ndarray] = {}
    table = pa.table(batch) if isinstance(batch, pa.RecordBatch) else batch
    for name in table.column_names:
        col = table.column(name)
        if pa.types.is_fixed_size_list(col.type):
            arr = col.combine_chunks()  # lakelint: ignore[hot-path-materialize] fallback for windows the zero-copy view path declined (nulls/odd layouts); the fused path never reaches here
            width = col.type.list_size
            flat = arr.flatten().to_numpy(zero_copy_only=False)
            if flat.dtype != object and len(flat) == len(arr) * width:
                shape = (tensor_shapes or {}).get(name) or (width,)
                out[name] = flat.reshape((len(arr),) + tuple(shape))
                continue
        try:
            arr = col.to_numpy(zero_copy_only=False)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
            raise ConfigError(
                f"column {name!r} has Arrow type {col.type} which only "
                "collates to dtype=object — object arrays cannot be "
                "device_put; flatten/encode the column upstream or pass a "
                "collate_fn that handles it"
            ) from e
        if arr.dtype == object and not _is_stringlike(col.type):
            raise ConfigError(
                f"column {name!r} has Arrow type {col.type} which only "
                "collates to dtype=object — object arrays cannot be "
                "device_put; flatten/encode the column upstream or pass a "
                "collate_fn that handles it"
            )
        out[name] = arr
    return out


def _np_column_views(
    batch: pa.RecordBatch,
    tensor_shapes: "dict[str, tuple[int, ...]] | None" = None,
) -> dict[str, np.ndarray] | None:
    """Zero-copy per-column numpy views of one record batch, or None when any
    column cannot be viewed without conversion (nulls, strings/objects,
    bit-packed bools, variable nesting) — the window then falls back to the
    arrow-table collate path, which handles those exactly as before.
    Declared tensor columns view straight to their logical shape
    (``(rows, *shape)``): the declaration was resolved once per loader, so
    the hot path never re-discovers ``fixed_size_list`` per batch."""
    views: dict[str, np.ndarray] = {}
    for i, name in enumerate(batch.schema.names):
        col = batch.column(i)
        t = col.type
        try:
            if pa.types.is_fixed_size_list(t):
                if col.null_count:
                    return None
                flat = col.flatten().to_numpy(zero_copy_only=True)
                shape = (tensor_shapes or {}).get(name) or (t.list_size,)
                views[name] = flat.reshape((len(col),) + tuple(shape))
            else:
                if col.null_count:
                    return None
                views[name] = col.to_numpy(zero_copy_only=True)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, ValueError):
            return None
    return views


class _Window:
    """One fixed-size row window over the pending batches, materialization
    deferred: the window holds zero-copy (batch, views, start, length) parts
    and either collates STRAIGHT from the numpy views into one output buffer
    per column (fast path — no intermediate table ever exists) or assembles
    a table from batch slices for the fallback/custom-collate path."""

    __slots__ = ("parts", "nrows", "fast", "tensor_shapes")

    def __init__(self, parts, nrows: int, tensor_shapes=None):
        self.parts = parts  # [(record_batch, views_or_None, start, length)]
        self.nrows = nrows
        self.fast = all(v is not None for _, v, _, _ in parts)
        self.tensor_shapes = tensor_shapes  # declared shapes for fallbacks

    def __len__(self) -> int:
        return self.nrows

    def to_table(self) -> pa.Table:
        # zero-copy: slices share the source batch buffers; the table's
        # chunked columns are exactly what the old concat-based rebatcher
        # handed to collate
        return pa.Table.from_batches(
            [b.slice(s, ln) for b, _, s, ln in self.parts]
        )

    def collate(self, buffers: "dict[str, np.ndarray] | None") -> dict[str, np.ndarray]:
        """Fused rebatch+collate: one ``out[pos:pos+len] = view[s:s+len]``
        memcpy per (column, part) into per-column output buffers —
        ``buffers`` (a reuse-ring slot) or freshly allocated once.  A window
        that is a single slice of one batch (the common case: the scan
        already emits ``batch_size``-row batches, so windows align) doesn't
        even copy — the numpy views pass straight through, sliced."""
        if buffers is None and len(self.parts) == 1:
            b, views, s, ln = self.parts[0]
            if s == 0 and ln == len(b):
                return dict(views)
            return {name: v[s : s + ln] for name, v in views.items()}
        first_views = self.parts[0][1]
        out: dict[str, np.ndarray] = {}
        for name, proto in first_views.items():
            shape = (self.nrows,) + proto.shape[1:]
            buf = None if buffers is None else buffers.get(name)
            if buf is None or buf.shape != shape or buf.dtype != proto.dtype:
                # 64-byte-aligned output buffers (tensorplane.dlpack): the
                # XLA CPU client only zero-copies aligned host buffers, so
                # alignment is what makes the DLPack/device_put hand-off
                # provably copy-free instead of malloc-luck-dependent
                buf = aligned_empty(shape, proto.dtype)
                if buffers is not None:
                    buffers[name] = buf
            pos = 0
            for _, views, s, ln in self.parts:
                v = views[name]
                if v.dtype != proto.dtype:
                    # batches disagree on dtype (schema drift): numpy would
                    # cast silently — take the exact table path instead
                    return _default_collate(self.to_table(), self.tensor_shapes)
                buf[pos : pos + ln] = v[s : s + ln]
                pos += ln
            out[name] = buf
        return out


def _schema_np_dtypes(scan) -> "list[np.dtype] | None":
    """The numpy dtypes the zero-copy collate fast path can emit for this
    scan (fixed-width columns; tensor columns contribute their element
    dtype) — the inputs of the ``delivery_copies`` aliasing probe that
    decides whether the reuse ring may arm.  None when the schema cannot
    be resolved: the probe then reports "assume aliasing" and the ring
    stays down."""
    try:
        schema = scan.projected_schema()
    except Exception:
        return None
    out: list[np.dtype] = []
    for field in schema:
        t = field.type
        if pa.types.is_fixed_size_list(t):
            t = t.value_type
        try:
            dt = np.dtype(t.to_pandas_dtype())
        except Exception:
            continue
        if dt != object:
            out.append(dt)
    return out or None


class _BufferRing:
    """Round-robin pool of collate output buffer sets (opt-in via
    ``LAKESOUL_COLLATE_REUSE=1``): with ``size`` ≥ the number of windows that
    can be live at once (prefetch queue + device-put pipeline + in-flight),
    steady-state collate allocates NOTHING — each window overwrites the
    buffers of a window the consumer has already retired.  Only safe when
    the consumer copies batches out (e.g. ``device_put`` to a non-host
    backend) before ``size`` further batches are drawn; the default path
    allocates fresh buffers per window.  That contract is machine-checked:
    ``LAKESOUL_RACECHECK=1`` (analysis/racecheck.py) wraps ``next_slot``
    with a canary that flags any slot handed out while a borrower still
    references its buffers, then poisons the dead bytes so a stale read
    is loud garbage instead of plausible training data."""

    def __init__(self, size: int):
        self._slots: list[dict[str, np.ndarray]] = [{} for _ in range(max(1, size))]
        self._next = 0

    def next_slot(self) -> dict[str, np.ndarray]:
        slot = self._slots[self._next]
        self._next = (self._next + 1) % len(self._slots)
        return slot


class _Rebatcher:
    """Accumulate arrow batches and emit fixed-size row windows — chunk-aware:
    pending batches are never concatenated (the old ``pa.concat_tables`` per
    pop rebuilt a table of everything buffered, per window); a window is a
    list of zero-copy slice descriptors resolved at collate time."""

    def __init__(self, batch_size: int, *, capture_views: bool = True,
                 tensor_shapes: "dict[str, tuple[int, ...]] | None" = None):
        self.batch_size = batch_size
        # a custom collate_fn consumes tables, never views — skip the
        # per-batch view capture entirely on that path
        self._capture_views = capture_views
        self._tensor_shapes = tensor_shapes
        self._pending: list[tuple[pa.RecordBatch, dict | None]] = []
        self._offset = 0  # consumed rows of the FIRST pending batch
        self._rows = 0

    def push(self, batch: pa.RecordBatch | pa.Table) -> "list[_Window]":
        if isinstance(batch, pa.Table):
            incoming = batch.to_batches()
        else:
            incoming = [batch]
        for b in incoming:
            if len(b) == 0:
                continue
            views = (
                _np_column_views(b, self._tensor_shapes)
                if self._capture_views else None
            )
            self._pending.append((b, views))
            self._rows += len(b)
        out = []
        while self._rows >= self.batch_size:
            out.append(self._pop(self.batch_size))
        return out

    def _pop(self, n: int) -> _Window:
        parts = []
        need = n
        while need:
            b, views = self._pending[0]
            avail = len(b) - self._offset
            take = min(avail, need)
            parts.append((b, views, self._offset, take))
            need -= take
            if take == avail:
                self._pending.pop(0)
                self._offset = 0
            else:
                self._offset += take
        self._rows -= n
        return _Window(parts, n, self._tensor_shapes)

    def tail(self) -> _Window | None:
        if self._rows == 0:
            return None
        out = self._pop(self._rows)
        return out


class JaxBatchIterator:
    """Iterator of device-resident, fixed-size batches.

    Args:
        scan: a LakeSoulScan (its batch_size sets the emitted batch size).
        collate_fn: arrow table → pytree of numpy arrays.  Default: dict of
            per-column arrays.
        transform: optional numpy-level pytree transform (e.g. tokenize,
            reshape features) applied on the host thread.
        device_put: move batches to device (default True; False yields host
            numpy pytrees — useful for tests and CPU pipelines).
        sharding: optional jax.sharding.Sharding for the device placement
            (e.g. NamedSharding(mesh, P("dp")) to batch-shard locally).
        prefetch: queue depth for the host pipeline (decode ahead).
        device_prefetch: how many batches to keep resident on device ahead of
            the consumer (double buffering = 2).
        drop_remainder: drop the final short batch (jit-friendly default True).
        io_threads: decode scan units on this many threads (multi-core hosts;
            see LakeSoulScan.to_batches).
        follow: make the loader a CONTINUOUS training source over the
            table's commit log (the freshness layer): ``True`` follows
            from now, a dict passes follower options
            (``start_timestamp_ms``, ``state``, ``poll_interval``,
            ``stop_event``, ``slo``, ``retry_policy`` — see
            :class:`lakesoul_tpu.freshness.follower.FreshFollower`), or an
            existing ``FollowBatchSource``.  The stream never ends on its
            own — set a ``stop_event`` to shut it down within one poll
            tick.  Resume via :meth:`follow_state_json`, NOT via
            ``checkpoint`` (the follower carries its own exactly-once
            position; mixing the two raises).  Note the pipeline-lag
            semantics under ``device_put=True``: the double buffer keeps
            ``device_prefetch`` transfers in flight and the rebatcher
            holds sub-``batch_size`` remainders, so when ingest PAUSES
            the consumer trails the stream head by up to
            ``device_prefetch`` windows + one partial window until more
            commits arrive (continuous traffic — the follow workload —
            keeps the lag bounded and flowing; latency-critical
            low-traffic consumers should use ``device_put=False``, where
            delivery is immediate).  The freshness SLO measures at the
            source hand-off either way.
        consumer: attribution tag for this loader's ``queue`` stall series
            (``lakesoul_scan_stage_seconds{stage=queue,consumer=...}``) —
            with several concurrent loaders (a trainer fleet on one host)
            the tag says WHICH client starved.  Default ``"local"``.
        cache: ``"device"`` pins delivered batches in device memory on the
            first complete epoch via the tensor plane's
            :class:`~lakesoul_tpu.tensorplane.replay.DeviceReplayCache`;
            re-iterating then replays the resident shards with ZERO
            storage/host/link traffic (the tf.data ``.cache()`` role,
            placed in HBM where re-reads are free).  Residency is
            budgeted per device (``replay_budget_bytes`` /
            ``LAKESOUL_REPLAY_BUDGET_BYTES``; unset = unbounded, the
            caller opted in knowing rows × bytes/row): past the budget
            the cache records a typed, metered spill and later epochs
            replay the resident prefix from HBM then re-stream only the
            tail.  An epoch abandoned early leaves the cache unfilled
            (partial replay would silently drop data).
        replay_budget_bytes: per-device HBM budget for ``cache='device'``
            (overrides ``LAKESOUL_REPLAY_BUDGET_BYTES``).
        replay_permute: re-permute the resident epoch on device each
            replay (seeded; batch order + on-device row permutation) —
            only honoured while fully resident, a spilled cache replays
            in stream order so the hybrid epoch stays position-exact.
        replay_seed: seed pinning the permutation schedule.
        multihost: shard the scan by this process's position on the data
            axis (``jax.process_index()/process_count()``, overridable via
            ``LAKESOUL_FLEET_PROCESS_INDEX``/``_COUNT`` for emulated
            multi-host) before the pipeline resolves it — N hosts then
            consume disjoint, union-complete shards, and ``cache='device'``
            pins exactly the local shard.  A scan already ``shard()``-ed
            the same way passes through; a conflicting shard raises.
    """

    def __init__(
        self,
        scan,
        *,
        collate_fn: Callable[[pa.Table], Any] | None = None,
        transform: Callable[[Any], Any] | None = None,
        device_put: bool = True,
        sharding=None,
        prefetch: int = 4,
        device_prefetch: int = 2,
        drop_remainder: bool = True,
        io_threads: int | None = None,
        checkpoint: "LoaderCheckpoint | None" = None,
        cache: str | None = None,
        replay_budget_bytes: int | None = None,
        replay_permute: bool = False,
        replay_seed: int = 0,
        consumer: str | None = None,
        follow=None,
        multihost: bool = False,
    ):
        from lakesoul_tpu.errors import ConfigError

        if multihost:
            # shard BEFORE anything else resolves the scan: the batch
            # source, plan digest, replay cache and checkpoint must all
            # see the local host's shard, never the global table.  The
            # process axis comes from jax.process_index()/process_count()
            # (LAKESOUL_FLEET_PROCESS_INDEX/COUNT override for emulated
            # multi-host); a consistently pre-sharded scan passes through,
            # a conflicting one raises (fleet/multihost.py).
            from lakesoul_tpu.fleet.multihost import shard_scan

            scan = shard_scan(scan)

        if cache not in (None, "device"):
            raise ConfigError(f"unknown cache mode {cache!r}; expected 'device'")
        if cache != "device" and (
            replay_budget_bytes is not None or replay_permute or replay_seed
        ):
            # same contract as the other invalid combos in this
            # constructor: a replay knob without the replay cache must not
            # silently train un-permuted / un-budgeted
            raise ConfigError(
                "replay_budget_bytes/replay_permute/replay_seed require"
                " cache='device'"
            )
        if follow is not None and follow is not False:
            if checkpoint is not None:
                raise ConfigError(
                    "follow and checkpoint are mutually exclusive: the"
                    " follower carries its own exactly-once position"
                    " (follow_state_json)"
                )
            if cache == "device":
                raise ConfigError(
                    "cache='device' cannot cache an unbounded follow stream"
                )
        if cache == "device" and checkpoint is not None:
            # a replayed epoch never touches the input stream, so a loader
            # checkpoint could not represent its position
            raise ConfigError("cache='device' and checkpoint are mutually exclusive")
        if cache == "device" and not device_put:
            raise ConfigError("cache='device' requires device_put=True")
        self._cache_mode = cache
        self._replay = None
        # exactly ONE active generator may fill the shared cache: two
        # interleaved iterations of the same loader would both offer into
        # it, sealing a doubled epoch (every replay batch served twice) or
        # tripping offer()-after-seal mid-stream — the first streaming
        # generator claims the fill, later concurrent ones stream plain
        self._fill_claimed = False
        if cache == "device":
            from lakesoul_tpu.tensorplane.replay import DeviceReplayCache

            self._replay = DeviceReplayCache(
                budget_bytes=replay_budget_bytes,
                permute=replay_permute,
                seed=replay_seed,
            )
        self._stats = LoaderStats()
        self._scan = scan
        self._collate = collate_fn or _default_collate
        # declared tensor shapes, resolved ONCE from the projected schema
        # (tensorplane/columns.py): the collate layer reshapes straight to
        # (batch, *shape) instead of re-probing Arrow types per batch
        try:
            from lakesoul_tpu.tensorplane.columns import tensor_specs

            self._tensor_shapes = {
                name: spec.shape
                for name, spec in tensor_specs(scan.projected_schema()).items()
            } or None
        except Exception:  # scans without resolvable schemas keep the
            self._tensor_shapes = None  # per-type collate contract
        # opt-in collate-buffer reuse ring (see _BufferRing contract); sized
        # to cover every window that can be live at once.  The disarm
        # condition keys on MEASURED aliasing (tensorplane/dlpack.py), not a
        # platform guess: PR 9's ring canary caught host-backed device_put
        # aliasing dtype-matching columns (float32 stays down on CPU), but a
        # loader whose every column demotes (int64/float64 under disabled
        # x64) pays a REAL copy per put — there the ring re-arms, on any
        # backend, and under cache='device' too (a pinned batch that owns
        # its bytes cannot be overwritten by slot reuse).  Host-consumer
        # loaders (device_put=False) keep the old contract: the consumer
        # copies batches out before the ring wraps, and cache='device'
        # requires device_put anyway.
        self._ring: _BufferRing | None = None
        if (
            collate_fn is None
            and os.environ.get("LAKESOUL_COLLATE_REUSE") == "1"
            and (
                (not device_put and cache != "device")
                or (device_put and delivery_copies(_schema_np_dtypes(scan),
                                                   sharding))
            )
        ):
            self._ring = _BufferRing(
                max(1, prefetch) + max(1, device_prefetch) + 2
            )
        # stage-attribution handles, fetched once (the obs hot-path
        # contract); the queue series carries this loader's consumer tag so
        # multi-client stall is attributable per client
        self._h_rebatch = stage_histogram("rebatch")
        self._h_collate = stage_histogram("collate")
        self._h_queue = stage_histogram("queue", consumer=consumer or "local")
        self._h_device_put = stage_histogram("device_put")
        self._transform = transform
        self._device_put = device_put
        self._sharding = sharding
        self._prefetch = max(1, prefetch)
        self._device_prefetch = max(1, device_prefetch)
        self._drop_remainder = drop_remainder
        self._io_threads = io_threads
        self._checkpoint = checkpoint
        # follow mode: ONE seam source for the iterator's lifetime — its
        # follower owns the exactly-once position follow_state_json() reads
        self._follow_source = None
        self._follow_started = False
        if follow is not None and follow is not False:
            from lakesoul_tpu.data.batch_source import batch_source_for

            self._follow_source = batch_source_for(scan, follow=follow)
        self._rows_out = 0  # consumer-delivered rows (follow resume anchor)
        if checkpoint is not None:
            digest = self._plan_digest()
            if checkpoint.plan_digest is None:
                checkpoint.plan_digest = digest
            elif checkpoint.plan_digest != digest:
                from lakesoul_tpu.errors import ConfigError

                raise ConfigError(
                    "loader checkpoint was taken against a different table"
                    " version/scan — the saved position is meaningless"
                )

    def _plan_digest(self) -> str:
        import hashlib

        return hashlib.md5(repr(self._scan._cache_key()).encode()).hexdigest()

    def stats(self) -> dict:
        """Loader telemetry snapshot: rows/batches (+ per-sec over in-epoch
        wall time), epochs, per-epoch row totals, consumer stall seconds,
        and current producer-queue depth — plus the replay cache's
        residency stats under ``"replay"`` in cache='device' mode.  Cheap
        enough to read every step."""
        snap = self._stats.snapshot()
        if self._replay is not None:
            snap["replay"] = self._replay.stats()
        return snap

    @property
    def _device_cached(self):
        """Compat view of the pinned epoch (pre-tensorplane attribute):
        the resident (rows, batch) list while a fully-resident cache is
        serving, else None."""
        if self._replay is not None and self._replay.ready \
                and not self._replay.spilled:
            return self._replay._batches
        return None

    def follow_state_json(self) -> str:
        """Resume-ready follower position covering exactly the batches this
        iterator has DELIVERED (rows sitting in the prefetch/device
        pipelines replay on restart — never skipped, never duplicated).
        Persist it next to the model checkpoint; a restarted trainer
        continues with ``scan.to_jax_iter(follow={"state": saved, ...})``.
        Only meaningful in follow mode."""
        from lakesoul_tpu.errors import ConfigError

        if self._follow_source is None:
            raise ConfigError("follow_state_json() requires follow mode")
        return self._follow_source.resume_state(self._rows_out).to_json()

    # ------------------------------------------------------------- pipeline
    def _epoch_windows(self, extra_skip: int = 0) -> "Iterator[_Window]":
        """Fixed-size row windows over one epoch's scan (the pipeline
        source).  Resume: the scan's unit order is deterministic, so the
        checkpoint's delivered-row count is a complete position; the scan
        skips whole units via metadata row counts without decoding them and
        decode-discards only the residual prefix of one unit.
        ``extra_skip`` is the spilled-replay tail resume: the resident
        prefix rows the cache already serves from device memory."""
        skip = (self._checkpoint.rows_delivered if self._checkpoint else 0) \
            + extra_skip
        rb = _Rebatcher(
            self._scan._batch_size,
            capture_views=self._collate is _default_collate,
            tensor_shapes=self._tensor_shapes,
        )
        h = self._h_rebatch
        # the batch-source seam: in-process decode, a scan-plane fleet
        # (scan.via_scanplane) OR a continuous follow stream (follow=) —
        # everything downstream (rebatch, collate, prefetch, device_put,
        # stats) is identical either way
        from lakesoul_tpu.data.batch_source import batch_source_for

        source = (
            self._follow_source
            if self._follow_source is not None
            else batch_source_for(self._scan)
        )
        for arrow_batch in source.iter_batches(
            num_threads=self._io_threads, skip_rows=skip
        ):
            t0 = time.perf_counter()
            windows = rb.push(arrow_batch)
            h.observe(time.perf_counter() - t0)
            yield from windows
        if not self._drop_remainder:
            tail = rb.tail()
            if tail is not None:
                yield tail

    def _host_pipeline(self, extra_skip: int = 0):
        """One epoch's host pipeline on the shared runtime: scan windows →
        collate/transform → bounded prefetch pump."""
        return (
            rt_pipeline("loader")
            .source(self._epoch_windows(extra_skip))
            .map(lambda w: (len(w), self._host_batch(w)), name="collate")
            .prefetch(self._prefetch, name="prefetch")
            .run()
        )

    def _host_batch(self, window):
        t0 = time.perf_counter()
        if isinstance(window, _Window):
            if window.fast and self._collate is _default_collate:
                # fused zero-copy path: views → output buffers, no
                # intermediate table, no per-column combine_chunks
                slot = self._ring.next_slot() if self._ring is not None else None
                batch = window.collate(slot)
            elif self._collate is _default_collate:
                batch = _default_collate(window.to_table(), self._tensor_shapes)
            else:
                batch = self._collate(window.to_table())
        else:
            batch = self._collate(window)
        if self._transform is not None:
            batch = self._transform(batch)
        self._h_collate.observe(time.perf_counter() - t0)
        return batch

    def _fresh_containers(self, batch):
        """Rebuild the pytree's containers (leaves — device arrays — stay
        shared): consumers that mutate a yielded dict in place must never
        poison the cached epoch."""
        import jax

        return jax.tree_util.tree_map(lambda x: x, batch)

    def __iter__(self):
        if self._follow_source is not None:
            from lakesoul_tpu.errors import ConfigError

            if self._follow_started:
                # a second pass would rebuild the follower from the INITIAL
                # state while _rows_out kept accumulating: duplicated
                # delivery now and a follow_state_json() position pointing
                # into a snapshot ring that never saw those rows later
                raise ConfigError(
                    "a follow-mode iterator is single-pass (the stream is"
                    " unbounded): build a new iterator — resuming with"
                    " follow={'state': it.follow_state_json()} — instead"
                    " of re-iterating"
                )
            self._follow_started = True
        if self._replay is not None and self._replay.ready:
            # steady state: replay the HBM-resident epoch — no storage, no
            # host pipeline, no link traffic; a spilled cache replays its
            # resident prefix then re-streams ONLY the tail (the offers
            # stopped at the first budget rejection, so the prefix is
            # contiguous and `resident_rows` is an exact resume position)
            self._stats.epoch_begin()
            completed = False
            try:
                for rows, b in self._replay.replay():
                    self._stats.delivered(rows, 0.0, 0)
                    self._rows_out += rows
                    yield self._fresh_containers(b)
                if self._replay.spilled:
                    completed = yield from self._deliver_stream(
                        extra_skip=self._replay.resident_rows
                    )
                else:
                    completed = True
            finally:
                self._stats.epoch_end(completed)
            return
        self._stats.epoch_begin()
        completed = False
        filling = self._replay is not None and not self._fill_claimed
        if filling:
            self._fill_claimed = True
        try:
            offer = self._replay.offer if filling else None
            completed = yield from self._deliver_stream(offer=offer)
            if completed and filling:
                # only a COMPLETE epoch becomes the resident cache: an
                # abandoned iteration (consumer break → GeneratorExit)
                # never reaches here
                self._replay.seal()
        finally:
            if filling:
                if not self._replay.ready:
                    self._replay.abandon()
                self._fill_claimed = False
            self._stats.epoch_end(completed)

    def _deliver_stream(self, extra_skip: int = 0, offer=None):
        """One streaming epoch: host pipeline → (device_put double buffer)
        → consumer.  Returns True when the pipeline ran to exhaustion AND
        every batch reached the consumer.  ``offer`` is the replay cache's
        pin hook: a pinned batch is handed to the consumer as fresh
        containers so in-place mutation cannot poison the cached epoch."""
        pipe = self._host_pipeline(extra_skip)
        produced_all = False  # the pipeline ran to exhaustion

        def host_iter():
            nonlocal produced_all
            try:
                while True:
                    waited = time.perf_counter()
                    try:
                        item = next(pipe)
                    except StopIteration:
                        produced_all = True
                        return
                    stall = time.perf_counter() - waited
                    # telemetry at the host hand-off: this is the loader's
                    # produced throughput and how long the consumer starved
                    self._h_queue.observe(stall)
                    self._stats.delivered(item[0], stall, pipe.queue_depth())
                    yield item
            finally:
                # quiesce, don't just signal: an abandoned producer that
                # keeps decoding in the background races whatever the caller
                # does next (a resumed iterator over the same table, a test's
                # monkeypatch, shutdown).  close() cancels the pipeline and
                # joins its pump; the bounded wait only rides out a unit
                # decode that is already in flight.
                pipe.close()

        def delivered(rows: int) -> None:
            # position advances when a batch reaches the CONSUMER: a trainer
            # saving (model, checkpoint) after step k resumes exactly at k+1
            self._rows_out += rows
            if self._checkpoint is not None:
                self._checkpoint.rows_delivered += rows

        if not self._device_put:
            for rows, host_batch in host_iter():
                delivered(rows)  # BEFORE yield: a post-step save includes it
                yield host_batch
            return produced_all

        # delivery rides the tensor plane's DLPack hand-off: dtype-preserved
        # contiguous leaves import zero-copy (the collate buffers are
        # 64-byte aligned for exactly this) and only the device placement
        # remains — on CPU nothing copies, on TPU only the H2D DMA does;
        # demoted dtypes fall back to plain device_put (the cast IS the
        # copy).  Aliasing semantics are identical to raw device_put, so
        # the ring probe's verdict governs this path unchanged.
        from lakesoul_tpu.tensorplane.dlpack import deliver as dlpack_deliver

        sharding = self._sharding
        raw_put = lambda b: dlpack_deliver(b, sharding)  # noqa: E731
        h_put = self._h_device_put

        def put(b):
            # dispatch cost only: the H2D copy itself overlaps the
            # training step (that's the double buffering's point)
            t0 = time.perf_counter()
            r = raw_put(b)
            h_put.observe(time.perf_counter() - t0)
            return r

        def emit(r, b):
            delivered(r)
            if offer is not None and offer(r, b):
                return self._fresh_containers(b)  # cache keeps the pristine one
            return b

        # double buffering: keep device_prefetch transfers in flight so the
        # H2D copy of batch k+1 overlaps the step on batch k
        buf: list = []
        for rows, host_batch in host_iter():
            buf.append((rows, put(host_batch)))
            if len(buf) > self._device_prefetch:
                r, b = buf.pop(0)
                yield emit(r, b)
        for r, b in buf:
            yield emit(r, b)
        # a consumer break during the tail flush raises GeneratorExit above
        # and never reaches here: the epoch is NOT complete
        return produced_all
