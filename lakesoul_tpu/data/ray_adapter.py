"""Ray Data adapter (parity with python/src/lakesoul/ray/read_lakesoul.py:60,80
and write_lakesoul.py:23,99): one read task per scan unit; distributed writes
stage files on workers and the driver commits once.

Ray contract used here (stable public API): ``ray.data.from_items(items)``
treats a MAPPING item as a row (its keys become columns) and wraps any
other item as ``{"item": <obj>}``; ``map_batches(fn, batch_size=1,
batch_format="pandas")`` hands ``fn`` a pandas DataFrame of rows and accepts
a pyarrow Table (of any length) as the return value; ``take_all()`` returns
rows as dicts.  Each scan unit therefore travels as ``{"unit": <dict>}`` —
one object column — never as a bare dict whose keys would explode into
columns.  tests/test_adapters.py pins this contract with a wire-faithful
stub so the adapter stays correct without ray in the image.
"""

from __future__ import annotations


def read_lakesoul(scan):
    """LakeSoulScan → ray.data.Dataset: one read task per scan unit
    (in-process scans) or per scan-plane range (``scan.via_scanplane``
    scans, where tasks pull from the fleet's gateway instead of decoding —
    the same batch-source seam every adapter rides)."""
    try:
        import ray
    except ImportError as e:  # pragma: no cover - ray not in the TPU image
        raise ImportError("ray is required for read_lakesoul") from e

    from lakesoul_tpu.data.batch_source import batch_source_for

    source = batch_source_for(scan)
    if getattr(source, "remote", False):
        payload = source.task_payload()
        items = [
            {"unit": {"scanplane": payload, "seq_index": i}}
            for i in range(source.num_task_ranges())
        ]

        def load_remote(df):
            unit = dict(df["unit"].iloc[0])
            from lakesoul_tpu.scanplane.client import read_task_range

            return read_task_range(unit["scanplane"], unit["seq_index"])

        return ray.data.from_items(items).map_batches(
            load_remote, batch_size=1, batch_format="pandas"
        )

    units = [
        {
            "unit": {
                "data_files": u.data_files,
                "primary_keys": u.primary_keys,
                **scan._unit_kwargs(u),
            }
        }
        for u in scan.scan_plan()
    ]

    def load_batch(df):
        # batch_size=1 → exactly one scan-unit dict per call, in the single
        # "unit" object column built above
        unit = dict(df["unit"].iloc[0])
        files = unit.pop("data_files")
        pks = unit.pop("primary_keys")
        from lakesoul_tpu.io.reader import read_scan_unit

        return read_scan_unit(files, pks, **unit)

    return ray.data.from_items(units).map_batches(
        load_batch, batch_size=1, batch_format="pandas"
    )


def write_lakesoul(dataset, table) -> None:
    """ray.data.Dataset → table: workers stage files via TableWriter, the
    driver commits every staged file in ONE ACID commit (reference: Datasink
    distributed write + driver-side single commit, write_lakesoul.py:99)."""
    try:
        import ray  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError("ray is required for write_lakesoul") from e

    import pandas as pd

    cfg = table.io_config()
    table_path = table.info.table_path

    def stage(batch):
        # emit one plain-typed row per staged file: worker→driver transport
        # must stay arrow-serializable (no dataclass objects in columns)
        import pyarrow as pa

        from lakesoul_tpu.io.writer import TableWriter

        w = TableWriter(cfg, table_path)
        w.write_batch(pa.Table.from_pandas(batch, preserve_index=False))
        outs = w.close()
        return pd.DataFrame(
            {
                "partition_desc": [o.partition_desc for o in outs],
                "path": [o.path for o in outs],
                "size": [o.size for o in outs],
                "file_exist_cols": [o.file_exist_cols for o in outs],
            }
        )

    from lakesoul_tpu.meta import CommitOp, DataFileOp

    staged = dataset.map_batches(stage, batch_format="pandas").take_all()
    files_by_partition: dict[str, list[DataFileOp]] = {}
    for row in staged:
        files_by_partition.setdefault(row["partition_desc"], []).append(
            DataFileOp(
                path=row["path"],
                file_op="add",
                size=row["size"],
                file_exist_cols=row["file_exist_cols"],
            )
        )
    op = CommitOp.MERGE if table.info.primary_keys else CommitOp.APPEND
    table.catalog.client.commit_data_files(
        table.info,
        files_by_partition,
        op,
        storage_options=cfg.object_store_options,
    )
