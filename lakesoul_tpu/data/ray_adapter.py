"""Ray Data adapter (parity with python/src/lakesoul/ray/read_lakesoul.py:60,80
and write_lakesoul.py:23,99): one read task per scan unit; distributed writes
stage files on workers and the driver commits once."""

from __future__ import annotations


def read_lakesoul(scan):
    """LakeSoulScan → ray.data.Dataset (one block per scan unit)."""
    try:
        import ray
    except ImportError as e:  # pragma: no cover - ray not in the TPU image
        raise ImportError("ray is required for read_lakesoul") from e

    units = [
        {"data_files": u.data_files, "primary_keys": u.primary_keys, **scan._unit_kwargs(u)}
        for u in scan.scan_plan()
    ]

    def load(unit: dict):
        from lakesoul_tpu.io.reader import read_scan_unit

        kwargs = {k: v for k, v in unit.items() if k not in ("data_files", "primary_keys")}
        return read_scan_unit(unit["data_files"], unit["primary_keys"], **kwargs)

    return ray.data.from_items(units).map_batches(
        lambda b: load(b), batch_format="pyarrow"
    )


def write_lakesoul(dataset, table) -> None:
    """ray.data.Dataset → table: workers stage parquet via TableWriter, the
    driver commits all FlushOutputs in one ACID commit (reference: Datasink
    distributed write + driver-side single commit)."""
    try:
        import ray  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError("ray is required for write_lakesoul") from e

    cfg = table.io_config()
    table_path = table.info.table_path

    def stage(batch):
        from lakesoul_tpu.io.writer import TableWriter

        w = TableWriter(cfg, table_path)
        w.write_batch(batch)
        return {"outputs": [w.close()]}

    from lakesoul_tpu.meta import CommitOp, DataFileOp

    staged = dataset.map_batches(stage, batch_format="pyarrow").take_all()
    files_by_partition: dict[str, list[DataFileOp]] = {}
    for row in staged:
        for out in row["outputs"]:
            files_by_partition.setdefault(out.partition_desc, []).append(
                DataFileOp(path=out.path, file_op="add", size=out.size,
                           file_exist_cols=out.file_exist_cols)
            )
    op = CommitOp.MERGE if table.info.primary_keys else CommitOp.APPEND
    table.catalog.client.commit_data_files(table.info, files_by_partition, op)
