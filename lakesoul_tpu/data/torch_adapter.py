"""PyTorch adapter (parity with python/src/lakesoul/torch/dataset.py:15)."""

from __future__ import annotations


def _require_torch():
    try:
        import torch.utils.data as tud
    except ImportError as e:  # pragma: no cover
        raise ImportError("torch is required for to_torch()") from e
    return tud


class TorchIterableDataset:
    """Lazy torch IterableDataset over a LakeSoulScan, yielding Arrow record
    batches (same contract as the reference's Dataset)."""

    def __new__(cls, scan):
        tud = _require_torch()

        class _DS(tud.IterableDataset):
            def __init__(self, scan):
                self._scan = scan

            def __iter__(self):
                yield from self._scan.to_batches()

        return _DS(scan)
