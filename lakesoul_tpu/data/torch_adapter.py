"""PyTorch adapter (parity with python/src/lakesoul/torch/dataset.py:15).

Batches come through the batch-source seam
(:mod:`lakesoul_tpu.data.batch_source`), so a scan bound to a scan-plane
fleet (``scan.via_scanplane(...)``) streams remotely with the same
iterator contract — the torch side never knows who decoded."""

from __future__ import annotations


def _require_torch():
    try:
        import torch.utils.data as tud
    except ImportError as e:  # pragma: no cover
        raise ImportError("torch is required for to_torch()") from e
    return tud


class TorchIterableDataset:
    """Lazy torch IterableDataset over a LakeSoulScan, yielding Arrow record
    batches (same contract as the reference's Dataset)."""

    def __new__(cls, scan):
        tud = _require_torch()

        class _DS(tud.IterableDataset):
            def __init__(self, scan):
                self._scan = scan

            def __iter__(self):
                from lakesoul_tpu.data.batch_source import batch_source_for

                yield from batch_source_for(self._scan).iter_batches()

        return _DS(scan)
