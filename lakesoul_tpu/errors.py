"""Framework-wide error types.

Mirrors the error surface of the reference's ``LakeSoulMetaDataError`` /
``LakeSoulError`` enums (rust/lakesoul-metadata/src/error.rs,
rust/lakesoul-io/src/lakesoul_io_config.rs) with idiomatic Python exceptions.
"""


class LakeSoulError(Exception):
    """Base class for all lakesoul_tpu errors."""


class MetadataError(LakeSoulError):
    """Metadata-layer failure (DAO op, schema, store IO)."""


class CommitConflictError(MetadataError):
    """Optimistic-concurrency conflict: another writer committed the same
    (table_id, partition_desc, version) first.  Callers re-read the current
    partition version and retry (the reference delegates this to a PG primary
    key conflict; see metadata_client.rs:467 and meta_init.sql:95-99)."""


class TableNotFoundError(MetadataError):
    pass


class LeaseFencedError(MetadataError):
    """A commit (or renewal) presented a lease that is no longer valid: the
    holder's TTL expired and a peer re-acquired the lease with a higher
    fencing token.  The presenter is a *zombie* — it must abandon the job,
    never retry it: the work has been (or will be) redone by the new
    holder, and retrying would double-apply it.  Deliberately permanent in
    the resilience taxonomy (MetadataError → not transient)."""


class TableAlreadyExistsError(MetadataError):
    pass


class IOError_(LakeSoulError):
    """Data-plane IO failure (read/write/merge)."""


class ConfigError(LakeSoulError):
    pass


class RBACError(LakeSoulError):
    """Permission denied by domain-based RBAC."""


class VectorIndexError(LakeSoulError):
    pass


class TensorColumnError(LakeSoulError):
    """A declared fixed-shape tensor column received data that violates its
    declaration (wrong element dtype, wrong flattened width, nulls in the
    list or its children, or the column missing entirely).  Raised at WRITE
    time by the tensor-plane validation (tensorplane/columns.py) so a
    malformed batch dies at the table boundary with the column named,
    instead of three stages into a training run as a shape error."""


class ScanPlaneWaitTimeout(LakeSoulError):
    """A ``scan_stream`` exchange exhausted ``LAKESOUL_SCANPLANE_WAIT_S``
    waiting for a worker to produce a range.  Carries the session id and
    the range index so an operator can tell WHICH shard starved (no
    workers against the spool, or a fleet too small for the backlog) —
    the generic Flight error this used to surface said neither.  The
    message format is part of the wire contract: the client re-raises the
    typed form from the marker the gateway's error string carries."""

    MARKER = "scanplane wait exhausted"

    def __init__(self, session: str, range_index: int, wait_s: float):
        self.session = session
        self.range_index = int(range_index)
        self.wait_s = float(wait_s)
        super().__init__(
            f"{self.MARKER}: session={session} range={range_index} after"
            f" {wait_s:.0f}s — are scanplane workers running against this"
            " spool?"
        )

    @classmethod
    def from_message(cls, message: str) -> "ScanPlaneWaitTimeout | None":
        """Re-raise surface for the client: recover the typed error from a
        Flight error string that carries the marker (gateway errors cross
        the wire as text).  Returns ``None`` for unrelated messages."""
        import re

        m = re.search(
            r"scanplane wait exhausted: session=(\S+) range=(\d+) after"
            r" (\d+)s",
            message,
        )
        if m is None:
            return None
        return cls(m.group(1), int(m.group(2)), float(m.group(3)))


class TransientError(LakeSoulError):
    """Marker base for failures that are expected to clear on their own
    (network blips, 5xx, races): the resilience layer
    (runtime/resilience.py) retries these and only these.  Raising a
    subclass is how a layer declares "try me again"."""


class OverloadedError(TransientError):
    """Admission control rejected the request: the in-flight bound and the
    bounded queue are both full (or the queue wait timed out).  Serving
    surfaces map this to Flight UNAVAILABLE — the client may back off and
    retry, which is why it is transient."""


class CircuitOpenError(TransientError):
    """A circuit breaker is open: recent failures crossed the threshold and
    the protected dependency is being given time to recover.  Calls fail
    fast instead of queueing behind a dead backend."""
