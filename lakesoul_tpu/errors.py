"""Framework-wide error types.

Mirrors the error surface of the reference's ``LakeSoulMetaDataError`` /
``LakeSoulError`` enums (rust/lakesoul-metadata/src/error.rs,
rust/lakesoul-io/src/lakesoul_io_config.rs) with idiomatic Python exceptions.
"""


class LakeSoulError(Exception):
    """Base class for all lakesoul_tpu errors."""


class MetadataError(LakeSoulError):
    """Metadata-layer failure (DAO op, schema, store IO)."""


class CommitConflictError(MetadataError):
    """Optimistic-concurrency conflict: another writer committed the same
    (table_id, partition_desc, version) first.  Callers re-read the current
    partition version and retry (the reference delegates this to a PG primary
    key conflict; see metadata_client.rs:467 and meta_init.sql:95-99)."""


class TableNotFoundError(MetadataError):
    pass


class TableAlreadyExistsError(MetadataError):
    pass


class IOError_(LakeSoulError):
    """Data-plane IO failure (read/write/merge)."""


class ConfigError(LakeSoulError):
    pass


class RBACError(LakeSoulError):
    """Permission denied by domain-based RBAC."""


class VectorIndexError(LakeSoulError):
    pass
