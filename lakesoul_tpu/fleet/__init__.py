"""Fleet plane: the cross-host tier composing the existing planes.

Three concerns, one package (ROADMAP item 2 — the pod-scale data fabric):

- :mod:`~lakesoul_tpu.fleet.transport` — the spool-segment transport seam.
  The PR-11 shm fast path stays the same-host lane; an object-store
  **spill** transport persists sealed segments (fsync+rename, CRC
  sidecars, pruned with the session) for cross-host pulls through the
  resilient fs; the Flight **stream** transport is the always-works
  floor.  Negotiation extends the shm probe: prove-you-can-read → shm,
  else prove-you-can-read-the-spill-prefix → spill, else stream.
- :mod:`~lakesoul_tpu.fleet.autoscale` — a leased controller that watches
  the spool backlog and the FleetAggregator merged view and spawns /
  retires scanplane workers between a declared min/max; a SIGKILLed
  controller fails over fenced via the PR-7 lease table.
- :mod:`~lakesoul_tpu.fleet.multihost` — the process-indexed training
  surface: ``to_jax_iter(multihost=True)`` shards the scan by
  ``jax.process_index()/process_count()`` (env-overridable for emulated
  multi-host), so N hosts consume disjoint, union-complete shards and
  the replay cache pins exactly the local host's shard.

``python -m lakesoul_tpu.fleet`` exposes the ``autoscale`` and ``train``
roles — the processes the chaos suite SIGKILLs.
"""

from __future__ import annotations

from lakesoul_tpu.fleet.multihost import process_axis, shard_scan
from lakesoul_tpu.fleet.transport import (
    TRANSPORTS,
    forced_transport,
    meter_range,
    negotiated,
)

__all__ = [
    "TRANSPORTS",
    "forced_transport",
    "meter_range",
    "negotiated",
    "process_axis",
    "shard_scan",
]
