"""``python -m lakesoul_tpu.fleet`` — the fleet-plane process entries.

Two roles (the fleet chaos suite runs THESE as the children it SIGKILLs —
what is tested is what deploys):

- ``autoscale``: the leased worker controller.  Watches one spool's
  backlog (plus the obs fleet's merged SLO view when armed) and sizes a
  scanplane worker fleet between ``--min/--max``.  Every action is one
  JSON line on stdout (``{"event": "spawn", "pid": ...}``) so a parent —
  bench, chaos test, operator tooling — can watch spawns, takeovers and
  backfills without scraping logs.
- ``train``: one emulated training host.  Resolves its position on the
  data axis (``LAKESOUL_FLEET_PROCESS_INDEX``/``_COUNT``, else jax's
  view), consumes its shard through ``to_jax_iter(multihost=True)`` —
  optionally via a scanplane gateway — and prints ``{rows, batches,
  sha256, ...}`` hashed over the collated host arrays, the per-rank
  identity oracle the fleet bench compares against single-process shard
  scans.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import logging
import time


def _cmd_autoscale(args) -> int:
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.obs import fleet
    from lakesoul_tpu.fleet.autoscale import (
        WorkerAutoscaler,
        WorkerSpawner,
        emit_jsonl,
    )

    catalog = LakeSoulCatalog(args.warehouse, db_path=args.db_path)
    spawner = WorkerSpawner(
        args.warehouse,
        args.spool,
        db_path=args.db_path,
        lease_ttl_s=args.worker_lease_ttl_s,
        poll_s=args.worker_poll_s,
    )
    controller = WorkerAutoscaler(
        catalog.client.store,
        spawner,
        spool_dir=args.spool,
        min_workers=args.min_workers,
        max_workers=args.max_workers,
        controller_id=args.controller_id,
        lease_ttl_s=args.lease_ttl_s,
    )
    fleet.arm("fleet-autoscaler", service_id=controller.controller_id)
    emit_jsonl({
        "event": "autoscaler",
        "controller": controller.controller_id,
        "spool": args.spool,
        "min": controller.policy.min_workers,
        "max": controller.policy.max_workers,
    })
    try:
        controller.run_forever(poll_s=args.poll_s, on_event=emit_jsonl)
    except KeyboardInterrupt:
        pass
    finally:
        controller.stop()
    return 0


def _cmd_train(args) -> int:
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.obs import fleet
    from lakesoul_tpu.obs.tracing import span
    from lakesoul_tpu.fleet.multihost import digest_batch, process_axis

    index, count = process_axis()
    fleet.arm("fleet-train", service_id=f"rank{index}")
    catalog = LakeSoulCatalog(args.warehouse, db_path=args.db_path)
    scan = catalog.scan(args.table, args.namespace).batch_size(args.batch_size)
    if args.location:
        scan = scan.via_scanplane(args.location)
    try:
        import jax

        local_devices = jax.local_device_count()
    except Exception:
        local_devices = 0
    digest = hashlib.sha256()
    rows = 0
    batches = 0
    started_unix = time.time()
    start = time.perf_counter()
    with span("fleet.train.consume", table=args.table, rank=index):
        it = scan.to_jax_iter(
            multihost=True,
            device_put=args.device_put,
            drop_remainder=False,
        )
        for batch in it:
            # hash the collated HOST arrays key-by-key: deterministic for
            # equal contents regardless of device placement or process, so
            # the same loop over a single-process scan.shard(rank, world)
            # is the byte-identity oracle
            rows += digest_batch(digest, batch)
            batches += 1
            if args.step_s:
                # emulated per-batch training step: the host's devices are
                # busy for a fixed wall slice, the realistic consumption
                # shape the fleet bench scales against (N hosts each step
                # over their OWN shard concurrently)
                time.sleep(args.step_s)
    elapsed = time.perf_counter() - start
    print(json.dumps({
        "rows": rows,
        "batches": batches,
        "sha256": digest.hexdigest(),
        "elapsed_s": round(elapsed, 4),
        "started_unix": started_unix,
        "ended_unix": time.time(),
        "process_index": index,
        "process_count": count,
        "local_devices": local_devices,
    }), flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "lakesoul-fleet",
        description="fleet plane: worker autoscaling + multi-host trainers",
    )
    sub = p.add_subparsers(dest="role")

    pa_ = sub.add_parser("autoscale", help="leased scanplane worker controller")
    pa_.add_argument("--warehouse", required=True)
    pa_.add_argument("--db-path", default=None)
    pa_.add_argument("--spool", required=True)
    pa_.add_argument("--min-workers", type=int, default=None,
                     help="floor (default LAKESOUL_FLEET_MIN_WORKERS or 1)")
    pa_.add_argument("--max-workers", type=int, default=None,
                     help="ceiling (default LAKESOUL_FLEET_MAX_WORKERS or 8)")
    pa_.add_argument("--lease-ttl-s", type=float, default=10.0,
                     help="controller lease TTL (fail-over bound)")
    pa_.add_argument("--poll-s", type=float, default=1.0)
    pa_.add_argument("--controller-id", default=None)
    pa_.add_argument("--worker-lease-ttl-s", type=float, default=None)
    pa_.add_argument("--worker-poll-s", type=float, default=None)
    pa_.set_defaults(fn=_cmd_autoscale)

    pt = sub.add_parser("train", help="one emulated training host (rows + sha256)")
    pt.add_argument("--warehouse", required=True)
    pt.add_argument("--db-path", default=None)
    pt.add_argument("--table", required=True)
    pt.add_argument("--namespace", default="default")
    pt.add_argument("--batch-size", type=int, default=8192)
    pt.add_argument("--location", default=None,
                    help="scanplane gateway; omit to decode in-process")
    pt.add_argument("--device-put", action="store_true",
                    help="move batches to device (default: host arrays)")
    pt.add_argument("--step-s", type=float, default=0.0,
                    help="emulated per-batch training-step seconds (bench"
                         " knob: makes consumption device-bound)")
    pt.set_defaults(fn=_cmd_train)

    args = p.parse_args(argv)
    if args.role is None:
        p.error("choose a role: autoscale | train")
    logging.basicConfig(level=logging.INFO)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
