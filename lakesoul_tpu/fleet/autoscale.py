"""Queue-driven scanplane worker autoscaling, leased and fenced.

One controller per spool SCOPE (the lease key hashes the spool path) owns
the worker fleet between a declared min/max.  The control loop is a pure
policy over observable signals — nothing here guesses:

- **backlog**: unproduced ranges across the spool's live sessions (the
  same work-discovery walk the workers run);
- **SLO burn** + **rows/s** + **queue stalls by consumer**: the PR-16
  :class:`~lakesoul_tpu.obs.fleet.FleetAggregator` merged view, when an
  obs spool is armed — a fleet meeting its freshness budget needs no
  growth a backlog count alone would demand.

Scale-up is immediate (backlog maps to workers at
``ranges_per_worker``; an SLO breach with backlog jumps straight to
max).  Scale-down waits ``idle_polls_to_scale_down`` consecutive empty
polls — production is bursty per session, and worker churn costs real
process boots.

Fail-over is the PR-7 lease table: the controller holds
``fleet/autoscaler/<scope>`` under TTL + heartbeat + fencing token.  A
SIGKILLed controller's lease lapses within one TTL; a standby acquires
it with a BUMPED token and becomes leader; the zombie — if it wakes —
observes its failed renewal, demotes itself, and retires its own
children instead of fighting the new leader's fleet.  The spawned
children are the REAL worker entry (``python -m lakesoul_tpu.scanplane
worker``) via :func:`~lakesoul_tpu.obs.fleet.child_env`, so they join
the same obs fleet and trace.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import subprocess
import sys
import threading
from dataclasses import dataclass, field

from lakesoul_tpu.obs import fleet as obs_fleet
from lakesoul_tpu.obs import registry
from lakesoul_tpu.runtime.resilience import _env_int
from lakesoul_tpu.scanplane import session as sess
from lakesoul_tpu.scanplane import spool as spool_mod

logger = logging.getLogger(__name__)

ENV_MIN_WORKERS = "LAKESOUL_FLEET_MIN_WORKERS"
ENV_MAX_WORKERS = "LAKESOUL_FLEET_MAX_WORKERS"

LEASE_PREFIX = "fleet/autoscaler/"


def lease_key(spool_dir: str) -> str:
    """The controller lease for one spool scope — peers watching the same
    spool contend for the same key no matter how they spelled the path."""
    scope = hashlib.md5(
        os.path.abspath(spool_dir).encode()
    ).hexdigest()[:12]
    return f"{LEASE_PREFIX}{scope}"


# ------------------------------------------------------------------ signals


@dataclass
class AutoscaleSignals:
    """One control tick's observed state (every field machine-derived)."""

    backlog: int = 0            # unproduced ranges across live sessions
    sessions: int = 0           # sessions with any backlog
    slo_breached: bool = False  # fleet freshness SLO out of budget
    rows_per_s: float = 0.0     # fleet north-star aggregate
    queue_stall_s: float = 0.0  # summed consumer queue-stall seconds


def spool_backlog(spool_dir: str) -> "tuple[int, int]":
    """(unproduced ranges, sessions with backlog) over the spool — the
    workers' own work-discovery walk, read-only."""
    backlog = 0
    sessions = 0
    for session_id in sess.list_sessions(spool_dir):
        session = sess.ScanSession.load(spool_dir, session_id)
        if session is None:
            continue
        missing = len(session.ranges) - len(
            spool_mod.ready_ranges(session.dir(spool_dir))
        )
        if missing > 0:
            backlog += missing
            sessions += 1
    return backlog, sessions


def collect_signals(
    spool_dir: str, *, obs_spool: str | None = None
) -> AutoscaleSignals:
    backlog, sessions = spool_backlog(spool_dir)
    sig = AutoscaleSignals(backlog=backlog, sessions=sessions)
    spool = obs_spool or os.environ.get(obs_fleet.ENV_SPOOL) or ""
    if spool:
        try:
            agg = obs_fleet.FleetAggregator(spool)
            doc = agg.aggregate()
            sig.slo_breached = not doc["slos"]["freshness"]["in_budget"]
            sig.rows_per_s = float(doc["fleet"]["rows_per_s"])
            for key, value in doc["snapshot"].items():
                if key.startswith("lakesoul_scan_stage_seconds{") \
                        and 'stage="queue"' in key and isinstance(value, dict):
                    sig.queue_stall_s += float(value.get("sum", 0.0))
        except Exception:
            logger.debug("fleet merged view unavailable", exc_info=True)
    return sig


# ------------------------------------------------------------------- policy


@dataclass
class AutoscalePolicy:
    """Pure target-size policy (the unit-testable machine).

    Stateful only in its idle counter: scale-down needs
    ``idle_polls_to_scale_down`` CONSECUTIVE backlog-free observations so
    one inter-session gap does not churn the fleet."""

    min_workers: int
    max_workers: int
    ranges_per_worker: int = 4
    idle_polls_to_scale_down: int = 3
    _idle: int = field(default=0, repr=False)

    def __post_init__(self):
        from lakesoul_tpu.errors import ConfigError

        if not 0 <= self.min_workers <= self.max_workers:
            raise ConfigError(
                f"invalid autoscale bounds min={self.min_workers}"
                f" max={self.max_workers}"
            )

    def _clamp(self, n: int) -> int:
        return max(self.min_workers, min(self.max_workers, n))

    def target(self, signals: AutoscaleSignals, current: int) -> int:
        if signals.backlog > 0:
            self._idle = 0
            if signals.slo_breached:
                return self.max_workers  # burn budget: all hands
            want = math.ceil(signals.backlog / max(1, self.ranges_per_worker))
            # never shrink under live backlog: the tail of a session is
            # not a reason to churn workers mid-drain
            return self._clamp(max(want, current))
        self._idle += 1
        if self._idle >= self.idle_polls_to_scale_down:
            return self.min_workers
        return self._clamp(max(current, self.min_workers))


# ------------------------------------------------------------------ spawner


class WorkerSpawner:
    """Own the controller's worker children (real ``scanplane worker``
    entries).  LIFO retire; reap() notices SIGKILLed children so the
    control loop backfills them on its next tick."""

    def __init__(
        self,
        warehouse: str,
        spool_dir: str,
        *,
        db_path: str | None = None,
        lease_ttl_s: float | None = None,
        poll_s: float | None = None,
        tag: str = "fleet",
    ):
        self.warehouse = warehouse
        self.spool_dir = spool_dir
        self.db_path = db_path
        self.lease_ttl_s = lease_ttl_s
        self.poll_s = poll_s
        self.tag = tag
        self._children: list[subprocess.Popen] = []
        # terminated-but-not-yet-exited children, reaped by reap(): a
        # retired worker that never got waited would stay a zombie until
        # the controller itself exits
        self._retiring: list[subprocess.Popen] = []
        self._seq = 0

    @property
    def count(self) -> int:
        return len(self._children)

    def worker_argv(self, worker_id: str) -> list[str]:
        argv = [
            sys.executable, "-m", "lakesoul_tpu.scanplane", "worker",
            "--warehouse", self.warehouse,
            "--spool", self.spool_dir,
            "--worker-id", worker_id,
        ]
        if self.db_path:
            argv += ["--db-path", self.db_path]
        if self.lease_ttl_s is not None:
            argv += ["--lease-ttl-s", str(self.lease_ttl_s)]
        if self.poll_s is not None:
            argv += ["--poll-s", str(self.poll_s)]
        return argv

    def spawn(self) -> dict:
        self._seq += 1
        worker_id = f"{self.tag}-{os.getpid()}-{self._seq}"
        proc = subprocess.Popen(
            self.worker_argv(worker_id),
            stdout=subprocess.DEVNULL,
            env=obs_fleet.child_env(),
        )
        self._children.append(proc)
        return {"worker_id": worker_id, "pid": proc.pid}

    def retire(self) -> "dict | None":
        if not self._children:
            return None
        proc = self._children.pop()
        proc.terminate()
        # hand the exiting child to reap() instead of wait()ing here —
        # blocking the control tick on a worker's shutdown grace would
        # stall every other scaling decision behind one slow drain
        self._retiring.append(proc)
        return {"pid": proc.pid}

    def reap(self) -> list[dict]:
        """Drop children that exited (crashed or SIGKILLed); the reported
        deficit is what the next control tick backfills.  Retired children
        are reaped here too (poll() collects the exit status) but are NOT
        a deficit — the controller asked them to leave."""
        dead = [p for p in self._children if p.poll() is not None]
        self._children = [p for p in self._children if p.poll() is None]
        self._retiring = [p for p in self._retiring if p.poll() is None]
        return [{"pid": p.pid, "returncode": p.returncode} for p in dead]

    def stop_all(self, timeout: float = 10.0) -> None:
        for p in self._children:
            if p.poll() is None:
                p.terminate()
        for p in self._children + self._retiring:
            try:
                p.wait(timeout)
            except subprocess.TimeoutExpired:
                p.kill()
        self._children = []
        self._retiring = []


# --------------------------------------------------------------- controller


class WorkerAutoscaler:
    """The leased control loop: standby ↔ leader ↔ fenced.

    ``step()`` is one tick, returning the events it emitted (the
    ``__main__`` role prints them as JSON lines; tests drive it with an
    injected ``now_ms`` clock and a fake spawner).  With
    ``heartbeat=True`` (production) a daemon renewal thread keeps the
    lease alive between ticks; with ``heartbeat=False`` (deterministic
    tests) each tick renews synchronously under the injected clock."""

    def __init__(
        self,
        store,
        spawner,
        *,
        spool_dir: str,
        min_workers: int | None = None,
        max_workers: int | None = None,
        controller_id: str | None = None,
        lease_ttl_s: float = 10.0,
        policy: AutoscalePolicy | None = None,
        obs_spool: str | None = None,
        heartbeat: bool = True,
    ):
        import uuid

        self.store = store
        self.spawner = spawner
        self.spool_dir = spool_dir
        self.key = lease_key(spool_dir)
        self.controller_id = (
            controller_id or f"autoscaler-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        self.lease_ttl_ms = int(lease_ttl_s * 1000)
        min_w = _env_int(ENV_MIN_WORKERS, 1) if min_workers is None else min_workers
        max_w = _env_int(ENV_MAX_WORKERS, 8) if max_workers is None else max_workers
        self.policy = policy or AutoscalePolicy(min_w, max_w)
        self.obs_spool = obs_spool
        self._use_heartbeat = heartbeat
        self._heartbeat = None
        self._lease = None
        self.state = "standby"
        reg = registry()
        self._g_workers = reg.gauge("lakesoul_fleet_workers")
        self._c_events = {
            a: reg.counter("lakesoul_fleet_scale_events_total", action=a)
            for a in ("spawn", "retire", "backfill", "fenced", "takeover")
        }
        self._stop = None

    @property
    def fencing_token(self) -> "int | None":
        return self._lease.fencing_token if self._lease is not None else None

    # ------------------------------------------------------------ lease fsm
    def _acquire(self, now_ms: int | None) -> bool:
        lease = self.store.acquire_lease(
            self.key, self.controller_id, self.lease_ttl_ms, now_ms=now_ms
        )
        if lease is None:
            return False
        self._lease = lease
        self.state = "leader"
        if self._use_heartbeat:
            from lakesoul_tpu.compaction.service import _LeaseHeartbeat

            self._heartbeat = _LeaseHeartbeat(
                self.store, self.key, self.controller_id,
                lease.fencing_token, self.lease_ttl_ms,
            )
            self._heartbeat.start()
        return True

    def _renewed(self, now_ms: int | None) -> bool:
        if self._use_heartbeat:
            return not (self._heartbeat is not None and self._heartbeat.fenced)
        lease = self.store.renew_lease(
            self.key, self.controller_id, self._lease.fencing_token,
            self.lease_ttl_ms, now_ms=now_ms,
        )
        if lease is not None:
            self._lease = lease
            return True
        return False

    def _demote(self) -> None:
        """Fenced: a peer's token passed ours.  Stop acting AND retire our
        own children — the new leader owns sizing now, and a zombie's
        workers double the fleet it is trying to control."""
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        self.spawner.stop_all()
        self._lease = None
        self.state = "standby"
        self._g_workers.set(0)

    # ----------------------------------------------------------------- tick
    def step(self, *, now_ms: int | None = None) -> list[dict]:
        events: list[dict] = []
        if self.state == "standby":
            if not self._acquire(now_ms):
                return [{"event": "standby", "controller": self.controller_id}]
            taken_over = self._lease.fencing_token > 1
            if taken_over:
                self._c_events["takeover"].inc()
            events.append({
                "event": "leader",
                "controller": self.controller_id,
                "fence": self._lease.fencing_token,
                "takeover": taken_over,
            })
        elif not self._renewed(now_ms):
            self._c_events["fenced"].inc()
            self._demote()
            return events + [{
                "event": "fenced", "controller": self.controller_id,
            }]

        reaped = self.spawner.reap()
        for r in reaped:
            self._c_events["backfill"].inc()
            events.append({"event": "worker_exit", **r})
        signals = collect_signals(self.spool_dir, obs_spool=self.obs_spool)
        target = self.policy.target(signals, self.spawner.count)
        while self.spawner.count < target:
            spawned = self.spawner.spawn()
            self._c_events["spawn"].inc()
            events.append({"event": "spawn", **spawned})
        while self.spawner.count > target:
            retired = self.spawner.retire()
            self._c_events["retire"].inc()
            events.append({"event": "retire", **(retired or {})})
        self._g_workers.set(self.spawner.count)
        events.append({
            "event": "tick",
            "state": self.state,
            "workers": self.spawner.count,
            "target": target,
            "backlog": signals.backlog,
            "slo_breached": signals.slo_breached,
        })
        return events

    # ----------------------------------------------------------------- loop
    def run_forever(
        self,
        *,
        poll_s: float = 1.0,
        stop_event: "threading.Event | None" = None,
        on_event=None,
    ) -> None:
        self._stop = stop_event or threading.Event()
        while not self._stop.is_set():
            try:
                for ev in self.step():
                    if on_event is not None and ev.get("event") != "standby":
                        on_event(ev)
            except Exception:
                logger.exception("autoscaler tick failed")
            self._stop.wait(poll_s)

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if self._lease is not None:
            try:
                self.store.release_lease(
                    self.key, self.controller_id, self._lease.fencing_token
                )
            except Exception:
                logger.debug("autoscaler lease release failed", exc_info=True)
            self._lease = None
        self.spawner.stop_all()
        self.state = "standby"


def emit_jsonl(event: dict) -> None:
    """The ``__main__`` role's event sink: one JSON line per action, so a
    bench/chaos parent can watch spawns and takeovers on stdout."""
    print(json.dumps(event, sort_keys=True), flush=True)
