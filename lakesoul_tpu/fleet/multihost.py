"""Process-indexed multi-host sharding: ONE table feeding N hosts.

``to_jax_iter(multihost=True)`` (and ``LakeSoulScan.auto_shard``) resolve
this module's :func:`process_axis` — the host's position on the data
axis — and shard the scan ``i % count == index`` through the existing
``shard()`` builder, so every downstream consumer (batch-source seam,
scanplane delivery, replay cache) sees a plain sharded scan:

- ranks are **disjoint** and their union is **complete** (the unit
  assignment is round-robin over the deterministic plan order);
- the per-rank stream is byte-identical to a single-process
  ``scan.shard(rank, world)`` — the property the fleet bench asserts
  per rank with sha256 oracles;
- the device-replay cache bills only the local shard (it meters via
  ``sharding.shard_shape``, which already accounts per-device slices).

The axis comes from ``jax.process_index()/process_count()`` on a real
multi-host mesh.  ``LAKESOUL_FLEET_PROCESS_INDEX`` /
``LAKESOUL_FLEET_PROCESS_COUNT`` override it — the emulation hook the
bench and chaos suites use to run N "hosts" as N processes on one
machine, and an escape hatch for launchers that know the topology before
jax does.
"""

from __future__ import annotations

import os

from lakesoul_tpu.errors import ConfigError

ENV_INDEX = "LAKESOUL_FLEET_PROCESS_INDEX"
ENV_COUNT = "LAKESOUL_FLEET_PROCESS_COUNT"


def process_axis() -> "tuple[int, int]":
    """(process_index, process_count) for the data axis: the env override
    when set (both vars required together, validated), else jax's view of
    the mesh, else a single process."""
    raw_idx = os.environ.get(ENV_INDEX)
    raw_cnt = os.environ.get(ENV_COUNT)
    if raw_idx is not None or raw_cnt is not None:
        if raw_idx is None or raw_cnt is None:
            raise ConfigError(
                f"{ENV_INDEX} and {ENV_COUNT} must be set together"
            )
        try:
            idx, cnt = int(raw_idx), int(raw_cnt)
        except ValueError:
            raise ConfigError(
                f"non-integer {ENV_INDEX}/{ENV_COUNT}:"
                f" {raw_idx!r}/{raw_cnt!r}"
            )
        if cnt < 1 or not 0 <= idx < cnt:
            raise ConfigError(
                f"invalid process axis index={idx} count={cnt}"
            )
        return idx, cnt
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:  # jax absent or uninitialised: single-host
        return 0, 1


def digest_batch(digest, batch: dict) -> int:
    """Fold one collated host batch into a sha256 — the per-rank identity
    oracle (``fleet train`` output vs a single-process shard scan).
    Content-deterministic across processes: numeric arrays hash their
    buffer bytes; string/object columns hash their VALUES (an object
    array's raw buffer is per-process pointers).  Returns the row count."""
    import numpy as np

    rows = None
    for name in sorted(batch):
        arr = np.asarray(batch[name])
        rows = len(arr) if rows is None else rows
        digest.update(name.encode())
        if arr.dtype == object or arr.dtype.kind in ("U", "S"):
            for v in arr:
                digest.update(str(v).encode())
                digest.update(b"\x00")
        else:
            digest.update(np.ascontiguousarray(arr).tobytes())
    return rows or 0


def shard_scan(scan):
    """Apply the process axis to a scan.  A scan the caller already
    sharded CONSISTENTLY passes through (idempotent — a shared input
    pipeline built once per host may hit both paths); an inconsistent
    explicit shard is a configuration conflict that must fail loudly,
    not silently train on the wrong rows."""
    index, count = process_axis()
    if scan._rank is not None:
        if (scan._rank, scan._world) == (index, count):
            return scan
        raise ConfigError(
            f"multihost=True on a scan already sharded"
            f" ({scan._rank}/{scan._world}) differently from this host's"
            f" process axis ({index}/{count}); drop the explicit shard()"
            " or the multihost flag"
        )
    if count <= 1:
        return scan
    return scan.shard(index, count)
