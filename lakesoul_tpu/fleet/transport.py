"""Spool-segment transport seam: shm, object-store spill, Flight stream.

A produced range lives in the spool as one sealed Arrow IPC segment.  How
its bytes reach a trainer is the TRANSPORT — negotiated per exchange, one
of three rungs:

- ``shm``: the PR-11 fast path.  The client proves it can read the spool
  (manifest probe + session token) and maps the segment zero-copy; only
  the range's control message crosses the socket.
- ``spill``: the cross-host object-store rung.  The delivery head copies
  the sealed segment to ``<prefix>/<session>/range-<k>.arrow`` with a CRC
  sidecar (tmp → fsync → rename, the spool's own publication discipline)
  through the resilient fs; the client — any host with same-region store
  access — pulls the bytes back through the resilient fs and verifies the
  CRC before decoding.  Spill files are pruned WITH their session: a
  session manifest gone from the spool retires its spill directory.
- ``stream``: the Flight host-to-host floor — record batches on the
  exchange's data plane, no shared medium required.

Negotiation ladder (client side, per exchange): a forced transport
(``LAKESOUL_FLEET_TRANSPORT`` or the client kwarg) short-circuits; auto
probes shm, then spill, then falls back to stream.  Every rung's probe is
*prove you can read*: a token file the server wrote, read back over the
candidate medium.

Per-transport delivery is metered into the obs registry
(``lakesoul_fleet_transport_bytes_total{transport=}``,
``lakesoul_fleet_transport_seconds{transport=}``,
``lakesoul_fleet_transport_ranges_total{transport=}``) plus one
``lakesoul_fleet_transport_negotiated_total{transport=}`` tick per
exchange — the fleet aggregator and ``console fleet-status`` read these
back as the per-member transport column.
"""

from __future__ import annotations

import json
import logging
import os
import posixpath
import zlib

import pyarrow as pa

from lakesoul_tpu.errors import ConfigError, IOError_
from lakesoul_tpu.obs import registry
from lakesoul_tpu.runtime import atomicio

logger = logging.getLogger(__name__)

ENV_TRANSPORT = "LAKESOUL_FLEET_TRANSPORT"
ENV_SPILL = "LAKESOUL_FLEET_SPILL"

TRANSPORTS = ("shm", "spill", "stream")

_PROBE_PREFIX = "probe-"
_CRC_SUFFIX = ".crc"


def forced_transport(value: str | None = None) -> str | None:
    """The operator's transport override: the explicit ``value`` (client
    kwarg) wins, else ``LAKESOUL_FLEET_TRANSPORT``; ``auto``/unset means
    negotiate.  Unknown names fail loudly — a typo'd override silently
    falling back to auto would defeat the point of forcing one."""
    raw = value if value is not None else os.environ.get(ENV_TRANSPORT)
    if raw is None or raw == "" or raw == "auto":
        return None
    if raw not in TRANSPORTS:
        raise ConfigError(
            f"unknown fleet transport {raw!r}; expected one of"
            f" {('auto',) + TRANSPORTS}"
        )
    return raw


def spill_prefix() -> str | None:
    """The configured object-store spill prefix (server side)."""
    return os.environ.get(ENV_SPILL) or None


# ---------------------------------------------------------------- metering


def negotiated(transport: str) -> None:
    registry().counter(
        "lakesoul_fleet_transport_negotiated_total", transport=transport
    ).inc()


def meter_range(transport: str, nbytes: int, seconds: float) -> None:
    """One delivered range's cost on one transport (client side: the
    consumer is where cross-host bytes/latency are felt)."""
    reg = registry()
    reg.counter(
        "lakesoul_fleet_transport_ranges_total", transport=transport
    ).inc()
    reg.counter(
        "lakesoul_fleet_transport_bytes_total", transport=transport
    ).inc(max(0, int(nbytes)))
    reg.histogram(
        "lakesoul_fleet_transport_seconds", transport=transport
    ).observe(max(0.0, float(seconds)))


# ------------------------------------------------------------- spill (server)


def _fs_for(path: str, *, write: bool = False):
    from lakesoul_tpu.io.object_store import filesystem_for

    return filesystem_for(path, write=write)


def spill_session_dir(prefix: str, session_id: str) -> str:
    return posixpath.join(prefix, session_id)


def spill_segment_path(prefix: str, session_id: str, index: int) -> str:
    return posixpath.join(prefix, session_id, f"range-{index:05d}.arrow")


def spill_probe_path(prefix: str, session_id: str) -> str:
    return posixpath.join(prefix, f"{_PROBE_PREFIX}{session_id}.json")


def write_spill_probe(prefix: str, session_id: str) -> dict:
    """Publish the spill offer's probe file (idempotent): a token document
    any same-region reader can pull back.  Returns the offer dict the
    hello message carries."""
    path = spill_probe_path(prefix, session_id)
    fs, p = _fs_for(path, write=True)
    if not fs.exists(p):
        fs.makedirs(posixpath.dirname(p) or "/", exist_ok=True)
        atomicio.publish_bytes_fs(
            fs, p, json.dumps({"session": session_id}).encode()
        )
    return {"prefix": prefix, "probe": path, "token": session_id}


def spill_range(prefix: str, session_id: str, spool_session_dir: str, index: int) -> dict:
    """Persist one sealed spool segment to the spill prefix (idempotent —
    the CRC sidecar is the publication barrier, written LAST so a reader
    that sees it can trust the segment bytes fully landed).  Returns the
    range message's ``spill`` payload: ``{path, crc32, nbytes}``.

    Local filesystems get the spool's own tmp→fsync→rename discipline;
    object stores (whose PUT is already atomic) ride the resilient fs
    wrapper, so transient store failures retry underneath."""
    from lakesoul_tpu.scanplane import spool as spool_mod

    seg = spill_segment_path(prefix, session_id, index)
    crc_path = seg + _CRC_SUFFIX
    fs, crc_p = _fs_for(crc_path, write=True)
    if fs.exists(crc_p):
        with fs.open(crc_p, "rb") as f:
            return json.loads(f.read().decode())
    src = spool_mod.segment_path(spool_session_dir, index)
    with open(src, "rb") as f:
        payload = f.read()
    fs_seg, seg_p = _fs_for(seg, write=True)
    fs_seg.makedirs(posixpath.dirname(seg_p), exist_ok=True)
    atomicio.publish_bytes_fs(fs_seg, seg_p, payload)
    doc = {
        "path": seg,
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "nbytes": len(payload),
    }
    # the CRC doc is the barrier: published only after the segment bytes
    # are durable above
    atomicio.publish_bytes_fs(fs, crc_p, json.dumps(doc, sort_keys=True).encode())
    return doc


def prune_spill(prefix: str, live_sessions: "set[str]") -> int:
    """Retire spill directories (and probe files) whose session manifest
    is gone from the spool — the spill mirrors the spool's lifecycle, so
    the session pruner is its pruner too.  Best-effort: a concurrent
    reader mid-pull sees a vanished object as a transient and resumes."""
    try:
        fs, p = _fs_for(prefix)
        names = [posixpath.basename(n.rstrip("/")) for n in fs.ls(p, detail=False)]
    except (OSError, FileNotFoundError):
        return 0
    pruned = 0
    for name in names:
        if name.startswith(_PROBE_PREFIX) and name.endswith(".json"):
            sid = name[len(_PROBE_PREFIX):-len(".json")]
            if sid not in live_sessions:
                try:
                    fs.rm_file(posixpath.join(p, name))
                except (OSError, FileNotFoundError):
                    pass
            continue
        if name not in live_sessions:
            try:
                fs.rm(posixpath.join(p, name), recursive=True)
                pruned += 1
            except (OSError, FileNotFoundError):
                continue
    return pruned


# ------------------------------------------------------------- spill (client)


def spill_probe_matches(offer: "dict | None") -> bool:
    """Client-side spill probe: pull the offer's probe object through the
    resilient fs and match the session token — proves this process can
    read the spill prefix (same region / shared credentials) before the
    exchange commits to the spill rung."""
    if not offer:
        return False
    try:
        fs, p = _fs_for(offer["probe"])
        with fs.open(p, "rb") as f:
            doc = json.loads(f.read().decode())
        return doc.get("session") == offer.get("token")
    except (OSError, ValueError, KeyError):
        return False


def fetch_spilled(spill: dict) -> "tuple[int, list[pa.RecordBatch]]":
    """Pull one spilled segment, verify its CRC, decode its batches.
    Returns ``(nbytes, batches)``.  A CRC mismatch is a loud IO error —
    a torn or truncated object must never decode into silently-wrong
    training data."""
    fs, p = _fs_for(spill["path"])
    with fs.open(p, "rb") as f:
        payload = f.read()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(spill["crc32"]) or len(payload) != int(spill["nbytes"]):
        raise IOError_(
            f"spilled segment {spill['path']} failed verification"
            f" (crc {crc:#x} != {int(spill['crc32']):#x} or"
            f" {len(payload)} != {spill['nbytes']} bytes)"
        )
    with pa.ipc.open_file(pa.BufferReader(payload)) as reader:
        batches = [
            reader.get_batch(i) for i in range(reader.num_record_batches)
        ]
    return len(payload), batches
