"""Freshness layer: the ingest-to-train staleness contract, measured.

The stack already has every ingredient of an always-fresh lakehouse —
exactly-once CDC ingest (streaming/cdc.py), a leased auto-compaction
service (compaction/), a resilience policy engine (runtime/resilience.py)
and streaming follow plans (meta/client.poll_scan_plan) — but until this
subsystem nothing MEASURED how long a committed upsert takes to reach a
training loop, let alone guaranteed it.  LakeSoul's defining loop is
exactly CDC + auto-compaction feeding readers (PAPER.md §0), and the
reproducibility discipline of arxiv 2604.21275 says a throughput claim for
a training-data pipeline only counts when measured end-to-end under the
full concurrent workload.  This package closes that gap:

- :mod:`~lakesoul_tpu.freshness.slo` — :class:`SloMonitor` turns each
  delivered commit into a commit-to-visible latency observation
  (``lakesoul_freshness_seconds``) and evaluates it against a DECLARED
  target (``LAKESOUL_FRESHNESS_SLO_S``) with error-budget accounting
  (``lakesoul_slo_violations_total{slo=}``); :class:`ThroughputSlo` does
  the same for sustained rows/s.
- :mod:`~lakesoul_tpu.freshness.follower` — the bounded-staleness
  follower: ``scan.follow()``'s poll/decode loop hardened onto the PR-6
  :class:`~lakesoul_tpu.runtime.resilience.RetryPolicy` (transient
  store/meta faults retry on the seeded schedule instead of killing the
  stream; permanent failures raise typed), with an exactly-once resumable
  position (:class:`FollowerState`) and a batch-source seam adapter
  (:class:`FollowBatchSource`) so ``scan.to_jax_iter(follow=...)`` is a
  continuous training source.
- ``python -m lakesoul_tpu.freshness writer`` — the real CDC-writer
  process role of the three-role chaos harness
  (tests/test_freshness_chaos.py, ``benchmarks/micro.py freshness``):
  writer + leased compactor + follower trainer run as real processes, the
  compactor is SIGKILLed mid-run and flaky-store faults injected, and the
  run must hold BOTH the freshness SLO and the throughput SLO with the
  follower's delivered rows exactly matching the writer's oracle.
"""

from __future__ import annotations

from lakesoul_tpu.freshness.follower import (
    FollowBatchSource,
    FollowerState,
    FreshFollower,
)
from lakesoul_tpu.freshness.slo import SloMonitor, ThroughputSlo

__all__ = [
    "FollowBatchSource",
    "FollowerState",
    "FreshFollower",
    "SloMonitor",
    "ThroughputSlo",
]
