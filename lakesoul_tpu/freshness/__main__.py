"""``python -m lakesoul_tpu.freshness`` — freshness-harness process roles.

``writer`` is the real CDC-ingest process of the three-role chaos harness
(tests/test_freshness_chaos.py, ``benchmarks/micro.py freshness``): it
streams checkpointed upserts into a CDC table at a declared cadence and
prints an **oracle** JSON line the follower's delivery is judged against —
total rows, a sha256 over the sorted ``(seq, id, v)`` tuples (delivery
order is bucket-grouped, so the oracle is order-invariant), and the
per-checkpoint commit instants.  What is tested is what deploys: the chaos
suite runs THIS entry as the writer child, exactly like the compaction
suite runs ``python -m lakesoul_tpu.compaction``.

Every row carries a unique, strictly-increasing ``seq``, so "delivered
rows exactly match the oracle" is a sha comparison with no dedup
ambiguity; ``id`` cycles a bounded keyspace so successive checkpoints are
genuine UPSERTS (same PKs re-written) and compaction has real merge work.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time


def _row_value(seq: int) -> float:
    # deterministic value stream: oracle and delivery hash the same floats
    return float((seq * 2654435761) % 1_000_003) / 997.0


def oracle_sha(rows: "list[tuple[int, int, float]]") -> str:
    h = hashlib.sha256()
    for seq, id_, v in sorted(rows):
        h.update(f"{seq}:{id_}:{v:.6f};".encode())
    return h.hexdigest()


def run_writer(args) -> dict:
    import pyarrow as pa

    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.obs import fleet, registry
    from lakesoul_tpu.obs.tracing import span
    from lakesoul_tpu.streaming.cdc import CheckpointedWriter

    fleet.arm("freshness-writer")
    c_rows = registry().counter("lakesoul_writer_rows_total")
    catalog = LakeSoulCatalog(args.warehouse, db_path=args.db_path)
    schema = pa.schema([
        ("id", pa.int64()),
        ("seq", pa.int64()),
        ("v", pa.float64()),
    ])
    if args.create and not catalog.table_exists(args.table):
        catalog.create_table(
            args.table,
            schema,
            primary_keys=["id"],
            hash_bucket_num=args.hash_buckets,
            cdc=True,
        )
    table = catalog.table(args.table)
    cdc_col = table.info.cdc_column
    writer = CheckpointedWriter(table)

    rows: list[tuple[int, int, float]] = []
    commit_ts: list[int] = []
    seq = 0
    for ckpt in range(args.commits):
        ids, seqs, vals, kinds = [], [], [], []
        for _ in range(args.rows_per_commit):
            # ids cycle the keyspace but stay unique WITHIN a commit (the
            # follower reads per-commit units raw, so an in-commit dup
            # would be merge-collapsed and break the oracle)
            id_ = seq % args.keyspace
            v = _row_value(seq)
            ids.append(id_)
            seqs.append(seq)
            vals.append(v)
            kinds.append("insert" if seq < args.keyspace else "update")
            rows.append((seq, id_, v))
            seq += 1
        # the COMMIT leg of the end-to-end trace: a root span joins the
        # spawning harness's trace via LAKESOUL_TRACE_ID, so the fleet
        # spool can assemble commit → worker-decode → client-delivery
        with span("freshness.commit", ckpt=ckpt, rows=args.rows_per_commit):
            writer.write(pa.table(
                {"id": ids, "seq": seqs, "v": vals, cdc_col: kinds},
                schema=table.schema,
            ))
            writer.checkpoint(ckpt)
        c_rows.inc(len(ids))
        commit_ts.append(int(time.time() * 1000))
        if args.interval_s > 0 and ckpt + 1 < args.commits:
            time.sleep(args.interval_s)
    return {
        "role": "writer",
        "table": args.table,
        "rows": len(rows),
        "commits": args.commits,
        "sha256": oracle_sha(rows),
        "commit_timestamps_ms": commit_ts,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "lakesoul-freshness",
        description="freshness-harness process roles",
    )
    sub = p.add_subparsers(dest="role", required=True)
    w = sub.add_parser("writer", help="stream checkpointed CDC upserts")
    w.add_argument("--warehouse", required=True)
    w.add_argument("--db-path", default=None)
    w.add_argument("--table", default="fresh")
    w.add_argument("--commits", type=int, default=20)
    w.add_argument("--rows-per-commit", type=int, default=1000)
    w.add_argument("--interval-s", type=float, default=0.2)
    w.add_argument("--keyspace", type=int, default=4096)
    w.add_argument("--hash-buckets", type=int, default=2)
    w.add_argument("--create", action="store_true")
    w.add_argument("--oracle-out", default=None,
                   help="also write the oracle JSON to this path (atomic)")
    args = p.parse_args(argv)

    if args.rows_per_commit > args.keyspace:
        p.error("--rows-per-commit must not exceed --keyspace"
                " (in-commit duplicate PKs would merge-collapse)")
    oracle = run_writer(args)
    line = json.dumps(oracle, sort_keys=True)
    if args.oracle_out:
        # tmp→fsync→rename through the sanctioned seam — the bare
        # tmp+replace this used to do could land an empty oracle doc
        # after a host crash (rename without fsync)
        from lakesoul_tpu.runtime import atomicio

        atomicio.publish_atomic(args.oracle_out, line)
    print(line, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
